"""EdgeNode — the live-query edge gateway (ISSUE 8 tentpole).

The missing analogue of the reference's UI tier at scale: where a Blazor
circuit holds one ComputedState per component and SignalR pushes each
re-render, an :class:`EdgeNode` turns server-fenced computeds into live
queries for END-USER sessions — thousands to hundreds of thousands of
SSE/WebSocket subscribers per edge process — without the server ever
seeing more than ONE subscription per distinct key per edge:

- **single-upstream coalescing**: the first session to ask for a key
  creates one ``_KeySub`` — one :class:`~..client.FusionClient` compute
  call whose invalidation rides PR 2's coalesced ``$sys-c`` batch frames
  (the server's fan-out cost is per-EDGE, not per-user). Every later
  session for that key attaches to the same sub. The invariant the CI
  smoke asserts: upstream subscriptions == distinct keys, never
  sessions × keys.
- **hierarchical re-fan**: each upstream fence re-reads the key once and
  re-fans the new value to the sub's sessions through per-session bounded
  outboxes (edge/session.py) — latest-wins per key, slow-consumer
  eviction with resume tokens, heartbeats. The shape is Tascade's
  asynchronous reduction tree (PAPERS.md) run in reverse: a wave reaches
  N·M browsers through N edge subscriptions.
- **shard-map-aware affinity**: with a cluster
  :class:`~..cluster.router.ShardMapRouter` installed, each key's
  upstream subscription pins at the key's OWNER member (same rendezvous
  placement the servers use), and an applied ``reshard:<epoch>`` — via
  gossip (``$sys-m.map``), a carried ``ShardMovedError`` map, or the
  owner's own reshard fence — re-subscribes exactly the moved keys at
  their new owner WITHOUT touching downstream sessions: a browser never
  reconnects because the cluster rebalanced.
- **observable end to end**: ``fusion_edge_*`` metrics (sessions, subs,
  frames, coalesced frames, evictions, the fence→client-visible delivery
  histogram), flight-recorder ``edge_fenced`` events carrying the
  originating wave's cause id (``explain()`` spans server wave → edge →
  session), and ``snapshot()`` for ``FusionMonitor.report()["edge"]``.

Scale notes: sessions and frames are slotted/tuple-shaped (the
1M-subscriber simulation in perf/edge_path.py runs in one process);
sink-flavor sessions deliver synchronously with no per-session task, so
a million subscribers cost memory, not scheduler load.
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple, Union

from ..client.client_function import FusionClient
from ..core.context import capture
from ..diagnostics.flight_recorder import RECORDER, call_key
from ..diagnostics.metrics import global_metrics
from ..utils.async_utils import TaskSet
from .admission import (
    LANE_ANONYMOUS,
    LANE_RESUME,
    AdmissionDecision,
    AdmissionRejected,
)
from .session import EdgeSession, EncodedFrame, Frame, KeyedMailbox

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["EdgeNode", "KeySpec", "DRAIN_KEY"]

#: the pseudo-key of drain hint frames (ISSUE 12c): EdgeNode.drain() ships
#: one per live session — ``value`` carries the session's resume token,
#: ``cause`` the ``drain:<edge-name>`` family explain() understands. It is
#: not a subscribable key; sinks/transports route on it.
DRAIN_KEY = "$edge/drain"


def _is_shard_moved(e: BaseException) -> bool:
    """Function-local cluster import (client_function.py's rule): the edge
    loads without the cluster package; the check must never cycle."""
    try:
        from ..cluster.shard_map import ShardMovedError
    except ImportError:  # pragma: no cover — cluster ships with the package
        return False
    return isinstance(e, ShardMovedError)

#: a key is named by (method, *args) on the edge's upstream service —
#: e.g. ``("node", 17)`` subscribes ``dag.node(17)``
KeySpec = Union[Tuple[Any, ...], List[Any]]


class _KeySub:
    """One distinct key's upstream subscription + downstream fan list.

    Sessions are PARTITIONED into the node's fan shards (``shards[w]`` is
    the set of attached sessions whose ``session.shard == w``), so the
    hottest key's fan-out is drained by W parallel workers instead of one
    sequential loop (ISSUE 10b). ``sessions`` is the compat union view —
    iteration/len only; membership mutations go through the shard sets.
    """

    __slots__ = (
        "key_str",
        "method",
        "args",
        "version",
        "last_frame",
        "shards",
        "task",
        "peer_ref",
        "closed",
        "parked_refs",
        "pins",
        "repin_cause",
        "_wake",
        "node",
        "needs_reread",
        "pending_fence",
        "backoff",
        "upstream_version",
        "block_mode",
        "block_call_id",
        "block_seq",
        "block_pending",
        "block_size",
        "last_src",
    )

    def __init__(
        self, key_str: str, method: str, args: tuple, n_shards: int = 1,
        backoff: float = 0.05,
    ):
        self.key_str = key_str
        self.method = method
        self.args = args
        #: monotonic per-key version — the resume ordering (Last-Event-ID
        #: style): bumped once per fanned frame, never reused
        self.version = 0
        self.last_frame: Optional[Frame] = None
        self.shards: List[Set[EdgeSession]] = [set() for _ in range(n_shards)]
        self.task: Optional[asyncio.Task] = None
        self.peer_ref: Optional[str] = None
        self.closed = False
        #: parked (evicted/disconnected) sessions holding this key — the
        #: sub must outlive its live sessions while a resume could return
        self.parked_refs = 0
        #: sessionless holds (EdgeNode.acquire_keys — the worker pool's
        #: remote sessions): the sub must outlive local sessions while a
        #: delivery-plane worker still serves the key
        self.pins = 0
        #: set when a shard-map change moved this key's owner: the watch
        #: loop re-subscribes there and stamps the next frame's cause
        self.repin_cause: Optional[str] = None
        #: the watch loop's wake event: repins, value-block arrivals and
        #: fallback fences all signal it (one event, not one side-task per
        #: wake source per cycle)
        self._wake = asyncio.Event()
        #: the current upstream ClientComputed (None while block-fed: the
        #: value plane retires the local node once blocks own the key)
        self.node = None
        #: set by fallback fences / block evictions / reconnects: the next
        #: serve cycle must go upstream (batched re-read)
        self.needs_reread = False
        #: (cause, origin_ts) carried by a fallback fence, stamped onto
        #: the re-read's fanned frame
        self.pending_fence: Optional[Tuple[Optional[str], Optional[float]]] = None
        #: per-sub exponential error backoff (reset on a healthy read)
        self.backoff = backoff
        #: last upstream LTag observed (diagnostics/tests — oracle checks)
        self.upstream_version: Optional[str] = None
        #: True once the server armed a standing publish registration for
        #: this key: fences arrive as value-block pushes, zero per-key RPCs
        self.block_mode = False
        self.block_call_id: Optional[int] = None
        #: last applied block seq — the monotonic stale-entry gate
        self.block_seq = 0
        #: latest unserved block entry (seq, version, value, cause, t0) —
        #: latest-wins: a newer entry replaces an unserved older one
        self.block_pending: Optional[tuple] = None
        self.block_size = 0  # pending entry's payload bytes (budget share)
        #: how the latest fanned frame's value was served ("wave block" /
        #: "batched re-read" / "per-key re-read") — explain() names it
        self.last_src: Optional[str] = None

    @property
    def sessions(self) -> Set[EdgeSession]:
        """Union view over the shard partitions (tests/operators; the hot
        paths use the shard sets and :attr:`session_count` directly)."""
        if len(self.shards) == 1:
            return self.shards[0]
        out: Set[EdgeSession] = set()
        for bucket in self.shards:
            out |= bucket
        return out

    @property
    def session_count(self) -> int:
        return sum(len(bucket) for bucket in self.shards)

    def add_session(self, session: EdgeSession) -> None:
        self.shards[session.shard].add(session)

    def discard_session(self, session: EdgeSession) -> None:
        self.shards[session.shard].discard(session)

    @property
    def unreferenced(self) -> bool:
        return (
            self.session_count == 0 and self.parked_refs <= 0 and self.pins <= 0
        )

    def repin(self, cause: str) -> None:
        self.repin_cause = cause
        self._wake.set()


class _FanShard:
    """One fan worker: a latest-wins (per key) queue of encoded frames +
    the drain task that walks ITS partition of each sub's sessions. The
    watch loop posts once per shard instead of walking every session
    itself, so W shards drain the hottest key concurrently and a fence
    for another key never queues behind a 250k-session fan."""

    __slots__ = ("node", "index", "_pending", "_event", "task",
                 "busy_ms", "delivered", "drains", "coalesced")

    def __init__(self, node: "EdgeNode", index: int):
        self.node = node
        self.index = index
        #: key_str -> (sub, frame, encoded) — latest-wins: a newer version
        #: posted before the drain REPLACES the older one (those sessions
        #: could never have seen it; counted as coalesced)
        self._pending: Dict[str, tuple] = {}
        self._event = asyncio.Event()
        self.task: Optional[asyncio.Task] = None
        self.busy_ms = 0.0
        self.delivered = 0
        self.drains = 0
        self.coalesced = 0

    def post(self, sub: _KeySub, frame: Frame, encoded: EncodedFrame) -> None:
        if sub.key_str in self._pending:
            self.coalesced += 1
        self._pending[sub.key_str] = (sub, frame, encoded)
        self._event.set()
        if self.task is None or self.task.done():
            self.task = asyncio.get_event_loop().create_task(self._run())

    def snapshot(self) -> dict:
        return {
            "busy_ms": round(self.busy_ms, 3),
            "delivered": self.delivered,
            "drains": self.drains,
            "coalesced": self.coalesced,
            "pending": len(self._pending),
        }

    async def _run(self) -> None:
        try:
            while True:
                while not self._pending:
                    self._event.clear()
                    await self._event.wait()
                self._event.clear()
                batch = list(self._pending.values())
                self._pending.clear()
                t0 = time.perf_counter()
                for sub, frame, encoded in batch:
                    self.node._fan_shard_deliver(self, sub, frame, encoded)
                self.busy_ms += (time.perf_counter() - t0) * 1e3
                self.drains += 1
                # yield between drains: siblings (and the watch loops) get
                # the loop even while one shard stays hot
                await asyncio.sleep(0)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — a fan shard must never die silently
            log.exception(
                "edge %s: fan shard %d failed", self.node.name, self.index
            )


class _RereadBatcher:
    """The upstream value plane's LEVEL 1 (ISSUE 11): fence-burst re-reads
    coalesce into ONE ``$sys-c.recompute_batch`` RPC per owner peer. A
    ``$sys-c`` batch frame wakes every fenced key's watch loop in the same
    event-loop ticks; each loop submits here and awaits its own entry, and
    the batcher flushes the owner's bucket after ``reread_batch_window``
    (or at ``reread_batch_max`` keys) — the per-key capture still runs on
    the server, but the RPC/codec/loop-hop envelope is paid once per burst
    instead of once per key (the PR 10 ~2 ms/key storm tail)."""

    __slots__ = ("node", "_pending", "_timers", "_flights")

    def __init__(self, node: "EdgeNode"):
        self.node = node
        #: owner peer ref -> [(sub, future)] awaiting the next flush
        self._pending: Dict[str, list] = {}
        self._timers: Dict[str, Any] = {}
        #: in-flight flush tasks — a lifecycle owner, not a fire-and-forget
        #: spawn: a flush mid-RPC when the node closes must be cancelled or
        #: it races the teardown's future sweep (fusionlint FL003)
        self._flights = TaskSet(name=f"edge-reread-flush")

    def submit(self, owner: str, sub: _KeySub) -> "asyncio.Future":
        loop = asyncio.get_event_loop()
        future = loop.create_future()
        bucket = self._pending.setdefault(owner, [])
        bucket.append((sub, future))
        if len(bucket) >= self.node.reread_batch_max:
            self._fire(owner)
        elif owner not in self._timers:
            # pressure-widened (ISSUE 12b): under load the window grows so
            # each upstream frame amortizes more keys; it snaps back to
            # the configured base as soon as the pressure sources drop
            window = self.node.effective_reread_window()
            if window > 0:
                self._timers[owner] = loop.call_later(window, self._fire, owner)
            else:
                self._timers[owner] = loop.call_soon(self._fire, owner)
        return future

    def _fire(self, owner: str) -> None:
        timer = self._timers.pop(owner, None)
        if timer is not None:
            timer.cancel()
        batch = self._pending.pop(owner, None)
        if not batch:
            return
        if self._flights.closed:  # node closed between timer arm and fire
            for _sub, future in batch:
                if not future.done():
                    future.cancel()
            return
        self._flights.spawn(self._flush(owner, batch))

    async def _flush(self, owner: str, batch: list) -> None:
        node = self.node
        client = node._client_for(owner)
        node.reread_batches += 1
        node.upstream_rpcs += 1
        node.reread_batch_keys += len(batch)
        node._batch_size_hist.record(len(batch))
        requests = [
            (sub.method, sub.args, node.value_blocks) for sub, _f in batch
        ]
        try:
            results = await client.capture_batch(requests)
        except asyncio.CancelledError:
            for _sub, future in batch:
                if not future.done():
                    future.cancel()
            raise
        except Exception as e:  # noqa: BLE001 — whole-frame failure: every
            # entry falls back per-key in its own watch loop (counted there)
            for _sub, future in batch:
                if not future.done():
                    future.set_exception(e)
            return
        for (_sub, future), result in zip(batch, results):
            if future.done():
                continue
            if isinstance(result, BaseException):
                future.set_exception(result)
            else:
                future.set_result(result)

    def cancel_all(self) -> None:
        for timer in self._timers.values():
            timer.cancel()
        self._timers.clear()
        self._flights.cancel()
        pending, self._pending = self._pending, {}
        for bucket in pending.values():
            for _sub, future in bucket:
                if not future.done():
                    future.cancel()


class EdgeNode:
    """One edge gateway process: holds exactly one upstream subscription
    per distinct key and re-fans each fence to its downstream sessions.

    ``rpc_hub`` is the edge's OWN client hub (dialing the server tier);
    ``fusion_hub`` its own computed graph (ClientComputeds intern there).
    ``router`` (optional) is a cluster ``ShardMapRouter`` — when present
    it is installed as the hub's call router, upstream subscriptions pin
    at each key's owner, and epoch changes re-pin moved keys."""

    def __init__(
        self,
        service: str,
        rpc_hub,
        fusion_hub=None,
        router=None,
        default_peer: str = "default",
        name: str = "edge",
        resume_ttl: float = 60.0,
        max_pending: int = 4096,
        error_backoff: float = 0.05,
        error_backoff_max: float = 1.0,
        allowed_methods=None,
        max_keys_per_session: int = 1024,
        fan_workers: int = 1,
        reread_batch: bool = True,
        reread_batch_window: float = 0.002,
        reread_batch_max: int = 512,
        value_blocks: bool = True,
        block_budget_bytes: int = 64 << 20,
        admission=None,
        pressure_widen: float = 4.0,
        pressure_fan_depth: int = 1024,
    ):
        from ..core.hub import FusionHub

        self.service = service
        self.rpc_hub = rpc_hub
        self.fusion_hub = fusion_hub or FusionHub()
        self.router = router
        self.default_peer = default_peer
        self.name = name
        self.resume_ttl = resume_ttl
        self.max_pending = max_pending
        self.error_backoff = error_backoff
        #: exponential-backoff cap for the watch loops' upstream retry
        #: paths (errors AND shard-move transients): a flapping upstream
        #: key backs off per-sub instead of hot-spinning 512 loops
        self.error_backoff_max = max(error_backoff, error_backoff_max)
        #: ISSUE 11 level 1: coalesce fence-burst re-reads into ONE
        #: recompute_batch RPC per owner (False = the per-key A/B shape)
        self.reread_batch = reread_batch
        self.reread_batch_window = reread_batch_window
        self.reread_batch_max = max(1, int(reread_batch_max))
        #: ISSUE 11 level 2: ask the serving member for publish-on-wave
        #: value blocks — fences then arrive WITH the recomputed value and
        #: a block-warm burst costs zero per-key upstream RPCs
        self.value_blocks = value_blocks
        #: bound on UNSERVED pending block-entry bytes across keys; an
        #: entry over budget is dropped (counted) and its key falls back
        #: to the batched re-read — never silent, never unbounded
        self.block_budget_bytes = block_budget_bytes
        #: method allowlist for key specs. The edge transports forward
        #: client-supplied (method, args) into upstream compute calls, so
        #: a node behind a PUBLIC EdgeHttpServer/EdgeWebSocketServer
        #: should name exactly its live-query read methods here; None
        #: (the in-process/trusted default) allows any public method —
        #: EDGE.md documents the trust boundary.
        self.allowed_methods = (
            frozenset(allowed_methods) if allowed_methods is not None else None
        )
        #: distinct keys one session may subscribe: bounds the upstream
        #: subscription state a single connection can mint
        self.max_keys_per_session = max_keys_per_session
        #: the overload-safety plane (ISSUE 12): an AdmissionController
        #: consulted by attach()/resume() (unless the transport already
        #: admitted) and by both transports; None = no admission control
        #: (the in-process/benchmark default — existing behavior)
        self.admission = admission
        #: how far the upstream re-read batching window widens under
        #: pressure: effective = base * (1 + pressure_widen * pressure).
        #: Overload then degrades to bigger (cheaper per key) upstream
        #: batches — higher latency — before it degrades to evictions.
        self.pressure_widen = pressure_widen
        #: fan-shard queue depth (pending distinct keys across shards) at
        #: which the fan plane reports FULL pressure (1.0)
        self.pressure_fan_depth = max(1, int(pressure_fan_depth))
        if admission is not None:
            admission.add_pressure_source(
                f"{name}:fan_shards", self._fan_pressure
            )
        #: fan shards (ISSUE 10b): sessions partition round-robin over W
        #: parallel fan workers; each upstream fence posts ONE encoded
        #: frame per shard instead of walking every session in the watch
        #: loop
        self.fan_workers = max(1, int(fan_workers))
        self._fan_shards = [_FanShard(self, w) for w in range(self.fan_workers)]
        self._shard_rr = 0
        #: version-keyed serialize-once cache (ISSUE 10a): key_str -> the
        #: latest fanned frame's EncodedFrame; every downstream transport
        #: writes the same immutable bytes. Bounded by live distinct keys
        #: (entries drop with their sub's teardown — the parked-session
        #: sweep path included).
        self._encoded: Dict[str, EncodedFrame] = {}
        #: delivery-plane broadcast hooks (the multi-process worker pool):
        #: called once per fanned frame with (key_str, frame, encoded)
        self._broadcasts: List = []
        #: attached EdgeWorkerPool (set by EdgeWorkerPool.start) — owned
        #: by the caller unless attached, then close() stops it
        self.worker_pool = None
        if router is not None:
            # affinity + gossip: route through the cluster map, and re-pin
            # moved keys on every applied epoch (membership pushes /
            # ShardMovedError-carried maps both land in apply_map)
            rpc_hub.call_router = router
            router.on_map_change.append(self._on_map_change)
        self._subs: Dict[str, _KeySub] = {}
        self._clients: Dict[str, FusionClient] = {}
        #: the level-1 batcher (one per node; buckets per owner peer)
        self._batcher = _RereadBatcher(self)
        #: publish-mode routing: upstream call_id -> its sub (the block
        #: frames and fallback fences address subscriptions by call id)
        self._block_calls: Dict[int, _KeySub] = {}
        #: total UNSERVED pending block bytes (the block_budget_bytes gauge)
        self._block_pending_bytes = 0
        #: per-owner reconnect monitor tasks (block-fed keys have no
        #: registered outbound call to ride the reconnect re-send, so the
        #: node itself re-reads them when an upstream link returns)
        self._monitor_tasks: List[asyncio.Task] = []
        if value_blocks:
            # route inbound $sys-c value_block frames + fallback fences
            # for retired publish-mode calls to this node. One value-plane
            # client per rpc hub: a second node on the SAME hub keeps the
            # plain re-read ladder (counted path, never silently wrong).
            if getattr(rpc_hub, "value_plane_client", None) is None:
                rpc_hub.value_plane_client = self
            else:
                log.warning(
                    "edge %s: rpc hub %s already has a value-plane client; "
                    "value blocks disabled on this node", name, rpc_hub.name,
                )
                self.value_blocks = False
        self._sessions: Set[EdgeSession] = set()
        #: token → (key specs, delivered-version map, expiry deadline)
        self._parked: Dict[str, Tuple[tuple, Dict[str, int], float]] = {}
        #: next full expiry sweep (monotonic): the purge amortizes — a
        #: full scan per detach would make a reconnect storm O(parked²)
        self._next_purge = 0.0
        #: timer for the QUIESCENT sweep: with no attach/detach traffic
        #: nothing else calls the purge, and the last disconnectors'
        #: parked refs would pin their subs (and upstream subscriptions)
        #: past resume_ttl forever
        self._sweep_handle = None
        self._closed = False
        #: set by drain(): no new admissions, live sessions hinted +
        #: parked; the node keeps serving resumes of OTHER nodes' state
        #: only through import_parked on a fresh node
        self._draining = False
        # -- counters (collector-exported as fusion_edge_*) ---------------
        self.frames_fanned = 0
        self.coalesced_frames = 0  # latest-wins drops inside session mailboxes
        #: distinct (key, version) wire payloads actually serialized — the
        #: amortization numerator: deliveries / encodes is the serialize-
        #: once win (CI gates encodes ≈ fenced pairs ≪ deliveries)
        self.frames_encoded = 0
        #: encodes that fell back to repr for a non-JSON payload —
        #: detected ONCE at encode time, never silently per session
        self.frames_lossy = 0
        #: client-visible session deliveries (sink returns + transport-
        #: accepted pump batches); the amortization denominator
        self.deliveries = 0
        self.evictions = 0
        self.resumes = 0
        self.resumes_expired = 0  # resume() hit an expired-unswept token
        self.resubscribes = 0  # upstream re-pins after a shard move
        # -- overload safety (ISSUE 12) -----------------------------------
        self.drains = 0  # drain() invocations (fusion_edge_drains_total)
        self.sessions_drained = 0  # sessions hinted + parked by drains
        #: shed counts when NO AdmissionController is installed (the
        #: transports' unified rejection path still counts); with a
        #: controller, sheds ride its per-reason counters instead
        self._shed_local: Dict[str, int] = {}
        self.upstream_fences = 0
        self.upstream_errors = 0
        self.sessions_attached_total = 0
        # -- the upstream value plane (ISSUE 11) --------------------------
        #: upstream RPC round trips: batch frames + per-key captures — the
        #: CI gate's numerator (block-warm bursts must keep this flat)
        self.upstream_rpcs = 0
        self.per_key_rereads = 0  # per-key capture round trips
        self.reread_batches = 0  # recompute_batch frames sent
        self.reread_batch_keys = 0  # keys those frames carried
        self.reread_fallbacks = 0  # batch entries that fell back per-key
        self.upstream_backoffs = 0  # error/transient backoff sleeps
        self.block_hits = 0  # fans served from a wave value block (0 RPCs)
        self.block_entries = 0  # block entries received
        self.block_stale = 0  # entries dropped by the seq gate
        self.block_evictions = 0  # entries dropped by the byte budget
        self.block_fences = 0  # fallback fences for block-fed keys
        self.block_reshard_drops = 0  # pending entries dropped by repins
        self.block_orphans = 0  # entries for unknown/closed call ids
        self.reconnect_rereads = 0  # block-fed keys re-read on reconnect
        self._delivery_hist = global_metrics().histogram(
            "fusion_edge_delivery_ms",
            help="server fence (wave apply) -> edge session client-visible",
        )
        # attribution (ISSUE 19): per-key delivery offers into the
        # process hot-key board — /hotkeys and explain() name the keys
        # that dominate the edge fan
        from ..diagnostics.hotkeys import global_hotkeys

        self._hotkeys = global_hotkeys()
        self._batch_size_hist = global_metrics().histogram(
            "fusion_edge_reread_batch_size",
            help="keys per recompute_batch upstream frame",
        )
        # the effective window is non-additive: N nodes at 2 ms are at
        # 2 ms, not 2N ms (fusion_edge_draining stays summed — the count
        # of currently-draining nodes in the process IS the operator
        # signal during a rolling deploy)
        global_metrics().set_aggregation("fusion_edge_reread_window_ms", "max")
        global_metrics().register_collector(self, EdgeNode._collect_metrics)

    # ------------------------------------------------------------------ metrics
    def _collect_metrics(self) -> dict:
        out = {
            "fusion_edge_sessions": len(self._sessions),
            "fusion_edge_parked_sessions": len(self._parked),
            "fusion_edge_upstream_subscriptions": len(self._subs),
            "fusion_edge_frames_sent_total": self.frames_fanned,
            "fusion_edge_coalesced_frames_total": self.coalesced_frames,
            "fusion_edge_frames_encoded_total": self.frames_encoded,
            "fusion_edge_frames_lossy_total": self.frames_lossy,
            "fusion_edge_deliveries_total": self.deliveries,
            "fusion_edge_fan_shard_busy_ms": round(
                sum(s.busy_ms for s in self._fan_shards), 3
            ),
            "fusion_edge_fan_workers": self.fan_workers,
            "fusion_edge_evictions_total": self.evictions,
            "fusion_edge_resumes_total": self.resumes,
            "fusion_edge_resumes_expired_total": self.resumes_expired,
            "fusion_edge_resubscribes_total": self.resubscribes,
            "fusion_edge_drains_total": self.drains,
            "fusion_edge_sessions_drained_total": self.sessions_drained,
            "fusion_edge_draining": 1 if self._draining else 0,
            "fusion_edge_reread_window_ms": round(
                self.effective_reread_window() * 1e3, 3
            ),
            "fusion_edge_upstream_fences_total": self.upstream_fences,
            "fusion_edge_upstream_errors_total": self.upstream_errors,
            "fusion_edge_upstream_rpcs_total": self.upstream_rpcs,
            "fusion_edge_per_key_rereads_total": self.per_key_rereads,
            "fusion_edge_reread_batches_total": self.reread_batches,
            "fusion_edge_reread_batch_keys_total": self.reread_batch_keys,
            "fusion_edge_reread_fallbacks_total": self.reread_fallbacks,
            "fusion_edge_upstream_backoffs_total": self.upstream_backoffs,
            "fusion_edge_value_block_hits_total": self.block_hits,
            "fusion_edge_value_block_entries_total": self.block_entries,
            "fusion_edge_value_block_stale_total": self.block_stale,
            "fusion_edge_value_block_evictions_total": self.block_evictions,
            "fusion_edge_value_block_fences_total": self.block_fences,
            "fusion_edge_value_block_pending_bytes": self._block_pending_bytes,
        }
        if self.admission is None:
            for reason, count in self._shed_local.items():
                out[f'fusion_edge_shed_total{{reason="{reason}"}}'] = count
        pool = self.worker_pool
        if pool is not None:
            # last-pulled worker aggregates (the pool's stats() refreshes
            # them; collectors must stay sync)
            out["fusion_edge_workers"] = pool.n_workers
            out["fusion_edge_worker_deliveries_total"] = pool.deliveries_seen
        return out

    def snapshot(self) -> dict:
        """Operator view (FusionMonitor.report()["edge"], GET /shards-style
        merges): counts + upstream placement."""
        owners: Dict[str, int] = {}
        for sub in self._subs.values():
            if sub.peer_ref is not None:
                owners[sub.peer_ref] = owners.get(sub.peer_ref, 0) + 1
        pool = self.worker_pool
        out = {
            "name": self.name,
            "service": self.service,
            "sessions": len(self._sessions),
            "parked_sessions": len(self._parked),
            "upstream_subscriptions": len(self._subs),
            "upstream_by_owner": owners,
            "frames_fanned": self.frames_fanned,
            "coalesced_frames": self.coalesced_frames,
            "frames_encoded": self.frames_encoded,
            "frames_lossy": self.frames_lossy,
            "deliveries": self.deliveries,
            # deliveries per encode — the serialize-once amortization
            # ratio an operator reads first (ISSUE 10); worker-pool
            # deliveries ride the SAME encodes, so they count
            "encode_ratio": round(
                (
                    self.deliveries
                    + (pool.deliveries_seen if pool is not None else 0)
                )
                / self.frames_encoded,
                1,
            )
            if self.frames_encoded
            else None,
            "fan_workers": self.fan_workers,
            "fan_shards": [s.snapshot() for s in self._fan_shards],
            "evictions": self.evictions,
            "resumes": self.resumes,
            "resumes_expired": self.resumes_expired,
            "resubscribes": self.resubscribes,
            "upstream_fences": self.upstream_fences,
            "upstream_errors": self.upstream_errors,
            # overload safety (ISSUE 12): drain + admission state — an
            # operator mid-deploy reads draining/drained first
            "draining": self._draining,
            "drains": self.drains,
            "sessions_drained": self.sessions_drained,
            "admission": (
                self.admission.snapshot() if self.admission is not None
                else {"shed": dict(self._shed_local)}
            ),
            "reread_window_ms": round(self.effective_reread_window() * 1e3, 3),
            # the upstream value plane (ISSUE 11): how this node's fences
            # were actually served — an operator reads block_hit_ratio
            # first (1.0 = zero per-key upstream RPCs on warm bursts)
            "value_plane": {
                "reread_batch": self.reread_batch,
                "value_blocks": self.value_blocks,
                "upstream_rpcs": self.upstream_rpcs,
                "per_key_rereads": self.per_key_rereads,
                "reread_batches": self.reread_batches,
                "reread_batch_keys": self.reread_batch_keys,
                "reread_fallbacks": self.reread_fallbacks,
                "block_hits": self.block_hits,
                "block_entries": self.block_entries,
                "block_stale": self.block_stale,
                "block_evictions": self.block_evictions,
                "block_fences": self.block_fences,
                "block_fed_keys": sum(
                    1 for s in self._subs.values() if s.block_mode
                ),
                "block_hit_ratio": round(
                    self.block_hits / self.upstream_fences, 3
                )
                if self.upstream_fences
                else None,
                "upstream_backoffs": self.upstream_backoffs,
            },
            # the delivery histogram is ONE process-wide registry metric
            # (every in-process edge node records into it) — named so a
            # multi-node report is never misread as this node's own
            # distribution; per-node triage uses the counters above
            "delivery_ms_process": self._delivery_hist.snapshot(),
        }
        if pool is not None:
            out["worker_pool"] = pool.snapshot()
        return out

    # ------------------------------------------------------------------ keys
    def _normalize(self, spec: KeySpec) -> Tuple[str, tuple]:
        if isinstance(spec, str):
            raise TypeError(
                f"key spec must be (method, *args), got string {spec!r} — "
                f"the HTTP layer parses wire keys before attach()"
            )
        method, *args = tuple(spec)
        method = str(method)
        if method.startswith("_") or (
            self.allowed_methods is not None and method not in self.allowed_methods
        ):
            raise ValueError(f"method {method!r} is not subscribable on this edge")
        return method, tuple(args)

    def key_str(self, spec: KeySpec) -> str:
        method, args = self._normalize(spec)
        # the SAME call-shaped journal key the rpc client stamps its fence
        # events with — what lets explain() join server wave → edge hop
        return call_key(self.service, method, args)

    def _owner_of(self, method: str, args: tuple) -> str:
        router = self.router
        if router is not None:
            owner = router.shard_map.owner_of(
                router.key_for(self.service, method, args)
            )
            if owner is not None:
                return owner
        return self.default_peer

    def _client_for(self, peer_ref: str) -> FusionClient:
        client = self._clients.get(peer_ref)
        if client is None:
            client = FusionClient(
                self.service,
                self.rpc_hub,
                self.fusion_hub,
                peer_ref,
                cluster_routed=self.router is not None,
            )
            self._clients[peer_ref] = client
            if self.value_blocks:
                # block-fed keys hold no registered outbound call, so the
                # reconnect re-send machinery cannot heal them — this
                # monitor re-reads them when the owner's link returns
                # (one task per OWNER peer, never per key)
                try:
                    self._monitor_tasks.append(
                        asyncio.get_event_loop().create_task(
                            self._reconnect_monitor(peer_ref)
                        )
                    )
                except RuntimeError:  # no loop (sync construction in tests)
                    pass
        return client

    async def _reconnect_monitor(self, peer_ref: str) -> None:
        """Watch one owner peer's connection state: every reconnect marks
        that owner's BLOCK-FED subs for a (batched) re-read — a block or
        fallback fence lost with the dead link must not strand a key on a
        stale value forever. Terminated peers are the repin machinery's."""
        try:
            peer = self.rpc_hub.client_peer(peer_ref)
            ev = peer.connection_state.latest()
            while not self._closed:
                ev = await ev.when(lambda s: s.is_connected or s.is_terminated)
                if ev.value.is_terminated or self._closed:
                    return
                ev = await ev.when(lambda s: not s.is_connected)
                if ev.value.is_terminated or self._closed:
                    return
                # the link dropped; when it returns, re-read block-fed keys
                ev = await ev.when(lambda s: s.is_connected or s.is_terminated)
                if ev.value.is_terminated or self._closed:
                    return
                for sub in self._subs.values():
                    if sub.block_mode and sub.peer_ref == peer_ref:
                        sub.needs_reread = True
                        self.reconnect_rereads += 1
                        sub._wake.set()
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — a monitor must never die silently
            log.exception(
                "edge %s: reconnect monitor for %s failed", self.name, peer_ref
            )

    # ------------------------------------------------------------------ attach
    def attach(
        self,
        keys: Sequence[KeySpec],
        sink=None,
        mailbox: Optional[KeyedMailbox] = None,
        track_versions: bool = True,
        replay_current: bool = True,
        tenant: str = "",
        lane: Optional[str] = None,
        admitted=None,
    ) -> EdgeSession:
        """Register one downstream session over ``keys``. Exactly one of
        ``sink`` (synchronous delivery) / ``mailbox`` (pump-drained) —
        see :class:`~.session.EdgeSession`. Each key's upstream
        subscription is created on FIRST use and shared by every later
        session (the single-upstream invariant). With ``replay_current``
        the session immediately receives each key's latest known frame.

        With an :class:`~.admission.AdmissionController` installed the
        attach is ADMITTED OR SHED first (``tenant``/``lane`` feed the
        decision; a shed raises :class:`AdmissionRejected`, counted) —
        unless the transport already admitted and passes its decision as
        ``admitted``. Without a controller only a drain refuses."""
        if self._closed:
            raise RuntimeError(f"edge node {self.name} is closed")
        if self._draining:
            # checked FIRST (even for pre-admitted/transport calls, and
            # with no controller installed): a draining node answers a
            # counted shed the transports turn into a 503 — never an
            # uncounted exception that drops the socket
            raise self._drain_rejection(lane)
        if self.admission is not None and admitted is None:
            decision = self.admission.admit(
                tenant_id=tenant, lane=lane, keys=len(keys)
            )
            if not decision.admitted:
                self._note_shed_event(decision.reason, lane=decision.lane)
                raise AdmissionRejected(decision)
        if len(keys) > self.max_keys_per_session:
            raise ValueError(
                f"session asks for {len(keys)} keys; this edge caps at "
                f"{self.max_keys_per_session} per session"
            )
        specs = [self._normalize(k) for k in keys]
        key_strs = tuple(call_key(self.service, m, a) for m, a in specs)
        session = EdgeSession(
            key_strs, sink=sink, mailbox=mailbox, track_versions=track_versions
        )
        self._assign_shard(session)
        self._sessions.add(session)
        self.sessions_attached_total += 1
        for (method, args), ks in zip(specs, key_strs):
            sub = self._sub_for(ks, method, args)
            sub.add_session(session)
        if replay_current:
            # replay AFTER the session joined every sub: a replay that
            # evicts (broken sink, overflow) has detached the session from
            # all of them — adding it to later subs afterwards would leave
            # a ghost that pins the sub forever
            for ks in key_strs:
                if session.evicted:
                    break
                sub = self._subs.get(ks)
                if sub is not None and sub.last_frame is not None:
                    self._deliver_contained(session, sub.last_frame)
        return session

    def _deliver_contained(self, session: EdgeSession, frame: Frame) -> None:
        """Replay-path delivery with the same broken-consumer containment
        as the fan loop: a sink that raises (or a mailbox that overflows)
        evicts THAT session instead of bubbling into attach()/resume().

        The replayed frame ships WITHOUT the fence's origin timestamp: the
        fence happened while this session was absent, so recording (or
        letting the client record) now-minus-then as "delivery latency"
        would poison the fence→client-visible histogram with reconnect
        gaps. The cause id stays — causality is still true."""
        if frame[4] is not None:
            frame = (frame[0], frame[1], frame[2], frame[3], None, frame[5])
        try:
            ok = session.deliver(frame)
        except Exception:  # noqa: BLE001
            log.exception("edge %s: session sink failed on replay; evicting", self.name)
            ok = False
        if not ok and not session.evicted:
            self.evict(session, reason="replay delivery failed")

    def _assign_shard(self, session: EdgeSession) -> None:
        """Round-robin fan-shard placement by attach ordinal — sessions
        partition evenly over the W fan workers."""
        session.shard = self._shard_rr % self.fan_workers
        self._shard_rr += 1

    def _sub_for(self, key_str: str, method: str, args: tuple) -> _KeySub:
        sub = self._subs.get(key_str)
        if sub is None:
            sub = self._subs[key_str] = _KeySub(
                key_str, method, args, n_shards=self.fan_workers,
                backoff=self.error_backoff,
            )
            sub.task = asyncio.get_event_loop().create_task(self._watch(sub))
        return sub

    # ------------------------------------------------------------------ pinning
    def acquire_keys(self, keys: Sequence[KeySpec]) -> List[str]:
        """Hold upstream subscriptions WITHOUT a local session (the
        multi-process delivery plane: workers own the sockets, this node
        owns the upstream subs). Each acquired key's sub stays alive until
        the matching :meth:`release_keys`. Returns the key_strs (the
        broadcast identity). Validation (allowlist, underscore methods)
        applies exactly as for attach()."""
        if self._closed:
            raise RuntimeError(f"edge node {self.name} is closed")
        specs = [self._normalize(k) for k in keys]
        key_strs = [call_key(self.service, m, a) for m, a in specs]
        for (method, args), ks in zip(specs, key_strs):
            sub = self._sub_for(ks, method, args)
            sub.pins += 1
        return key_strs

    def release_keys(self, key_strs: Sequence[str]) -> None:
        """Release :meth:`acquire_keys` holds; a sub with no sessions, no
        parked refs and no pins tears down (and its encoded-cache entry
        drops with it)."""
        for ks in key_strs:
            sub = self._subs.get(ks)
            if sub is None:
                continue
            sub.pins -= 1
            if sub.unreferenced:
                self._teardown_sub(sub)

    # ------------------------------------------------------------------ encode
    def encode_frame(self, frame: Frame) -> EncodedFrame:
        """The serialize-once cache (ISSUE 10a): ONE wire encode per
        (key, version), shared by every downstream session's pump, the
        fan shards and the worker-pool broadcast. A cache hit is a dict
        probe; the cache holds the LATEST version per key (latest-wins
        delivery means older versions can only be asked for by a pump
        that raced a newer fence — encoded then, but never cached over a
        newer entry)."""
        key, version = frame[0], frame[1]
        has_t0 = frame[4] is not None
        cached = self._encoded.get(key)
        if cached is not None and cached.version == version:
            if cached.has_t0 == has_t0:
                return cached
            # the t0-stripped replay twin (attach/resume replays must not
            # ship the stale fence timestamp): encoded once, cached on
            # the canonical entry
            variant = cached.replay_variant
            if variant is not None and variant.has_t0 == has_t0:
                return variant
            variant = EncodedFrame(frame)
            self.frames_encoded += 1
            if variant.lossy:
                self.frames_lossy += 1
            cached.replay_variant = variant
            return variant
        encoded = EncodedFrame(frame)
        self.frames_encoded += 1
        if encoded.lossy:
            self.frames_lossy += 1
        if cached is None or version > cached.version:
            self._encoded[key] = encoded
        return encoded

    def detach(self, session: EdgeSession, park: bool = True) -> Optional[str]:
        """Remove a session. With ``park`` (the disconnect default) its
        delivered-version map is kept for ``resume_ttl`` seconds under the
        session's token, so a reconnect resumes exactly where it left off;
        returns the token (None when not parked). An upstream sub whose
        last live AND parked reference is gone tears down — the server
        subscription count follows the distinct-key demand."""
        if session not in self._sessions:
            return None
        self._sessions.discard(session)
        session.evicted = True
        token: Optional[str] = None
        if park:
            self._purge_parked()
            self._parked[session.token] = (
                session.keys,
                session.resume_state(),
                time.monotonic() + self.resume_ttl,
            )
            token = session.token
            self._arm_sweep()
        for ks in session.keys:
            sub = self._subs.get(ks)
            if sub is None:
                continue
            sub.discard_session(session)
            if park:
                sub.parked_refs += 1
            if sub.unreferenced:
                self._teardown_sub(sub)
        return token

    def resume(
        self, token: str, sink=None, mailbox=None, tenant: str = "",
        admitted=None,
    ) -> EdgeSession:
        """Re-attach a parked session by its resume token (query param or
        SSE ``Last-Event-ID`` — every event carries the token as its id).
        Replays each key whose CURRENT version is newer than the last one
        this session saw (latest-wins: intermediates are gone by design —
        the monotonic versions say *whether* it missed, the live frame
        says *what is true now*). Raises ``KeyError`` on unknown/expired
        tokens: the client falls back to a fresh attach. With an
        admission controller installed, resumes ride the RESERVED resume
        lane (admitted ahead of cold attaches; shed only by a full gate,
        the resume-rate bucket, or a drain)."""
        if (sink is None) == (mailbox is None):
            # validate BEFORE consuming the parked entry: a bad call must
            # not destroy the token's resume state or strand parked_refs
            raise ValueError("resume needs exactly one of sink= or mailbox=")
        if self._draining:
            # a hinted session must resume on the SUCCESSOR, not back
            # here: re-attaching to a draining node would strand it with
            # no hint when the caller closes the node (the drain hints
            # each session exactly once) — shed, counted
            raise self._drain_rejection(LANE_RESUME)
        if self.admission is not None and admitted is None:
            decision = self.admission.admit(tenant_id=tenant, lane=LANE_RESUME)
            if not decision.admitted:
                self._note_shed_event(decision.reason, lane=LANE_RESUME)
                raise AdmissionRejected(decision)
        self._purge_parked()
        entry = self._parked.pop(token, None)
        if entry is None:
            raise KeyError(f"unknown or expired resume token {token!r}")
        key_strs, versions, deadline = entry
        if deadline < time.monotonic():
            # expired but not yet swept (the sweep is amortized): release
            # the entry's parked refs IMMEDIATELY — a mass-reconnect storm
            # of expired tokens must not pin upstream subscriptions until
            # the next timer sweep (ISSUE 12 satellite; counted, and the
            # sweep re-arms since there is evidence of expiry)
            self.resumes_expired += 1
            self._drop_parked_refs(key_strs)
            self._arm_sweep()
            raise KeyError(f"unknown or expired resume token {token!r}")
        session = EdgeSession(key_strs, sink=sink, mailbox=mailbox, token=token)
        if session.versions is not None:
            session.versions.update(versions)
        self._assign_shard(session)
        self._sessions.add(session)
        self.resumes += 1
        for ks in key_strs:
            sub = self._subs.get(ks)
            if sub is None:  # torn down while parked (should not happen —
                continue  # parked_refs pins it — but never KeyError a resume)
            sub.parked_refs -= 1
            sub.add_session(session)
        for ks in key_strs:  # replay after joining every sub (see attach)
            if session.evicted:
                break
            sub = self._subs.get(ks)
            if (
                sub is not None
                and sub.last_frame is not None
                and sub.version > versions.get(ks, 0)
            ):
                self._deliver_contained(session, sub.last_frame)
        return session

    def _purge_parked(self) -> None:
        """Amortized expiry sweep: a full scan runs at most every
        resume_ttl/4 seconds — per-detach full scans would cost O(parked²)
        across a reconnect storm. An expired-but-unswept token is also
        rejected at :meth:`resume` time (deadline check there)."""
        now = time.monotonic()
        if now < self._next_purge:
            return
        self._next_purge = now + max(1.0, self.resume_ttl / 4)
        expired = [t for t, (_k, _v, dl) in self._parked.items() if dl < now]
        for t in expired:
            key_strs, _versions, _dl = self._parked.pop(t)
            self._drop_parked_refs(key_strs)

    def _arm_sweep(self) -> None:
        """Schedule the quiescent expiry sweep: the ONLY caller of the
        purge when no connection churn drives it. Re-arms while anything
        stays parked; idle + empty means no timer."""
        if self._sweep_handle is not None or self._closed:
            return
        try:
            loop = asyncio.get_event_loop()
        except RuntimeError:  # no loop (sync teardown): nothing to sweep for
            return
        self._sweep_handle = loop.call_later(
            max(1.0, self.resume_ttl / 2), self._sweep
        )

    def _sweep(self) -> None:
        self._sweep_handle = None
        self._next_purge = 0.0  # the timer IS the amortization: force
        self._purge_parked()
        if self._parked:
            self._arm_sweep()

    def _drop_parked_refs(self, key_strs) -> None:
        for ks in key_strs:
            sub = self._subs.get(ks)
            if sub is None:
                continue
            sub.parked_refs -= 1
            if sub.unreferenced:
                self._teardown_sub(sub)

    def evict(self, session: EdgeSession, reason: str = "stalled") -> Optional[str]:
        """Drop a slow consumer WITH a resume token (the pump's timeout
        path, the mailbox-overflow path and broken-sink containment all
        land here). Counted; the flight recorder notes it so an operator
        can see who got cut. The session's ``on_evicted`` transport hook
        runs LAST, so an eviction that did not originate in the transport
        pump still aborts the peer's connection. Idempotent: racing
        eviction paths (overflow in the fan loop vs the pump's send
        timeout) count — and fire the transport hook — exactly once."""
        if session not in self._sessions:
            return None  # already detached/evicted
        token = self.detach(session, park=True)
        self.evictions += 1
        if RECORDER.enabled:
            RECORDER.note(
                "edge_evicted",
                key=session.keys[0] if session.keys else None,
                detail=f"edge={self.name} reason={reason} token={token}",
            )
        if session.on_evicted is not None:
            try:
                session.on_evicted()
            except Exception:  # noqa: BLE001 — shutdown hooks must not bubble
                log.exception("edge %s: on_evicted hook failed", self.name)
        return token

    # ------------------------------------------------------------------ overload
    def _fan_pressure(self) -> float:
        """Fan-plane load signal, 0..1: pending distinct keys queued
        across the fan shards against the configured depth. Registered as
        an admission pressure source at construction."""
        pending = sum(len(s._pending) for s in self._fan_shards)
        return min(1.0, pending / self.pressure_fan_depth)

    def effective_reread_window(self) -> float:
        """The upstream re-read batching window, WIDENED under pressure
        (ISSUE 12b): overload buys bigger recompute_batch frames — more
        keys amortized per upstream RPC, higher latency — instead of
        deeper queues and evictions. Returns to the configured baseline
        the moment the pressure sources drop (pull-time, no hysteresis
        state to get stuck)."""
        base = self.reread_batch_window
        adm = self.admission
        if adm is None or base <= 0:
            return base
        p = adm.pressure()
        if p <= 0.0:
            return base
        return base * (1.0 + self.pressure_widen * p)

    def _note_shed_event(
        self, reason: str, lane: Optional[str] = None, key: Optional[str] = None,
    ) -> None:
        """Journal one shed (the counter already moved — admission's
        per-reason map, or count_shed's local fallback): explain()/an
        operator can see WHO was turned away and why."""
        if RECORDER.enabled:
            RECORDER.note(
                "edge_shed",
                key=key,
                detail=f"edge={self.name} reason={reason}"
                + (f" lane={lane}" if lane else ""),
            )

    def _drain_rejection(self, lane: Optional[str] = None) -> AdmissionRejected:
        """A COUNTED draining shed (attach/resume on a draining node —
        with or without a controller installed): the transports turn the
        carried decision into a 503 + Retry-After, in-process callers get
        the typed exception."""
        decision = AdmissionDecision(
            False,
            lane or LANE_ANONYMOUS,
            "",
            reason="draining",
            retry_after=(
                self.admission.retry_after
                if self.admission is not None
                else 1.0
            ),
        )
        self.count_shed("draining", lane=decision.lane)
        return AdmissionRejected(decision)

    def count_shed(
        self, reason: str, lane: Optional[str] = None, key: Optional[str] = None,
    ) -> None:
        """The transports' unified rejection counter (ISSUE 12
        satellite): admission rejections, key-allowlist 400s,
        replay-evicted 409s and dropped worker handoffs all land here —
        counted in ``fusion_edge_shed_total{reason=}`` (through the
        controller when installed, a node-local map otherwise) and
        journaled. Never silent."""
        if self.admission is not None:
            self.admission.note_shed(reason)
        else:
            self._shed_local[reason] = self._shed_local.get(reason, 0) + 1
        self._note_shed_event(reason, lane=lane, key=key)

    async def drain(self, retry_after: Optional[float] = None) -> dict:
        """Graceful drain for rolling deploys (ISSUE 12c): stop admitting
        (the controller sheds ``draining``), ship every live session ONE
        ``reconnect`` hint frame carrying its resume token (transports
        forward it as an SSE ``event: reconnect`` / WS hint and close the
        stream CLEANLY), park each session's delivered-version state, and
        return the parked-state export a successor node adopts via
        :meth:`import_parked`. Zero deliveries are lost across the
        handoff: resume replay covers the gap (latest-wins — the
        reconnected session sees the newest value of anything it missed).
        Idempotent; the caller closes the node (and hands its listener
        off) afterwards."""
        export = None
        if not self._draining:
            self._draining = True
            if self.admission is not None:
                self.admission.begin_drain()
            self.drains += 1
            cause = f"drain:{self.name}"
            pool = self.worker_pool
            if pool is not None:
                # the delivery plane first: worker-held SSE sessions get
                # their reconnect hints + clean closes too (a pooled
                # deployment's sessions are not the parent's _sessions)
                try:
                    self.sessions_drained += await pool.drain()
                except Exception:  # noqa: BLE001 — a wedged pool must
                    # not stop the parent-side drain
                    log.exception(
                        "edge %s: worker pool drain failed", self.name
                    )
            sessions = list(self._sessions)
            for session in sessions:
                hint: Frame = (
                    DRAIN_KEY,
                    0,
                    {"resume": session.token, "retry_after": retry_after},
                    cause,
                    None,
                    None,
                )
                try:
                    if session.on_drain is not None:
                        # transport hook: write the reconnect event and
                        # wind the connection down cleanly (not abort —
                        # the hint must reach the peer)
                        session.on_drain(hint)
                    else:
                        session.deliver(hint)
                except Exception:  # noqa: BLE001 — one broken consumer
                    # must not stop the drain for its siblings
                    log.exception(
                        "edge %s: drain hint failed for a session", self.name
                    )
                self.detach(session, park=True)
                self.sessions_drained += 1
            if RECORDER.enabled:
                RECORDER.note(
                    "edge_drained",
                    key=None,
                    count=len(sessions),
                    detail=(
                        f"edge={self.name} sessions={len(sessions)} parked "
                        f"for resume (rolling deploy)"
                    ),
                )
            # one loop tick: transports flush their reconnect hints before
            # the caller tears the listener/process down
            await asyncio.sleep(0)
        export = self.export_parked()
        return export

    def export_parked(self) -> dict:
        """The drain handoff payload: every parked token with its key
        SPECS (method + args — a successor node must be able to re-mint
        the subscriptions) and remaining TTL. Wire-serializable (JSON)."""
        now = time.monotonic()
        parked = []
        for token, (key_strs, versions, deadline) in self._parked.items():
            specs = []
            for ks in key_strs:
                sub = self._subs.get(ks)
                specs.append(
                    [sub.method, list(sub.args)] if sub is not None else None
                )
            parked.append(
                {
                    "token": token,
                    "specs": specs,
                    "ttl": max(0.0, deadline - now),
                }
            )
        return {"name": self.name, "service": self.service, "parked": parked}

    def import_parked(self, state: dict) -> int:
        """Adopt a drained sibling's parked sessions (the rolling-restart
        successor): each token's keys are re-pinned here (parked refs mint
        the upstream subscriptions, so the successor is already watching
        before anyone resumes) and the delivered-version maps reset to
        ZERO — this node's per-key versions restart, so a resume replays
        every key: correct (latest-wins hands the newest value), just not
        minimal, which is exactly what zero-loss across a restart needs.
        Keys that fail this node's allowlist are skipped (counted as a
        shed — a drain export is still client-named key state). Returns
        the number of tokens adopted."""
        now = time.monotonic()
        adopted = 0
        for entry in state.get("parked", []):
            token = entry.get("token")
            if not token or token in self._parked:
                continue
            # honor the EXPORTED remaining TTL (capped at this node's
            # resume_ttl): an entry that was a second from expiry on the
            # exporter must not get a fresh full lease here — on a mass
            # drain that would re-pin the whole parked population's
            # upstream subs for clients that will never return. Already-
            # expired entries are not adopted at all.
            ttl = min(float(entry.get("ttl", 0.0)), self.resume_ttl)
            if ttl <= 0.0:
                continue
            key_strs = []
            for spec in entry.get("specs", []):
                if not spec:
                    continue
                try:
                    method, args = self._normalize(
                        (spec[0], *tuple(spec[1]))
                    )
                except (ValueError, TypeError):
                    self.count_shed("import_rejected")
                    continue
                ks = call_key(self.service, method, args)
                sub = self._sub_for(ks, method, args)
                sub.parked_refs += 1
                key_strs.append(ks)
            self._parked[token] = (tuple(key_strs), {}, now + ttl)
            adopted += 1
        if adopted:
            self._arm_sweep()
        return adopted

    @property
    def draining(self) -> bool:
        return self._draining

    def _teardown_sub(self, sub: _KeySub) -> None:
        sub.closed = True
        sub._wake.set()  # unblock a parked watch loop so it exits
        self._subs.pop(sub.key_str, None)
        self._drop_block_state(sub)
        # the serialize-once cache entry dies with the sub (this is the
        # eviction path the parked-session sweep drives: last parked ref
        # expires -> sub tears down -> cached bytes are released)
        self._encoded.pop(sub.key_str, None)
        if sub.task is not None and not sub.task.done():
            sub.task.cancel()

    # ------------------------------------------------------------------ upstream
    async def _watch(self, sub: _KeySub) -> None:
        """The key's single upstream loop, now a three-rung value plane
        (ISSUE 11). Serve order per cycle:

        1. **repin** — the key's owner moved: drop local + block state,
           re-read at the new owner (batched);
        2. **value block** — a publish-on-wave entry is pending: fan it
           directly, ZERO upstream RPCs (the local node retires — the
           block stream is the subscription's truth now);
        3. **re-read** — the node fenced / a fallback fence or eviction
           marked the key: ONE ``recompute_batch`` entry shared with every
           other key this burst fenced (per-key capture only as the
           counted fallback rung).

        Latest-wins at every rung: fences landing mid-read collapse into
        the next cycle; a newer block entry replaces an unserved one.
        Errors and shard-move transients re-arm with per-sub exponential
        backoff (capped, counted) — a flapping upstream key cannot
        hot-spin the node's watch loops."""
        pending_cause: Optional[str] = None
        pending_t0: Optional[float] = None
        try:
            while not sub.closed and not self._closed:
                # ---- rung 0: owner moved (repin precedes everything —
                # a pending block entry from the OLD owner dies with it)
                if sub.repin_cause is not None:
                    repin_cause, sub.repin_cause = sub.repin_cause, None
                    node = sub.node
                    if (
                        node is not None
                        and not node.is_invalidated
                        and sub.block_pending is None
                        and not sub.needs_reread
                        and sub.peer_ref == self._owner_of(sub.method, sub.args)
                    ):
                        pass  # already pinned at the new owner: absorb
                    else:
                        pending_cause = repin_cause
                        self.resubscribes += 1
                        self._drop_block_state(sub, reshard=True)
                        self._retire_node(sub)
                        sub.needs_reread = True
                # ---- rung 1: publish-on-wave value block (zero RPCs)
                entry = sub.block_pending
                if entry is not None:
                    _seq, version, value, cause, t0 = entry
                    sub.block_pending = None
                    self._block_pending_bytes -= sub.block_size
                    sub.block_size = 0
                    sub.upstream_version = version
                    self.upstream_fences += 1
                    self.block_hits += 1
                    # the value plane owns this key now: the local node is
                    # a stale shadow — retire it (once) so nothing on this
                    # edge's graph can read the superseded value
                    self._retire_node(sub)
                    self._fan(sub, value, cause, t0, None, src="wave block")
                    pending_cause = pending_t0 = None
                # ---- rung 2: upstream (re)read — batched, per-key fallback
                elif (
                    sub.needs_reread
                    or sub.node is None
                    or sub.node.is_invalidated
                ):
                    if sub.pending_fence is not None:
                        fence_cause, fence_t0 = sub.pending_fence
                        sub.pending_fence = None
                        if fence_cause is not None:
                            pending_cause = fence_cause
                        if fence_t0 is not None:
                            pending_t0 = fence_t0
                    sub.needs_reread = False
                    node, err, src = await self._reread(sub)
                    if sub.closed or self._closed:
                        return
                    if node is None and err is None:
                        # routing transient: the reshard raced our map sync
                        # and the rejection's carried map was already
                        # applied (client_function note_moved) — retry at
                        # the new owner without fanning a phantom error
                        # frame to every session
                        sub.needs_reread = True
                        await self._backoff_sleep(sub)
                        continue
                    if err is not None:
                        self.upstream_errors += 1
                        self._fan(sub, None, pending_cause, pending_t0, err, src=src)
                        pending_cause = pending_t0 = None
                        sub.needs_reread = True
                        await self._backoff_sleep(sub)
                        continue
                    sub.backoff = self.error_backoff  # healthy: reset
                    out = node._output
                    self._fan(
                        sub, out.value if out is not None else None,
                        pending_cause, pending_t0, None, src=src,
                    )
                    pending_cause = pending_t0 = None
                # ---- wait for the next fence / block / repin
                while True:
                    sub._wake.clear()
                    node = sub.node
                    if (
                        sub.repin_cause is None
                        and sub.block_pending is None
                        and not sub.needs_reread
                        and (node is None or not node.is_invalidated)
                    ):
                        if node is None:
                            # block-fed: the wake event is the only signal
                            await sub._wake.wait()
                        elif self.router is None and not sub.block_mode:
                            # plain single-server sub: nothing ever calls
                            # repin()/wake — wait on the fence alone (the
                            # side-task pair is measurable per-cycle
                            # overhead across a 512-key fence storm)
                            await node.when_invalidated()
                        else:
                            inval = node.when_invalidated()
                            wake_task = asyncio.get_event_loop().create_task(
                                sub._wake.wait()
                            )
                            try:
                                await asyncio.wait(
                                    {inval, wake_task},
                                    return_when=asyncio.FIRST_COMPLETED,
                                )
                            finally:
                                wake_task.cancel()
                    if sub.closed or self._closed:
                        return
                    if (
                        sub.repin_cause is not None
                        or sub.block_pending is not None
                        or sub.needs_reread
                    ):
                        break  # the serve rungs above decide
                    node = sub.node
                    if node is not None and node.is_invalidated:
                        self.upstream_fences += 1
                        pending_cause = node.invalidation_cause
                        pending_t0 = node.invalidation_origin_ts
                        if pending_cause is not None and pending_cause.startswith(
                            "reshard:"
                        ):
                            # fenced BY the reshard itself (gossip not yet
                            # applied here): the re-capture re-routes via
                            # the map the ShardMovedError retry carries
                            self.resubscribes += 1
                        break
                    # stray wake (absorbed repin / cancelled waiter): rearm
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — a watch loop must never die silently
            log.exception("edge %s: watch loop for %s failed", self.name, sub.key_str)

    async def _reread(self, sub: _KeySub):
        """One upstream read: the batched rung when enabled (ONE
        ``recompute_batch`` frame per owner per burst window), the per-key
        capture as the counted fallback. Returns ``(node, err, src)`` —
        ``(None, None, _)`` is the shard-moved transient (caller re-arms
        with backoff). A healthy result also (re)arms publish mode from
        the server's echo."""
        owner = self._owner_of(sub.method, sub.args)
        src = "batched re-read"
        node = None
        if self.reread_batch:
            try:
                node = await self._batcher.submit(owner, sub)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — entry-level failure
                if _is_shard_moved(e):
                    return None, None, src
                self.reread_fallbacks += 1
                node = None
        if node is None:
            src = "per-key re-read"
            client = self._client_for(owner)
            self.per_key_rereads += 1
            self.upstream_rpcs += 1
            try:
                node = await capture(
                    lambda: getattr(client, sub.method)(*sub.args)
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — routing/link failures
                if _is_shard_moved(e):
                    return None, None, src
                return None, f"{type(e).__name__}: {e}", src
        if sub.node is not None and sub.node is not node:
            # a still-live node superseded by this re-read (reconnect
            # re-read, block budget eviction, undecodable entry): retire
            # it so its outbound call never leaks in peer.outbound_calls
            self._retire_node(sub)
        sub.peer_ref = owner
        sub.node = node
        sub.upstream_version = node.version.format()
        self._arm_block_mode(sub, node)
        out = node._output
        if out is not None and out.has_error:
            return None, f"{type(out.error).__name__}: {out.error}", src
        return node, None, src

    async def _backoff_sleep(self, sub: _KeySub) -> None:
        """Per-sub exponential backoff with a cap and a counter: the
        re-read error/transient paths re-arm through here, so a flapping
        upstream key costs one bounded retry cadence, never a hot spin
        across every watch loop (ISSUE 11 satellite)."""
        delay = sub.backoff
        sub.backoff = min(self.error_backoff_max, delay * 2)
        self.upstream_backoffs += 1
        await asyncio.sleep(delay)

    def _retire_node(self, sub: _KeySub) -> None:
        """Retire the sub's local ClientComputed (block mode took over, or
        a repin dropped the old owner's subscription): invalidating it
        unregisters the outbound call and keeps this edge's own computed
        graph honest — the value plane, not the node, carries the truth."""
        node, sub.node = sub.node, None
        if node is not None and not node.is_invalidated:
            node.invalidate(immediately=True)

    def _arm_block_mode(self, sub: _KeySub, node) -> None:
        """Adopt the server's publish echo for this sub's NEW upstream
        call: block frames and fallback fences address subscriptions by
        call id, so the routing entry follows the live call exactly."""
        old_cid = sub.block_call_id
        if old_cid is not None and self._block_calls.get(old_cid) is sub:
            self._block_calls.pop(old_cid, None)
        sub.block_call_id = None
        call = getattr(node, "call", None)
        armed = (
            self.value_blocks
            and call is not None
            and getattr(call, "publish_armed", False)
        )
        sub.block_mode = bool(armed)
        if armed:
            sub.block_call_id = call.call_id
            self._block_calls[call.call_id] = sub
            # the seq gate's scope is ONE call's block stream (frames are
            # routed by call id; a late frame for the old call is an
            # orphan): a new owner's publisher counts from its own epoch,
            # so carrying the old high-water mark would drop every fresh
            # entry as stale — silently-stale forever, since the server
            # diverted the plain fence into the block
            sub.block_seq = 0

    def _drop_block_state(self, sub: _KeySub, reshard: bool = False) -> None:
        """Clear a sub's value-plane state (teardown / repin): the pending
        entry — minted under the OLD owner — is invalidated, and the call
        routing entry dies so a late block for it counts as an orphan."""
        if sub.block_pending is not None:
            sub.block_pending = None
            self._block_pending_bytes -= sub.block_size
            sub.block_size = 0
            if reshard:
                self.block_reshard_drops += 1
        cid = sub.block_call_id
        if cid is not None and self._block_calls.get(cid) is sub:
            self._block_calls.pop(cid, None)
        sub.block_call_id = None
        sub.block_mode = False

    # ------------------------------------------------------------------ value plane
    def on_value_block(self, peer, message) -> None:
        """Inbound ``$sys-c.value_block`` frame (the publish-on-wave push,
        ISSUE 11 level 2): columnar ``(call_id, version, seq, cause, t0,
        offset)`` over one shared payload blob. Each entry is gated by the
        per-sub monotonic seq (a stale/duplicate entry is dropped,
        counted), budgeted (an entry over ``block_budget_bytes`` falls
        back to the batched re-read, counted), decoded ONCE, and parked
        latest-wins for the key's watch loop."""
        from ..diagnostics.clocksync import global_clock_sync
        from ..utils.serialization import loads as wire_loads

        try:
            cids, vers, seqs, causes, t0s, offsets, payload = wire_loads(
                message.argument_data
            )
        except Exception:  # noqa: BLE001 — a malformed frame must not kill
            # the receive pump; the keys heal through their fence fallbacks
            log.exception("edge %s: bad value_block frame", self.name)
            return
        sync = global_clock_sync()
        peer_ref = getattr(peer, "ref", None)
        for i, cid in enumerate(cids):
            sub = self._block_calls.get(cid)
            if sub is None or sub.closed or not sub.block_mode:
                self.block_orphans += 1
                continue
            seq = int(seqs[i])
            if seq <= sub.block_seq:
                self.block_stale += 1
                continue
            raw = payload[offsets[i]: offsets[i + 1]]
            size = len(raw)
            if (
                self._block_pending_bytes - sub.block_size + size
                > self.block_budget_bytes
            ):
                # over budget: drop the entry AND any unserved older one
                # (latest-wins — fanning the superseded value before the
                # corrective re-read would hand every session stale
                # data), fall back to the batched re-read — counted, and
                # the fence is never lost
                self.block_evictions += 1
                if sub.block_pending is not None:
                    sub.block_pending = None
                    self._block_pending_bytes -= sub.block_size
                    sub.block_size = 0
                sub.block_seq = seq
                sub.needs_reread = True
                sub.pending_fence = (
                    causes[i],
                    sync.to_local(peer_ref, t0s[i]) if t0s[i] is not None else None,
                )
                sub._wake.set()
                continue
            try:
                value = wire_loads(raw)
            except Exception:  # noqa: BLE001 — undecodable entry: re-read
                log.exception(
                    "edge %s: undecodable value_block entry for %s",
                    self.name, sub.key_str,
                )
                sub.block_seq = seq
                sub.needs_reread = True
                sub._wake.set()
                continue
            t0 = sync.to_local(peer_ref, t0s[i]) if t0s[i] is not None else None
            self.block_entries += 1
            self._block_pending_bytes += size - sub.block_size
            sub.block_size = size
            sub.block_seq = seq
            # latest-wins: an unserved older entry is superseded (those
            # sessions could never have seen it)
            if sub.block_pending is not None:
                self.coalesced_frames += 1
            sub.block_pending = (seq, vers[i], value, causes[i], t0)
            sub._wake.set()

    def on_block_fence(
        self, peer, call_id: int, cause: Optional[str], origin_ts: Optional[float],
    ) -> None:
        """A plain invalidation addressed to a RETIRED publish-mode call
        (the publisher's fallback ladder: recompute error, reshard,
        overflow, dead-link block). The key leaves block mode and
        re-reads — batched — carrying the fence's cause and timestamp."""
        sub = self._block_calls.get(call_id)
        if sub is None or sub.closed:
            return
        from ..diagnostics.clocksync import global_clock_sync

        self.block_fences += 1
        self.upstream_fences += 1
        t0 = (
            global_clock_sync().to_local(getattr(peer, "ref", None), origin_ts)
            if origin_ts is not None
            else None
        )
        sub.pending_fence = (cause, t0)
        sub.block_mode = False  # the server dropped the standing sub;
        # the re-read's publish echo re-arms it
        sub.needs_reread = True
        if cause is not None and cause.startswith("reshard:"):
            self.resubscribes += 1
        sub._wake.set()

    def _fan(
        self,
        sub: _KeySub,
        value: Any,
        cause: Optional[str],
        origin_ts: Optional[float],
        err: Optional[str],
        src: Optional[str] = None,
    ) -> None:
        """Fan one upstream frame: serialize the wire payload ONCE (the
        version-keyed encode cache), hand the shared bytes to the
        delivery-plane broadcasts (worker pool), and post one entry per
        fan shard — the shard workers walk their session partitions
        concurrently instead of this watch loop walking every session
        sequentially (ISSUE 10a+b). ``src`` names the value-plane rung
        that produced the value (recorder detail → explain())."""
        sub.last_src = src
        sub.version += 1
        frame: Frame = (sub.key_str, sub.version, value, cause, origin_ts, err)
        sub.last_frame = frame
        # encode-once, eagerly: one dumps per fanned (key, version) makes
        # the amortization ratio exact and the shared bytes ready before
        # any pump or worker asks
        encoded = self.encode_frame(frame)
        sessions = sum(len(bucket) for bucket in sub.shards)
        if sessions:
            # one offer per fanned frame, weighted by its session count —
            # the sketch sees "this key reached N downstreams" without a
            # per-session hop inside the delivery loops
            self._hotkeys.offer("edge_deliveries", sub.key_str, sessions)
        if self._broadcasts:
            for hook in self._broadcasts:
                try:
                    hook(sub.key_str, frame, encoded)
                except Exception:  # noqa: BLE001 — a broken delivery plane
                    # must not kill the key's watch loop
                    log.exception(
                        "edge %s: broadcast hook failed for %s",
                        self.name, sub.key_str,
                    )
        for bucket, shard in zip(sub.shards, self._fan_shards):
            if bucket:
                shard.post(sub, frame, encoded)

    def _fan_shard_deliver(
        self, shard: _FanShard, sub: _KeySub, frame: Frame,
        encoded: EncodedFrame,
    ) -> None:
        """One fan shard's delivery walk over ITS partition of the sub's
        sessions. Sessions whose bounded mailbox overflowed (or whose
        sink raised) are evicted (with resume tokens) AFTER the loop — a
        slow consumer never stalls its siblings, it just stops being a
        consumer."""
        bucket = sub.shards[shard.index]
        if not bucket:
            return
        cause, origin_ts = frame[3], frame[4]
        err = frame[5]
        dead: Optional[List[Tuple[EdgeSession, str]]] = None
        n = 0
        sinks = 0
        for session in bucket:
            mailbox = session.mailbox
            was_coalesced = mailbox.coalesced if mailbox is not None else 0
            try:
                ok = session.deliver(frame)
            except Exception:  # noqa: BLE001 — ONE broken consumer sink
                # must never kill the fan for its siblings: contain it as
                # an eviction (parked; a fixed consumer can resume from
                # its token)
                log.exception(
                    "edge %s: session sink failed for %s; evicting",
                    self.name, sub.key_str,
                )
                ok = False
                if dead is None:
                    dead = []
                dead.append((session, "sink raised"))
            else:
                if not ok:
                    if dead is None:
                        dead = []
                    dead.append((session, "mailbox overflow"))
            if ok and mailbox is None:
                sinks += 1  # counted in THIS loop — the fan over the
                # hottest zipf key must not pay a second O(sessions) pass
            if mailbox is not None:
                self.coalesced_frames += mailbox.coalesced - was_coalesced
            n += 1
        if dead:
            # evict BEFORE the counters/histogram below: a failed delivery
            # must not ride the fan total, the recorder count, or the
            # delivery distribution as if a client saw it
            for session, reason in dead:
                self.evict(session, reason=reason)
            n -= len(dead)
        self.frames_fanned += n
        self.deliveries += sinks  # sink sessions are client-visible NOW;
        # mailbox sessions count at record_delivery (transport-accepted)
        shard.delivered += n
        if origin_ts is not None:
            # sink-flavor sessions became client-visible in this drain —
            # one timestamp after the loop bounds them all, INCLUDING the
            # shard-queue wait (fence → visible, honestly). Mailbox
            # sessions record at pump-send time instead (the pump calls
            # record_delivery per drained frame).
            delta_ms = (time.perf_counter() - origin_ts) * 1e3
            if 0.0 <= delta_ms < 3.6e6 and sinks:  # range guard as $sys-c e2e
                self._delivery_hist.record_many(delta_ms, sinks, cause=cause)
        if (cause is not None or err is not None) and RECORDER.enabled and n > 0:
            # the edge hop of the causal chain: explain() joins this to
            # the client-side "fenced" event (same call-shaped key, same
            # cause) and SUMS per-shard counts into "edge re-fanned to N
            # session(s)"; causeless initial-value fans stay un-journaled
            # (they are attach mechanics, not invalidation causality),
            # error fans are journaled so an operator sees who saw the
            # failure
            RECORDER.note(
                "edge_fenced",
                key=sub.key_str,
                cause=cause,
                count=n,
                detail=(
                    f"edge={self.name} v{frame[1]} shard={shard.index} "
                    f"owner={sub.peer_ref}"
                    + (
                        f" value served from {sub.last_src}"
                        if sub.last_src is not None
                        else ""
                    )
                ),
            )

    def record_delivery(self, frame: Frame) -> None:
        """Pump callback: a mailbox frame reached its peer — count the
        client-visible delivery and record the fence→client-visible
        sample (the transport half of the histogram sink-flavor sessions
        record inline)."""
        self.deliveries += 1
        origin_ts = frame[4]
        if origin_ts is None:
            return
        delta_ms = (time.perf_counter() - origin_ts) * 1e3
        if 0.0 <= delta_ms < 3.6e6:
            self._delivery_hist.record(delta_ms, cause=frame[3])

    # ------------------------------------------------------------------ plane
    def attach_broadcast(self, hook) -> None:
        """Register a delivery-plane broadcast: ``hook(key_str, frame,
        encoded)`` runs once per fanned frame with the SHARED encoded
        bytes (the worker pool's feed)."""
        self._broadcasts.append(hook)

    def detach_broadcast(self, hook) -> None:
        try:
            self._broadcasts.remove(hook)
        except ValueError:
            pass

    # ------------------------------------------------------------------ reshard
    def _on_map_change(self, old, new) -> None:
        """Router callback on every applied epoch: re-pin exactly the subs
        whose key's owner moved. Downstream sessions notice nothing — the
        next frame just says ``cause=reshard:<epoch>``."""
        from ..cluster.shard_map import ShardMap

        moved = set(ShardMap.diff(old, new))
        if not moved:
            return
        cause = f"reshard:{new.epoch}"
        for sub in self._subs.values():
            shard = new.shard_of(
                self.router.key_for(self.service, sub.method, sub.args)
            )
            if shard in moved:
                sub.repin(cause)

    def apply_map(self, new_map) -> bool:
        """Adopt a shard map directly (tests / static deployments without
        a gossip feed)."""
        if self.router is None:
            raise RuntimeError("edge node has no shard-map router")
        return self.router.apply_map(new_map)

    # ------------------------------------------------------------------ lifecycle
    async def close(self) -> None:
        """Stop every watch loop and drop session state (the rpc/fusion
        hubs are the caller's to stop — they may be shared)."""
        self._closed = True
        pool, self.worker_pool = self.worker_pool, None
        if pool is not None:
            try:
                await pool.stop()
            except Exception:  # noqa: BLE001 — teardown must not bubble
                log.exception("edge %s: worker pool stop failed", self.name)
        if self.rpc_hub is not None and getattr(
            self.rpc_hub, "value_plane_client", None
        ) is self:
            self.rpc_hub.value_plane_client = None
        self._batcher.cancel_all()
        for task in self._monitor_tasks:
            if not task.done():
                task.cancel()
        self._monitor_tasks.clear()
        self._block_calls.clear()
        subs = list(self._subs.values())
        self._subs.clear()
        self._encoded.clear()
        for sub in subs:
            sub.closed = True
            sub._wake.set()
            if sub.task is not None and not sub.task.done():
                sub.task.cancel()
        for sub in subs:
            if sub.task is not None:
                try:
                    await sub.task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
        for shard in self._fan_shards:
            if shard.task is not None and not shard.task.done():
                shard.task.cancel()
        for shard in self._fan_shards:
            if shard.task is not None:
                try:
                    await shard.task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
        self._sessions.clear()
        self._parked.clear()
        if self._sweep_handle is not None:
            self._sweep_handle.cancel()
            self._sweep_handle = None
        if self.router is not None:
            try:
                self.router.on_map_change.remove(self._on_map_change)
            except ValueError:
                pass
        if self.admission is not None:
            # a SHARED controller must stop reading this node's fan
            # shards: close() leaves their _pending populated, so a stale
            # bound-method source would report phantom pressure (and pin
            # the node graph) forever
            self.admission.clear_pressure(f"{self.name}:fan_shards")
        global_metrics().unregister_collector(self)
