"""Edge tier (ISSUE 8) — million-subscriber live-query fan-out.

An :class:`EdgeNode` holds EXACTLY ONE upstream ``$sys-c`` subscription
per distinct key (riding the coalesced batch frames) and re-fans each
fence to thousands of downstream SSE/WebSocket sessions with per-session
bounded outboxes, latest-wins coalescing, slow-consumer eviction with
resume tokens, and shard-map-aware upstream affinity. The overload plane
(ISSUE 12) sits in front of it: an :class:`AdmissionController` with
per-tenant rate limits, priority lanes and pressure-fed shedding, plus
graceful :meth:`EdgeNode.drain` for rolling deploys. EDGE.md is the
runbook.
"""
from .admission import (
    AdmissionController,
    AdmissionDecision,
    AdmissionRejected,
    rejection_bytes,
)
from .gateway import DRAIN_KEY, EdgeNode
from .server import EdgeHttpServer, EdgeWebSocketServer
from .session import (
    EdgeSession,
    EncodedFrame,
    KeyedMailbox,
    LatestWinsMailbox,
    frame_to_dict,
    pump_payloads,
)
from .worker_pool import EdgeWorkerPool

__all__ = [
    "AdmissionController",
    "AdmissionDecision",
    "AdmissionRejected",
    "DRAIN_KEY",
    "EdgeNode",
    "EdgeHttpServer",
    "EdgeWebSocketServer",
    "EdgeSession",
    "EdgeWorkerPool",
    "EncodedFrame",
    "KeyedMailbox",
    "LatestWinsMailbox",
    "frame_to_dict",
    "pump_payloads",
    "rejection_bytes",
]
