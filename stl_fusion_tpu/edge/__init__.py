"""Edge tier (ISSUE 8) — million-subscriber live-query fan-out.

An :class:`EdgeNode` holds EXACTLY ONE upstream ``$sys-c`` subscription
per distinct key (riding the coalesced batch frames) and re-fans each
fence to thousands of downstream SSE/WebSocket sessions with per-session
bounded outboxes, latest-wins coalescing, slow-consumer eviction with
resume tokens, and shard-map-aware upstream affinity. EDGE.md is the
runbook.
"""
from .gateway import EdgeNode
from .server import EdgeHttpServer, EdgeWebSocketServer
from .session import (
    EdgeSession,
    EncodedFrame,
    KeyedMailbox,
    LatestWinsMailbox,
    frame_to_dict,
    pump_payloads,
)
from .worker_pool import EdgeWorkerPool

__all__ = [
    "EdgeNode",
    "EdgeHttpServer",
    "EdgeWebSocketServer",
    "EdgeSession",
    "EdgeWorkerPool",
    "EncodedFrame",
    "KeyedMailbox",
    "LatestWinsMailbox",
    "frame_to_dict",
    "pump_payloads",
]
