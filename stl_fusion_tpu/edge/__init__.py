"""Edge tier (ISSUE 8) — million-subscriber live-query fan-out.

An :class:`EdgeNode` holds EXACTLY ONE upstream ``$sys-c`` subscription
per distinct key (riding the coalesced batch frames) and re-fans each
fence to thousands of downstream SSE/WebSocket sessions with per-session
bounded outboxes, latest-wins coalescing, slow-consumer eviction with
resume tokens, and shard-map-aware upstream affinity. EDGE.md is the
runbook.
"""
from .gateway import EdgeNode
from .server import EdgeHttpServer, EdgeWebSocketServer
from .session import (
    EdgeSession,
    KeyedMailbox,
    LatestWinsMailbox,
    frame_to_dict,
    pump_payloads,
)

__all__ = [
    "EdgeNode",
    "EdgeHttpServer",
    "EdgeWebSocketServer",
    "EdgeSession",
    "KeyedMailbox",
    "LatestWinsMailbox",
    "frame_to_dict",
    "pump_payloads",
]
