"""Edge transports: SSE over stdlib asyncio + optional WebSocket.

The browser-facing surface of the edge tier (ISSUE 8): an
:class:`EdgeHttpServer` serves ``text/event-stream`` live queries against
an :class:`~.gateway.EdgeNode` with zero dependencies beyond the standard
library (the same asyncio-streams shape as ``rpc/http_gateway.py``), and
:class:`EdgeWebSocketServer` serves the same sessions over WebSocket when
the optional ``websockets`` package is installed (gated exactly like
``ui/web.py`` — environments without it still get SSE).

Protocol (SSE):

    GET /edge/sse?keys=<urlencoded JSON [[method, arg...], ...]>
    GET /edge/sse?resume=<token>          (or the Last-Event-ID header)

Every event carries the session's RESUME TOKEN as its SSE ``id`` — so the
browser's own ``Last-Event-ID`` reconnect header IS the resume handle
(EventSource does this without any client code). Event stream:

    event: hello            data: {"token": ..., "keys": [...]}
    event: update           data: {"key", "ver", "value", "cause", "t0"}
    : hb                    (comment heartbeat every heartbeat_interval)

``ver`` is the key's monotonic version; ``cause``/``t0`` are the upstream
fence's identity and wave-apply timestamp (the explain()/delivery-
histogram hop propagation). A reconnect with a token replays exactly the
keys whose current version is newer than the last the session saw.

Observability routes (loopback-only, matching the gateway's trust
default): ``GET /metrics`` (Prometheus exposition of the process
registry — ``fusion_edge_*`` included) and ``GET /edge/stats`` (the
node's snapshot). A slow consumer — a peer that stops reading while the
transport buffer is full — is EVICTED after ``send_timeout`` and handed
its resume token in the close; siblings never notice.
"""
from __future__ import annotations

import asyncio
import json
import logging
import urllib.parse
from typing import Optional

from ..utils.async_utils import TaskSet
from .admission import LANE_RESUME, AdmissionRejected, rejection_bytes
from .gateway import EdgeNode
from .session import KeyedMailbox, frame_to_dict, pump_payloads

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["EdgeHttpServer", "EdgeWebSocketServer"]


def _validate_keys(specs):
    """Wire key-spec shape check shared by BOTH transports: a list of
    non-empty ``[method, arg...]`` arrays. A flat ``["node"]`` must fail
    loudly here — ``tuple("node")`` would silently become the garbage
    method ``'n'``."""
    if not isinstance(specs, list):
        raise ValueError("keys must be a JSON array of [method, arg...] arrays")
    out = []
    for spec in specs:
        if not isinstance(spec, list) or not spec:
            raise ValueError(f"bad key spec {spec!r} (want [method, arg...])")
        out.append(tuple(spec))
    return out


def _parse_keys(raw: Optional[str]):
    if not raw:
        return []
    return _validate_keys(json.loads(raw))


class EdgeHttpServer:
    """SSE live queries for one :class:`EdgeNode` (stdlib-only)."""

    def __init__(
        self,
        node: EdgeNode,
        host: str = "127.0.0.1",
        port: int = 0,
        heartbeat_interval: float = 15.0,
        send_timeout: Optional[float] = 10.0,
        min_send_interval: float = 0.0,
    ):
        self.node = node
        self.host = host
        self.port = port
        self.heartbeat_interval = heartbeat_interval
        self.send_timeout = send_timeout
        self.min_send_interval = min_send_interval
        self.connections = 0
        #: live per-connection pump tasks: stop() cancels them so shutdown
        #: never hangs behind a healthy long-lived stream (Python ≥3.12
        #: wait_closed() waits for connection handlers)
        self._pumps: set = set()
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> "EdgeHttpServer":
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def drain(self) -> None:
        """Hand the listen port off (rolling deploy, ISSUE 12c): stop
        ACCEPTING — the successor process can bind — while live streams
        stay up until ``node.drain()`` hints them to reconnect. Call this
        first, then ``await node.drain()``, then :meth:`stop`."""
        if self._server is not None:
            self._server.close()  # idempotent; stop() finishes the teardown

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for task in list(self._pumps):
                task.cancel()
            if self._pumps:
                await asyncio.gather(*self._pumps, return_exceptions=True)
            await self._server.wait_closed()
            self._server = None

    # ------------------------------------------------------------------ http
    @staticmethod
    async def _write_json(writer, status: str, payload) -> None:
        from ..rpc.http_gateway import FusionHttpServer

        await FusionHttpServer._write_json(writer, status, payload)

    async def _reject(
        self, writer, status: str, payload: dict, reason: str,
        retry_after=None, count: bool = True, note: bool = True,
    ) -> None:
        """The unified COUNTED rejection responder (ISSUE 12 satellite):
        admission 503s, key-allowlist/bad-spec 400s, replay-evicted 409s
        and expired-resume 410s all ride one path — correct Retry-After +
        ``Connection: close`` headers, one ``fusion_edge_shed_total``
        count per response, one journal note. ``count=False`` for
        rejections the admission controller already counted (admit()
        moved the per-reason counter; double counting would make the shed
        totals lie); ``note=False`` when the raiser already journaled
        too (EdgeNode's draining shed)."""
        node = self.node
        if count:
            node.count_shed(reason)
        elif note:
            node._note_shed_event(reason)
        writer.write(rejection_bytes(status, payload, retry_after))
        try:
            await writer.drain()
        except (ConnectionError, OSError):
            pass  # the peer is already gone; the count stands

    async def _reject_admission(self, writer, decision, note=True) -> None:
        await self._reject(
            writer, "503 Service Unavailable",
            {"error": {
                "type": "AdmissionRejected",
                "reason": decision.reason,
                "retry_after": decision.retry_after,
            }},
            reason=decision.reason,
            retry_after=decision.retry_after,
            count=False,  # the controller/node already counted this shed
            note=note,
        )

    @staticmethod
    def _is_loopback(writer) -> bool:
        from ..rpc.http_gateway import _normalize_ip

        peer = writer.get_extra_info("peername")
        return bool(peer) and _normalize_ip(peer[0]) in ("127.0.0.1", "::1")

    async def _handle(self, reader, writer) -> None:
        from ..rpc.http_gateway import read_request_head

        try:
            method, target, headers = await read_request_head(reader)
            if method is None:
                return
            parsed = urllib.parse.urlsplit(target)
            path = parsed.path
            query = urllib.parse.parse_qs(parsed.query)
            if method != "GET":
                await self._write_json(
                    writer, "405 Method Not Allowed",
                    {"error": {"type": "MethodNotAllowed", "message": method}},
                )
                return
            if path == "/edge/sse":
                await self._serve_sse(reader, writer, query, headers)
                return
            if path == "/metrics" and self._is_loopback(writer):
                from ..rpc.http_gateway import write_metrics_response

                await write_metrics_response(writer)
                return
            if path == "/edge/stats" and self._is_loopback(writer):
                pool = self.node.worker_pool
                if pool is not None:
                    # refresh the per-worker stats the snapshot embeds
                    # (each worker replies over its control channel)
                    try:
                        await pool.stats()
                    except Exception:  # noqa: BLE001 — stats are best-effort
                        log.exception("edge worker pool stats failed")
                await self._write_json(writer, "200 OK", self.node.snapshot())
                return
            await self._write_json(
                writer, "404 Not Found",
                {"error": {"type": "NotFound", "message": path}},
            )
        except Exception:  # noqa: BLE001 — one bad request never kills the server
            log.exception("edge http request failed")
        finally:
            writer.close()

    # ------------------------------------------------------------------ sse
    async def _serve_sse(self, reader, writer, query, headers) -> None:
        node = self.node
        token = (
            query.get("resume", [None])[0]
            or headers.get("last-event-id")
            or None
        )
        try:
            keys = _parse_keys(query.get("keys", [None])[0])
        except (ValueError, TypeError) as e:
            await self._reject(
                writer, "400 Bad Request",
                {"error": {"type": "BadRequest", "message": str(e)}},
                reason="bad_request",
            )
            return
        # -- admission (ISSUE 12a): admit or shed BEFORE any session/
        # upstream state exists. Tenant rides the request head (?tenant=
        # or X-Tenant), resolved through the controller's TenantResolver;
        # reconnects ride the reserved resume lane. The gate slot is HELD
        # across attach + replay (the expensive setup), released when the
        # stream starts.
        admission = node.admission
        tenant_id = (
            query.get("tenant", [None])[0] or headers.get("x-tenant") or ""
        )
        decision = None
        if admission is not None:
            # the reserved resume lane (and its global bucket) only for a
            # token this node actually PARKED: a forged/expired
            # ?resume=<garbage> is a cold attach — granting the lane on
            # the token's mere presence would let a flood of garbage
            # tokens bypass the per-tenant buckets AND starve the resume
            # bucket genuine post-deploy reconnects depend on
            decision = admission.admit(
                tenant_id=tenant_id,
                lane=LANE_RESUME if (token and token in node._parked) else None,
                keys=len(keys),
                hold=True,
            )
            if not decision.admitted:
                await self._reject_admission(writer, decision)
                return
        mailbox = KeyedMailbox(max_pending=node.max_pending)
        session = None
        try:
            if token:
                try:
                    session = node.resume(
                        token, mailbox=mailbox, admitted=decision
                    )
                except KeyError:
                    session = None  # expired: fresh attach below
                    if admission is not None and decision.lane == LANE_RESUME:
                        # admitted on the RESERVED resume lane but the
                        # park vanished between the admit and the resume
                        # (expired/raced): this is a COLD attach now —
                        # re-admit on the cold lane so the request pays
                        # the per-tenant buckets/pressure/ceiling like
                        # any other (a cold-lane admission stands as-is)
                        admission.release(decision)
                        decision = admission.admit(
                            tenant_id=tenant_id, lane=None,
                            keys=len(keys), hold=True,
                        )
                        if not decision.admitted:
                            await self._reject_admission(writer, decision)
                            return
            if session is None:
                if not keys:
                    await self._reject(
                        writer, "410 Gone",
                        {"error": {
                            "type": "ResumeExpired",
                            "message": "token unknown/expired and no keys= given",
                        }},
                        reason="resume_expired",
                    )
                    return
                try:
                    session = node.attach(keys, mailbox=mailbox, admitted=decision)
                except (ValueError, TypeError) as e:
                    # allowlist rejection / per-session key cap / bad specs —
                    # the CLIENT's bad input, answered, never a dropped socket
                    await self._reject(
                        writer, "400 Bad Request",
                        {"error": {"type": "BadRequest", "message": str(e)}},
                        reason="bad_request",
                    )
                    return
            if session.evicted:
                # the attach/resume REPLAY itself evicted the session (mailbox
                # bound smaller than the key set): answer loudly — streaming
                # would be exactly the silent heartbeat-alive dead
                # subscription the eviction hook exists to prevent
                await self._reject(
                    writer, "409 Conflict",
                    {"error": {
                        "type": "Evicted",
                        "message": "replay overflowed the session outbox "
                                   "(more keys than max_pending?)",
                        "resume": session.token,
                    }},
                    reason="replay_evicted",
                )
                return
        except AdmissionRejected as e:
            # the NODE refused (a draining edge — with or without a
            # controller installed): answered 503 + Retry-After, counted
            # by the raiser, never a dropped socket
            await self._reject_admission(writer, e.decision, note=False)
            return
        finally:
            # the gate covers head-read -> attach -> replay; streaming is
            # bounded by the session machinery itself
            if admission is not None:
                admission.release(decision)
        self.connections += 1
        sid = session.token
        writer.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\nConnection: close\r\n\r\n"
        )
        hello = json.dumps({"token": sid, "keys": list(session.keys)})
        writer.write(f"id: {sid}\nevent: hello\ndata: {hello}\n\n".encode())
        #: per-session envelope — the ONLY per-session bytes on the hot
        #: path; the event body is the node's shared serialize-once cache
        id_prefix = f"id: {sid}\n".encode()

        async def send(batch) -> None:
            writer.write(
                b"".join(
                    node.encode_frame(frame).sse_event(id_prefix)
                    for frame in batch
                )
            )
            await writer.drain()
            # delivered: advance the resume map + the fence→visible samples
            session.mark_delivered(batch)
            for frame in batch:
                node.record_delivery(frame)

        async def heartbeat() -> None:
            writer.write(b": hb\n\n")
            await writer.drain()

        pump_task = asyncio.ensure_future(
            pump_payloads(
                mailbox,
                send,
                min_send_interval=self.min_send_interval,
                send_timeout=self.send_timeout,
                heartbeat_interval=self.heartbeat_interval,
                heartbeat=heartbeat,
                on_evict=lambda: node.evict(session, reason="sse send timeout"),
            )
        )

        def shutdown_transport() -> None:
            # runs from EdgeNode.evict (any eviction path — send timeout,
            # mailbox overflow, broken sink): the peer must see the stream
            # DIE so its reconnect logic engages, never a silent
            # heartbeat-alive stream that stopped updating
            transport = writer.transport
            if transport is not None:
                transport.abort()
            if not pump_task.done():
                pump_task.cancel()

        def drain_hint(frame) -> None:
            # EdgeNode.drain(): write the reconnect hint — the resume
            # token rides the data payload AND the id line — then wind
            # the pump down; the handler's normal teardown CLOSES (not
            # aborts) the stream so the hint reaches the peer
            try:
                payload = json.dumps(
                    frame_to_dict(frame), separators=(",", ":")
                )
                writer.write(
                    f"id: {sid}\nevent: reconnect\ndata: {payload}\n\n".encode()
                )
            except Exception:  # noqa: BLE001 — a dying peer mid-drain
                pass
            if not pump_task.done():
                pump_task.cancel()

        session.on_evicted = shutdown_transport
        session.on_drain = drain_hint
        self._pumps.add(pump_task)
        try:
            outcome = await pump_task
            if outcome == "closed":
                # normal disconnect: park for resume_ttl so the browser's
                # Last-Event-ID reconnect picks up where it left off
                node.detach(session, park=True)
        except asyncio.CancelledError:
            if not session.evicted:
                # cancelled from OUTSIDE (server stop, handler teardown):
                # park so the client can resume against a restarted server
                node.detach(session, park=True)
                raise
            # eviction-driven cancel: the session is already parked
        finally:
            self._pumps.discard(pump_task)
            self.connections -= 1


class EdgeWebSocketServer:
    """The same sessions over WebSocket (optional ``websockets`` dep,
    gated like ``ui/web.py``). Protocol: the client's FIRST message is
    ``{"keys": [[method, arg...], ...]}`` or ``{"resume": token}``; the
    server replies ``{"hello": {"token", "keys"}}`` and then streams
    ``{"frames": [frame...]}`` batches (latest-wins per key between
    sends) and ``{"ping": t}`` heartbeats."""

    def __init__(
        self,
        node: EdgeNode,
        host: str = "127.0.0.1",
        port: int = 0,
        heartbeat_interval: float = 15.0,
        send_timeout: Optional[float] = 10.0,
        min_send_interval: float = 0.0,
    ):
        self.node = node
        self.host = host
        self.port = port
        self.heartbeat_interval = heartbeat_interval
        self.send_timeout = send_timeout
        self.min_send_interval = min_send_interval
        self.connections = 0
        self._server = None
        #: drain-hint send/close side tasks — owned so stop() can cancel a
        #: hint still in flight instead of leaking it (fusionlint FL003)
        self._side_tasks = TaskSet(name="edge-ws-side")

    async def start(self) -> "EdgeWebSocketServer":
        try:
            from websockets.asyncio.server import serve
        except ImportError as e:  # pragma: no cover — optional dependency
            raise RuntimeError(
                "EdgeWebSocketServer needs the optional 'websockets' package; "
                "the SSE transport (EdgeHttpServer) is dependency-free"
            ) from e
        self._server = await serve(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def url(self) -> str:
        return f"ws://{self.host}:{self.port}/edge/ws"

    async def drain(self) -> None:
        """Stop accepting (the SSE twin's rolling-deploy contract): live
        WS streams stay up until ``node.drain()`` hints them. Unlike
        asyncio's plain ``Server.close()``, the websockets server's
        default also closes every OPEN connection — which would kill the
        streams BEFORE the reconnect hints could reach them — so the
        listener alone is closed here."""
        if self._server is not None:
            try:
                self._server.close(close_connections=False)
            except TypeError:  # older websockets: no kwarg; stop() will
                self._server.close()  # close everything at teardown anyway

    async def stop(self) -> None:
        await self._side_tasks.aclose()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, ws) -> None:
        node = self.node
        loop = asyncio.get_running_loop()
        try:
            raw = await ws.recv()
        except Exception:  # noqa: BLE001 — the peer left before a hello
            # (health probes, stray disconnects): a normal exit, NOT a
            # shed — counting it would pollute bad_request on a healthy
            # node and make the counters untrustworthy
            return
        try:
            first = json.loads(raw)
            if not isinstance(first, dict):
                raise ValueError("hello must be a JSON object")
        except Exception as e:  # noqa: BLE001 — bad hello: answer, close
            node.count_shed("bad_request")
            try:
                await ws.send(json.dumps({"error": f"bad hello: {e}"}))
            except Exception:  # noqa: BLE001 — peer already gone
                pass
            await ws.close()
            return
        token = first.get("resume")
        # -- admission (ISSUE 12a): the WS twin of the SSE path — tenant
        # rides the hello ({"tenant": ...}), reconnects the resume lane; a
        # shed answers a CLEAN error frame + close 1013 (Try Again Later),
        # never a dropped socket
        async def reject_ws(decision) -> None:
            # the WS twin of _reject_admission: ONE clean error frame +
            # close 1013 (Try Again Later) — every WS shed rides it
            try:
                await ws.send(json.dumps({
                    "error": "admission rejected",
                    "reason": decision.reason,
                    "retry_after": decision.retry_after,
                }))
            finally:
                await ws.close(code=1013)

        admission = node.admission
        decision = None
        if admission is not None:
            raw_keys = first.get("keys")
            # resume lane only for a token this node PARKED (the SSE rule)
            decision = admission.admit(
                tenant_id=first.get("tenant") or "",
                lane=LANE_RESUME if (token and token in node._parked) else None,
                keys=len(raw_keys) if isinstance(raw_keys, list) else 0,
                hold=True,
            )
            if not decision.admitted:
                node._note_shed_event(decision.reason, lane=decision.lane)
                await reject_ws(decision)
                return
        mailbox = KeyedMailbox(max_pending=node.max_pending)
        session = None
        try:
            if token:
                try:
                    session = node.resume(
                        token, mailbox=mailbox, admitted=decision
                    )
                except KeyError:
                    session = None
                    if admission is not None and decision.lane == LANE_RESUME:
                        # resume-lane admission whose park vanished (the
                        # SSE twin's rule): re-admit as the cold attach
                        # it now is; a cold-lane admission stands as-is
                        admission.release(decision)
                        raw_keys = first.get("keys")
                        decision = admission.admit(
                            tenant_id=first.get("tenant") or "",
                            lane=None,
                            keys=len(raw_keys)
                            if isinstance(raw_keys, list) else 0,
                            hold=True,
                        )
                        if not decision.admitted:
                            node._note_shed_event(
                                decision.reason, lane=decision.lane
                            )
                            await reject_ws(decision)
                            return
            if session is None:
                try:
                    keys = _validate_keys(first.get("keys", []))
                    if not keys:
                        raise ValueError("no keys and no valid resume token")
                    session = node.attach(keys, mailbox=mailbox, admitted=decision)
                except (ValueError, TypeError) as e:
                    node.count_shed("bad_request")
                    await ws.send(json.dumps({"error": str(e)}))
                    await ws.close()
                    return
            if session.evicted:  # replay overflow: same contract as SSE's 409
                node.count_shed("replay_evicted")
                await ws.send(
                    json.dumps({"error": "replay overflowed the session outbox",
                                "resume": session.token})
                )
                await ws.close()
                return
        except AdmissionRejected as e:
            # the NODE refused (a draining edge): a clean answered close,
            # counted by the raiser
            await reject_ws(e.decision)
            return
        finally:
            if admission is not None:
                admission.release(decision)
        async def send(batch) -> None:
            # the frame bodies are the node's shared serialize-once cache
            # (decoded to str at most once per (key, version)); only the
            # tiny batch envelope is assembled per send
            await ws.send(
                '{"frames":['
                + ",".join(node.encode_frame(f).text for f in batch)
                + "]}"
            )
            session.mark_delivered(batch)
            for frame in batch:
                node.record_delivery(frame)

        async def heartbeat() -> None:
            await ws.send(json.dumps({"ping": loop.time()}))

        pump_task = asyncio.ensure_future(
            pump_payloads(
                mailbox,
                send,
                min_send_interval=self.min_send_interval,
                send_timeout=self.send_timeout,
                heartbeat_interval=self.heartbeat_interval,
                heartbeat=heartbeat,
                on_evict=lambda: node.evict(session, reason="ws send timeout"),
            )
        )

        def shutdown_transport() -> None:
            # any eviction path (send timeout, overflow, broken sink) must
            # kill the socket so the peer's reconnect logic engages
            transport = getattr(ws, "transport", None)
            if transport is not None:
                transport.abort()
            if not pump_task.done():
                pump_task.cancel()

        def drain_hint(frame) -> None:
            # EdgeNode.drain(): send the reconnect hint as its own frame,
            # then close 1001 (Going Away) — the peer reconnects with the
            # carried resume token; never an abort (the hint must arrive)
            async def _send_and_close() -> None:
                try:
                    await ws.send(
                        json.dumps({"reconnect": frame_to_dict(frame)})
                    )
                finally:
                    await ws.close(code=1001)

            try:
                self._side_tasks.spawn(_send_and_close())
            except RuntimeError:  # server already stopped: nothing to hint
                pass
            if not pump_task.done():
                pump_task.cancel()

        session.on_evicted = shutdown_transport
        session.on_drain = drain_hint
        self.connections += 1
        # EVERY await from here on sits under the finally: a peer that
        # drops right after subscribing (the hello send raising) must
        # still detach — a ghost session would be fanned to forever and
        # pin its subs
        try:
            await ws.send(
                json.dumps(
                    {"hello": {"token": session.token, "keys": list(session.keys)}}
                )
            )
            async for _raw in ws:  # inbound ignored; the stream is one-way
                pass
        except Exception:  # noqa: BLE001 — a dying socket is a normal exit
            pass
        finally:
            self.connections -= 1
            pump_task.cancel()
            if not session.evicted:  # evict() already parked it otherwise
                node.detach(session, park=True)
