"""Edge transports: SSE over stdlib asyncio + optional WebSocket.

The browser-facing surface of the edge tier (ISSUE 8): an
:class:`EdgeHttpServer` serves ``text/event-stream`` live queries against
an :class:`~.gateway.EdgeNode` with zero dependencies beyond the standard
library (the same asyncio-streams shape as ``rpc/http_gateway.py``), and
:class:`EdgeWebSocketServer` serves the same sessions over WebSocket when
the optional ``websockets`` package is installed (gated exactly like
``ui/web.py`` — environments without it still get SSE).

Protocol (SSE):

    GET /edge/sse?keys=<urlencoded JSON [[method, arg...], ...]>
    GET /edge/sse?resume=<token>          (or the Last-Event-ID header)

Every event carries the session's RESUME TOKEN as its SSE ``id`` — so the
browser's own ``Last-Event-ID`` reconnect header IS the resume handle
(EventSource does this without any client code). Event stream:

    event: hello            data: {"token": ..., "keys": [...]}
    event: update           data: {"key", "ver", "value", "cause", "t0"}
    : hb                    (comment heartbeat every heartbeat_interval)

``ver`` is the key's monotonic version; ``cause``/``t0`` are the upstream
fence's identity and wave-apply timestamp (the explain()/delivery-
histogram hop propagation). A reconnect with a token replays exactly the
keys whose current version is newer than the last the session saw.

Observability routes (loopback-only, matching the gateway's trust
default): ``GET /metrics`` (Prometheus exposition of the process
registry — ``fusion_edge_*`` included) and ``GET /edge/stats`` (the
node's snapshot). A slow consumer — a peer that stops reading while the
transport buffer is full — is EVICTED after ``send_timeout`` and handed
its resume token in the close; siblings never notice.
"""
from __future__ import annotations

import asyncio
import json
import logging
import urllib.parse
from typing import Optional

from .gateway import EdgeNode
from .session import KeyedMailbox, pump_payloads

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["EdgeHttpServer", "EdgeWebSocketServer"]


def _validate_keys(specs):
    """Wire key-spec shape check shared by BOTH transports: a list of
    non-empty ``[method, arg...]`` arrays. A flat ``["node"]`` must fail
    loudly here — ``tuple("node")`` would silently become the garbage
    method ``'n'``."""
    if not isinstance(specs, list):
        raise ValueError("keys must be a JSON array of [method, arg...] arrays")
    out = []
    for spec in specs:
        if not isinstance(spec, list) or not spec:
            raise ValueError(f"bad key spec {spec!r} (want [method, arg...])")
        out.append(tuple(spec))
    return out


def _parse_keys(raw: Optional[str]):
    if not raw:
        return []
    return _validate_keys(json.loads(raw))


class EdgeHttpServer:
    """SSE live queries for one :class:`EdgeNode` (stdlib-only)."""

    def __init__(
        self,
        node: EdgeNode,
        host: str = "127.0.0.1",
        port: int = 0,
        heartbeat_interval: float = 15.0,
        send_timeout: Optional[float] = 10.0,
        min_send_interval: float = 0.0,
    ):
        self.node = node
        self.host = host
        self.port = port
        self.heartbeat_interval = heartbeat_interval
        self.send_timeout = send_timeout
        self.min_send_interval = min_send_interval
        self.connections = 0
        #: live per-connection pump tasks: stop() cancels them so shutdown
        #: never hangs behind a healthy long-lived stream (Python ≥3.12
        #: wait_closed() waits for connection handlers)
        self._pumps: set = set()
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> "EdgeHttpServer":
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for task in list(self._pumps):
                task.cancel()
            if self._pumps:
                await asyncio.gather(*self._pumps, return_exceptions=True)
            await self._server.wait_closed()
            self._server = None

    # ------------------------------------------------------------------ http
    @staticmethod
    async def _write_json(writer, status: str, payload) -> None:
        from ..rpc.http_gateway import FusionHttpServer

        await FusionHttpServer._write_json(writer, status, payload)

    @staticmethod
    def _is_loopback(writer) -> bool:
        from ..rpc.http_gateway import _normalize_ip

        peer = writer.get_extra_info("peername")
        return bool(peer) and _normalize_ip(peer[0]) in ("127.0.0.1", "::1")

    async def _handle(self, reader, writer) -> None:
        from ..rpc.http_gateway import read_request_head

        try:
            method, target, headers = await read_request_head(reader)
            if method is None:
                return
            parsed = urllib.parse.urlsplit(target)
            path = parsed.path
            query = urllib.parse.parse_qs(parsed.query)
            if method != "GET":
                await self._write_json(
                    writer, "405 Method Not Allowed",
                    {"error": {"type": "MethodNotAllowed", "message": method}},
                )
                return
            if path == "/edge/sse":
                await self._serve_sse(reader, writer, query, headers)
                return
            if path == "/metrics" and self._is_loopback(writer):
                from ..rpc.http_gateway import write_metrics_response

                await write_metrics_response(writer)
                return
            if path == "/edge/stats" and self._is_loopback(writer):
                pool = self.node.worker_pool
                if pool is not None:
                    # refresh the per-worker stats the snapshot embeds
                    # (each worker replies over its control channel)
                    try:
                        await pool.stats()
                    except Exception:  # noqa: BLE001 — stats are best-effort
                        log.exception("edge worker pool stats failed")
                await self._write_json(writer, "200 OK", self.node.snapshot())
                return
            await self._write_json(
                writer, "404 Not Found",
                {"error": {"type": "NotFound", "message": path}},
            )
        except Exception:  # noqa: BLE001 — one bad request never kills the server
            log.exception("edge http request failed")
        finally:
            writer.close()

    # ------------------------------------------------------------------ sse
    async def _serve_sse(self, reader, writer, query, headers) -> None:
        node = self.node
        token = (
            query.get("resume", [None])[0]
            or headers.get("last-event-id")
            or None
        )
        try:
            keys = _parse_keys(query.get("keys", [None])[0])
        except (ValueError, TypeError) as e:
            await self._write_json(
                writer, "400 Bad Request",
                {"error": {"type": "BadRequest", "message": str(e)}},
            )
            return
        mailbox = KeyedMailbox(max_pending=node.max_pending)
        session = None
        if token:
            try:
                session = node.resume(token, mailbox=mailbox)
            except KeyError:
                session = None  # expired: fall back to a fresh attach below
        if session is None:
            if not keys:
                await self._write_json(
                    writer, "410 Gone",
                    {"error": {
                        "type": "ResumeExpired",
                        "message": "token unknown/expired and no keys= given",
                    }},
                )
                return
            try:
                session = node.attach(keys, mailbox=mailbox)
            except (ValueError, TypeError) as e:
                # allowlist rejection / per-session key cap / bad specs —
                # the CLIENT's bad input, answered, never a dropped socket
                await self._write_json(
                    writer, "400 Bad Request",
                    {"error": {"type": "BadRequest", "message": str(e)}},
                )
                return
        if session.evicted:
            # the attach/resume REPLAY itself evicted the session (mailbox
            # bound smaller than the key set): answer loudly — streaming
            # would be exactly the silent heartbeat-alive dead
            # subscription the eviction hook exists to prevent
            await self._write_json(
                writer, "409 Conflict",
                {"error": {
                    "type": "Evicted",
                    "message": "replay overflowed the session outbox "
                               "(more keys than max_pending?)",
                    "resume": session.token,
                }},
            )
            return
        self.connections += 1
        sid = session.token
        writer.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\nConnection: close\r\n\r\n"
        )
        hello = json.dumps({"token": sid, "keys": list(session.keys)})
        writer.write(f"id: {sid}\nevent: hello\ndata: {hello}\n\n".encode())
        #: per-session envelope — the ONLY per-session bytes on the hot
        #: path; the event body is the node's shared serialize-once cache
        id_prefix = f"id: {sid}\n".encode()

        async def send(batch) -> None:
            writer.write(
                b"".join(
                    node.encode_frame(frame).sse_event(id_prefix)
                    for frame in batch
                )
            )
            await writer.drain()
            # delivered: advance the resume map + the fence→visible samples
            session.mark_delivered(batch)
            for frame in batch:
                node.record_delivery(frame)

        async def heartbeat() -> None:
            writer.write(b": hb\n\n")
            await writer.drain()

        pump_task = asyncio.ensure_future(
            pump_payloads(
                mailbox,
                send,
                min_send_interval=self.min_send_interval,
                send_timeout=self.send_timeout,
                heartbeat_interval=self.heartbeat_interval,
                heartbeat=heartbeat,
                on_evict=lambda: node.evict(session, reason="sse send timeout"),
            )
        )

        def shutdown_transport() -> None:
            # runs from EdgeNode.evict (any eviction path — send timeout,
            # mailbox overflow, broken sink): the peer must see the stream
            # DIE so its reconnect logic engages, never a silent
            # heartbeat-alive stream that stopped updating
            transport = writer.transport
            if transport is not None:
                transport.abort()
            if not pump_task.done():
                pump_task.cancel()

        session.on_evicted = shutdown_transport
        self._pumps.add(pump_task)
        try:
            outcome = await pump_task
            if outcome == "closed":
                # normal disconnect: park for resume_ttl so the browser's
                # Last-Event-ID reconnect picks up where it left off
                node.detach(session, park=True)
        except asyncio.CancelledError:
            if not session.evicted:
                # cancelled from OUTSIDE (server stop, handler teardown):
                # park so the client can resume against a restarted server
                node.detach(session, park=True)
                raise
            # eviction-driven cancel: the session is already parked
        finally:
            self._pumps.discard(pump_task)
            self.connections -= 1


class EdgeWebSocketServer:
    """The same sessions over WebSocket (optional ``websockets`` dep,
    gated like ``ui/web.py``). Protocol: the client's FIRST message is
    ``{"keys": [[method, arg...], ...]}`` or ``{"resume": token}``; the
    server replies ``{"hello": {"token", "keys"}}`` and then streams
    ``{"frames": [frame...]}`` batches (latest-wins per key between
    sends) and ``{"ping": t}`` heartbeats."""

    def __init__(
        self,
        node: EdgeNode,
        host: str = "127.0.0.1",
        port: int = 0,
        heartbeat_interval: float = 15.0,
        send_timeout: Optional[float] = 10.0,
        min_send_interval: float = 0.0,
    ):
        self.node = node
        self.host = host
        self.port = port
        self.heartbeat_interval = heartbeat_interval
        self.send_timeout = send_timeout
        self.min_send_interval = min_send_interval
        self.connections = 0
        self._server = None

    async def start(self) -> "EdgeWebSocketServer":
        try:
            from websockets.asyncio.server import serve
        except ImportError as e:  # pragma: no cover — optional dependency
            raise RuntimeError(
                "EdgeWebSocketServer needs the optional 'websockets' package; "
                "the SSE transport (EdgeHttpServer) is dependency-free"
            ) from e
        self._server = await serve(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def url(self) -> str:
        return f"ws://{self.host}:{self.port}/edge/ws"

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, ws) -> None:
        node = self.node
        loop = asyncio.get_running_loop()
        try:
            first = json.loads(await ws.recv())
            if not isinstance(first, dict):
                raise ValueError("hello must be a JSON object")
        except Exception as e:  # noqa: BLE001 — bad hello: answer, close
            try:
                await ws.send(json.dumps({"error": f"bad hello: {e}"}))
            except Exception:  # noqa: BLE001 — peer already gone
                pass
            await ws.close()
            return
        mailbox = KeyedMailbox(max_pending=node.max_pending)
        session = None
        token = first.get("resume")
        if token:
            try:
                session = node.resume(token, mailbox=mailbox)
            except KeyError:
                session = None
        if session is None:
            try:
                keys = _validate_keys(first.get("keys", []))
                if not keys:
                    raise ValueError("no keys and no valid resume token")
                session = node.attach(keys, mailbox=mailbox)
            except (ValueError, TypeError) as e:
                await ws.send(json.dumps({"error": str(e)}))
                await ws.close()
                return
        if session.evicted:  # replay overflow: same contract as SSE's 409
            await ws.send(
                json.dumps({"error": "replay overflowed the session outbox",
                            "resume": session.token})
            )
            await ws.close()
            return
        async def send(batch) -> None:
            # the frame bodies are the node's shared serialize-once cache
            # (decoded to str at most once per (key, version)); only the
            # tiny batch envelope is assembled per send
            await ws.send(
                '{"frames":['
                + ",".join(node.encode_frame(f).text for f in batch)
                + "]}"
            )
            session.mark_delivered(batch)
            for frame in batch:
                node.record_delivery(frame)

        async def heartbeat() -> None:
            await ws.send(json.dumps({"ping": loop.time()}))

        pump_task = asyncio.ensure_future(
            pump_payloads(
                mailbox,
                send,
                min_send_interval=self.min_send_interval,
                send_timeout=self.send_timeout,
                heartbeat_interval=self.heartbeat_interval,
                heartbeat=heartbeat,
                on_evict=lambda: node.evict(session, reason="ws send timeout"),
            )
        )

        def shutdown_transport() -> None:
            # any eviction path (send timeout, overflow, broken sink) must
            # kill the socket so the peer's reconnect logic engages
            transport = getattr(ws, "transport", None)
            if transport is not None:
                transport.abort()
            if not pump_task.done():
                pump_task.cancel()

        session.on_evicted = shutdown_transport
        self.connections += 1
        # EVERY await from here on sits under the finally: a peer that
        # drops right after subscribing (the hello send raising) must
        # still detach — a ghost session would be fanned to forever and
        # pin its subs
        try:
            await ws.send(
                json.dumps(
                    {"hello": {"token": session.token, "keys": list(session.keys)}}
                )
            )
            async for _raw in ws:  # inbound ignored; the stream is one-way
                pass
        except Exception:  # noqa: BLE001 — a dying socket is a normal exit
            pass
        finally:
            self.connections -= 1
            pump_task.cancel()
            if not session.evicted:  # evict() already parked it otherwise
                node.detach(session, park=True)
