"""Multi-process edge delivery plane (ISSUE 10c).

The PR 8 edge tier measured a pure-Python ceiling: one process fans
~292k session-deliveries/s no matter how cheap the per-delivery work
gets, because one interpreter walks every session. This module moves the
DELIVERY half of the edge onto N OS worker processes while the parent
:class:`~.gateway.EdgeNode` keeps the UPSTREAM half — the single
subscription per distinct key, the shard-map affinity, the resume/park
state. The split rides the serialize-once contract end to end:

- the parent encodes each fenced frame ONCE (``EdgeNode.encode_frame``)
  and pushes the immutable body bytes over a per-worker socketpair —
  one ``F`` message per (worker, key, version), never per session;
- each worker owns its sockets and ONLY writes bytes: the per-session
  work is assembling ``id: <token>\\n`` + the shared SSE tail and
  pushing it down the connection — no JSON, no Python object graph, no
  upstream state;
- deliveries/s therefore scales with worker processes (measured in
  perf/edge_path.py; the bench records ``deliveries_per_s_per_worker``).

**Socket ownership: a ``send_fds`` accept plane (ISSUE 11), REUSEPORT
as the fallback knob.** PR 10 shipped per-worker ``SO_REUSEPORT``
listeners — symmetric workers, no parent accept loop — at the cost of
kernel-hash placement: a RECONNECT could land on a different worker, so
resume tokens were worker-local. The default accept plane now closes
that tradeoff: the PARENT owns one listening socket, reads just the
request head off each accepted connection, routes by the resume token's
worker ordinal (``es-w<N>-…``, from the ``Last-Event-ID`` header or the
``resume=`` query param; tokenless connections round-robin), and hands
the fd to that worker over a dedicated ``socket.send_fds`` channel
along with the already-read head bytes. A resume token is therefore
valid on ANY connection — the parent delivers it to the worker that
parked it, which replays only the versions the session missed.
``accept_plane="reuseport"`` keeps the PR 10 shape (symmetric
independently-restartable workers, no parent accept hop) for
deployments that prefer it; its resume misses still fall back to a
fresh attach. EDGE.md documents both planes' capacity math.

Wire protocol (parent <-> worker, framed ``!BI`` type+length):

    parent -> worker                     worker -> parent
    K {id, key}        register key
    S {sessions}       add sim sessions
    F key_id ver t0 body  one encoded frame
    L {host, port}     start SSE listener  P {port}   actual bound port
    G {heartbeat, resume_ttl}  SSE config (send_fds plane: no bind)
    Q {seq}            stats request       R {...}    stats reply
    X                  shutdown            U {conn, keys}  SSE subscribe
                                           D {conn, key_ids} SSE closed

    (fd channel, send_fds plane only: one sendmsg per accepted conn —
     ``!I``-framed JSON {head: b64} + the connection fd as ancillary)

Workers are spawned as ``python <this file> --worker`` subprocesses so
they import NOTHING beyond the standard library — no jax, no package
``__init__`` — and are serving in tens of milliseconds.

Simulated sessions (``S``) are the 1M-subscriber benchmark's population:
a worker-held list of per-session envelope prefixes per key; a frame
"delivery" assembles the exact bytes a socket write would take (prefix +
shared tail) and accounts for it, without a million real TCP peers. The
REAL path (``L`` + SSE over SO_REUSEPORT) serves actual browsers with
the same code path and is what the CI smoke drives.
"""
from __future__ import annotations

import asyncio
import base64
import json
import logging
import os
import socket
import struct
import sys
import time
from typing import Any, Dict, List, Optional, Sequence

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["EdgeWorkerPool"]

_HEADER = struct.Struct("!BI")
_FRAME = struct.Struct("!IId")  # key_id, version, t0 (-1.0 = none)

#: the worker's drain-time 503 for connections caught MID-ATTACH — the
#: same wire shape as edge.admission.rejection_bytes (status, JSON body,
#: Retry-After, Connection: close), inlined because the worker half of
#: this file is stdlib-only and cannot import the package
_DRAIN_503_BODY = (
    b'{"error":{"type":"AdmissionRejected","reason":"draining",'
    b'"retry_after":1}}'
)
_DRAIN_503 = (
    b"HTTP/1.1 503 Service Unavailable\r\n"
    b"Content-Type: application/json\r\n"
    b"Content-Length: " + str(len(_DRAIN_503_BODY)).encode() + b"\r\n"
    b"Cache-Control: no-cache\r\nConnection: close\r\nRetry-After: 1"
    b"\r\n\r\n" + _DRAIN_503_BODY
)

# log-scale histogram buckets — MUST mirror diagnostics.metrics.Histogram
# (lo * 2^k up to hi, + overflow) so the parent can merge worker counts
# into fusion_edge_delivery_ms bucket-for-bucket
_HIST_LO, _HIST_HI = 0.001, 120_000.0


def _hist_edges() -> List[float]:
    edges, edge = [], _HIST_LO
    while edge <= _HIST_HI:
        edges.append(edge)
        edge *= 2.0
    return edges


def _bisect_left(edges: List[float], v: float) -> int:
    lo, hi = 0, len(edges)
    while lo < hi:
        mid = (lo + hi) // 2
        if edges[mid] < v:
            lo = mid + 1
        else:
            hi = mid
    return lo


# ======================================================================
# parent side
# ======================================================================


class _Worker:
    """Parent-side handle to one delivery worker process."""

    __slots__ = (
        "index", "proc", "sock", "fd_sock", "fd_lock", "reader", "writer",
        "reader_task", "interest", "sim_keys", "conn_refs", "stats_futures",
        "port_future", "last_stats", "last_hist", "sim_sessions", "outbuf",
    )

    def __init__(self, index: int):
        self.index = index
        self.proc = None
        self.sock: Optional[socket.socket] = None
        #: the send_fds channel: accepted-connection fds ride here (one
        #: sendmsg per conn), never the framed control stream above.
        #: NON-blocking + lock-serialized: a wedged worker must cost
        #: dropped handoffs, never a frozen parent event loop, and two
        #: concurrent handoffs must never interleave a partial frame
        self.fd_sock: Optional[socket.socket] = None
        self.fd_lock: Optional[asyncio.Lock] = None
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.reader_task: Optional[asyncio.Task] = None
        #: key_ids this worker has sessions (sim or real) on — the frame
        #: broadcast filter. Materialized from ``sim_keys`` (permanent for
        #: the pool's life) ∪ keys with a live real-connection refcount —
        #: pruned on disconnect so a key nobody watches stops costing a
        #: pipe write per fence
        self.interest: set = set()
        self.sim_keys: set = set()
        self.conn_refs: Dict[int, int] = {}
        self.stats_futures: Dict[int, asyncio.Future] = {}
        self.port_future: Optional[asyncio.Future] = None
        self.last_stats: Optional[dict] = None
        #: previous cumulative histogram buckets (delta-merge source)
        self.last_hist: Optional[List[int]] = None
        self.sim_sessions = 0
        #: pending outbound messages — flushed as ONE write per event-loop
        #: tick (a write per message would wake the worker per frame; the
        #: wake-up preemption ping-pong measurably halves the parent's
        #: upstream throughput during a burst)
        self.outbuf: List[bytes] = []

    def send(self, mtype: bytes, payload: bytes) -> None:
        if self.writer is None or self.writer.is_closing():
            return
        self.outbuf.append(_HEADER.pack(mtype[0], len(payload)) + payload)

    def send_json(self, mtype: bytes, obj: Any) -> None:
        self.send(mtype, json.dumps(obj).encode())

    def flush(self) -> None:
        if not self.outbuf:
            return
        buf, self.outbuf = self.outbuf, []
        if self.writer is None or self.writer.is_closing():
            return
        self.writer.write(b"".join(buf))


class EdgeWorkerPool:
    """N OS delivery processes behind one :class:`~.gateway.EdgeNode`.

    ``await pool.start()`` spawns the workers and registers the pool as
    the node's delivery-plane broadcast: every fanned frame's SHARED
    encoded bytes go to each worker with sessions on that key, exactly
    once per (worker, key, version).

    - :meth:`add_sim_sessions` populates the benchmark population;
    - :meth:`listen` starts the real SO_REUSEPORT SSE listeners;
    - :meth:`stats` pulls per-worker counters and merges the workers'
      delivery histograms into the process ``fusion_edge_delivery_ms``
      (so the system's own histogram stays the single source of truth).
    """

    def __init__(self, node, workers: int = 2, stats_timeout: float = 10.0,
                 flush_interval: float = 0.02, accept_plane: str = "send_fds",
                 resume_ttl: float = 60.0):
        if workers < 1:
            raise ValueError("worker pool needs at least 1 worker")
        if accept_plane not in ("send_fds", "reuseport"):
            raise ValueError(
                f"accept_plane must be 'send_fds' or 'reuseport', "
                f"got {accept_plane!r}"
            )
        self.node = node
        self.n_workers = workers
        self.stats_timeout = stats_timeout
        #: "send_fds" (default): the parent accepts, routes by resume
        #: token, and hands each fd to the owning worker — portable resume
        #: tokens (ISSUE 11). "reuseport": per-worker SO_REUSEPORT
        #: listeners, kernel-hash placement, worker-local tokens (PR 10).
        self.accept_plane = accept_plane
        #: how long a worker parks a disconnected SSE session's delivered-
        #: version map under its token (the resume replay source)
        self.resume_ttl = resume_ttl
        #: frame-pipe flush window. Every write to a worker pipe WAKES the
        #: worker process, and on a saturated box the sender-preemption
        #: ping-pong (one wake per fanned frame per worker) measurably
        #: halves the parent's upstream fence throughput — so frame posts
        #: buffer up to this long and ship as one write per worker. The
        #: added delivery latency (≤ the window) is noise against the
        #: fence→visible distribution; control round-trips (stats, listen,
        #: shutdown) flush immediately.
        self.flush_interval = flush_interval
        self._workers: List[_Worker] = []
        self._key_ids: Dict[str, int] = {}
        self._key_specs: Dict[str, tuple] = {}
        #: upstream pins held for simulated sessions (released at stop)
        self._sim_acquired: List[str] = []
        #: (worker, conn) -> acquired key_strs for real SSE connections
        self._conn_keys: Dict[tuple, List[str]] = {}
        self._stats_seq = 0
        self._started = False
        self._flush_scheduled = False
        self.listen_port: Optional[int] = None
        #: the send_fds plane's parent listener + accept machinery
        self._listen_sock: Optional[socket.socket] = None
        self._accept_task: Optional[asyncio.Task] = None
        self._route_tasks: set = set()
        self._accept_rr = 0
        self.routed_conns = 0  # fds handed to workers
        self.routed_by_token = 0  # of which: placed by a resume token
        self.route_errors = 0
        self.shed_conns = 0  # admission/overload rejections answered 503
        #: tokens the workers reported PARKED (disconnect `D` messages
        #: carry them): the accept plane grants the reserved resume lane
        #: only to a token it knows is genuinely parked — a forged
        #: ``?resume=es-w0-x`` rides the cold lane like any other cold
        #: attach. token -> expiry (resume_ttl), amortized prune.
        self._parked_tokens: Dict[str, float] = {}
        self._next_token_prune = 0.0
        #: recent dropped-handoff timestamps — the worker-pipe saturation
        #: signal (ISSUE 12b): registered as an admission pressure source
        #: at start(); ``drop_pressure_threshold`` drops inside
        #: ``drop_pressure_window`` seconds reads as FULL pressure
        self._drop_times: List[float] = []
        self.drop_pressure_window = 5.0
        self.drop_pressure_threshold = 8
        #: cumulative deliveries last pulled from workers (sync-readable
        #: by the node's metrics collector)
        self.deliveries_seen = 0
        self._hist_edges = _hist_edges()

    # -------------------------------------------------------------- lifecycle
    async def start(self) -> "EdgeWorkerPool":
        if self._started:
            return self
        loop = asyncio.get_event_loop()
        script = os.path.abspath(__file__)
        for i in range(self.n_workers):
            w = _Worker(i)
            parent_sock, child_sock = socket.socketpair()
            parent_sock.setblocking(False)
            # the fd-handoff channel (send_fds accept plane) — created
            # unconditionally so the plane can be chosen at listen() time
            parent_fd_sock, child_fd_sock = socket.socketpair()
            parent_fd_sock.setblocking(False)
            w.fd_lock = asyncio.Lock()
            import subprocess

            w.proc = subprocess.Popen(
                [sys.executable, script, "--worker", str(i),
                 str(child_sock.fileno()), str(child_fd_sock.fileno())],
                pass_fds=(child_sock.fileno(), child_fd_sock.fileno()),
                close_fds=True,
            )
            child_sock.close()
            child_fd_sock.close()
            w.sock = parent_sock
            w.fd_sock = parent_fd_sock
            w.reader, w.writer = await asyncio.open_connection(sock=parent_sock)
            w.reader_task = loop.create_task(self._read_worker(w))
            self._workers.append(w)
        self._started = True
        self.node.worker_pool = self
        self.node.attach_broadcast(self._on_frame)
        admission = getattr(self.node, "admission", None)
        if admission is not None:
            # worker-pipe saturation feeds the admission controller: a
            # wedged delivery worker costs dropped handoffs (already
            # counted in route_errors), and the drop rate IS the load
            # signal that sheds anonymous cold attaches upstream of it
            admission.add_pressure_source(
                f"{self.node.name}:worker_pipe", self._pipe_pressure
            )
        return self

    # -------------------------------------------------------------- pressure
    def _note_drop(self) -> None:
        self._drop_times.append(time.monotonic())

    def _pipe_pressure(self) -> float:
        """0..1 worker-pipe saturation from recent dropped fd-handoffs
        (pruned to the window on every pull)."""
        cutoff = time.monotonic() - self.drop_pressure_window
        self._drop_times = [t for t in self._drop_times if t >= cutoff]
        return min(1.0, len(self._drop_times) / self.drop_pressure_threshold)

    async def stop(self) -> None:
        if not self._started:
            return
        self._started = False
        self.node.detach_broadcast(self._on_frame)
        admission = getattr(self.node, "admission", None)
        if admission is not None:
            admission.clear_pressure(f"{self.node.name}:worker_pipe")
        if self.node.worker_pool is self:
            self.node.worker_pool = None
        if self._accept_task is not None:
            self._accept_task.cancel()
            self._accept_task = None
        for task in list(self._route_tasks):
            task.cancel()
        if self._listen_sock is not None:
            try:
                self._listen_sock.close()
            except OSError:
                pass
            self._listen_sock = None
        for w in self._workers:
            try:
                w.send(b"X", b"")
                w.flush()
                if w.writer is not None:
                    await w.writer.drain()
            except Exception:  # noqa: BLE001 — already-dead worker
                pass
        for w in self._workers:
            if w.reader_task is not None:
                w.reader_task.cancel()
            if w.writer is not None:
                try:
                    w.writer.close()
                except Exception:  # noqa: BLE001
                    pass
            if w.proc is not None:
                # reap off-loop: a blocking wait() here would freeze every
                # other edge's watch loops and pumps for up to the timeout
                try:
                    await asyncio.get_event_loop().run_in_executor(
                        None, w.proc.wait, 5.0
                    )
                except Exception:  # noqa: BLE001 — escalate
                    try:
                        w.proc.kill()
                        await asyncio.get_event_loop().run_in_executor(
                            None, w.proc.wait, 5.0
                        )
                    except Exception:  # noqa: BLE001 — a zombie must not
                        # fail stop(); the OS reaps it with the parent
                        log.exception(
                            "edge worker %d did not exit after kill", w.index
                        )
        for w in self._workers:
            if w.fd_sock is not None:
                try:
                    w.fd_sock.close()
                except OSError:
                    pass
        # release every key real connections + sim sessions still held
        for (_wi, _conn), (key_strs, _kids) in list(self._conn_keys.items()):
            self.node.release_keys(key_strs)
        self._conn_keys.clear()
        self.node.release_keys(self._sim_acquired)
        self._sim_acquired.clear()
        self._workers.clear()

    async def drain(self) -> int:
        """The delivery plane's half of a graceful drain (ISSUE 12c):
        stop accepting (both planes — the parent listener closes, each
        worker closes its REUSEPORT listener), then every worker writes
        its live SSE connections ONE ``event: reconnect`` hint carrying
        the session's resume token and closes them cleanly, parking the
        delivered-version maps. Returns the number of connections
        hinted. Called by :meth:`EdgeNode.drain` — a pooled deployment's
        sessions are NOT stranded when the node drains."""
        if not self._started:
            return 0
        if self._accept_task is not None:
            self._accept_task.cancel()
            self._accept_task = None
        if self._listen_sock is not None:
            try:
                self._listen_sock.close()
            except OSError:
                pass
            self._listen_sock = None
        loop = asyncio.get_event_loop()
        self._stats_seq += 1
        seq = self._stats_seq
        futures = []
        for w in self._workers:
            fut = loop.create_future()
            w.stats_futures[seq] = fut
            w.send_json(b"Y", {"seq": seq})
            futures.append(fut)
        self._flush_all()
        # per-future harvest: ONE wedged worker missing the deadline must
        # not discard the healthy workers' counts (their sessions WERE
        # hinted — under-reporting sessions_drained would make the drain
        # accounting unreconcilable); its own clients reconnect on the
        # dead socket instead
        await asyncio.wait(futures, timeout=self.stats_timeout)
        total = 0
        for w, fut in zip(self._workers, futures):
            if fut.done() and not fut.cancelled() and fut.exception() is None:
                total += int(fut.result().get("drained", 0))
            else:
                log.warning(
                    "edge worker %d never acked the drain", w.index
                )
                fut.cancel()
        return total

    # -------------------------------------------------------------- flushing
    def _kick_flush(self) -> None:
        """Coalesce up to ``flush_interval`` of outbound messages into ONE
        write per worker (see the knob's comment: per-frame writes cost
        the parent half its upstream throughput in wake-up preemption)."""
        if self._flush_scheduled:
            return
        self._flush_scheduled = True
        asyncio.get_event_loop().call_later(self.flush_interval, self._flush_all)

    def _flush_all(self) -> None:
        self._flush_scheduled = False
        for w in self._workers:
            w.flush()

    # -------------------------------------------------------------- keys
    def _key_id_for(self, key_str: str, spec: tuple) -> int:
        kid = self._key_ids.get(key_str)
        if kid is None:
            kid = self._key_ids[key_str] = len(self._key_ids)
            self._key_specs[key_str] = spec
            for w in self._workers:
                w.send_json(b"K", {"id": kid, "key": key_str})
        return kid

    # -------------------------------------------------------------- sim
    async def add_sim_sessions(
        self, worker: int, counts: Dict[Any, int], acquire: bool = True
    ) -> int:
        """Register simulated sessions on one worker: ``counts`` maps a
        key spec ``(method, *args)`` to how many sessions subscribe it
        there. With ``acquire`` the parent pins the upstream subs (the
        node must keep watching these keys while the worker serves
        them). Returns the number of (session, key) subscriptions
        added."""
        w = self._workers[worker]
        specs = list(counts.keys())
        if acquire:
            key_strs = self.node.acquire_keys(specs)
            self._sim_acquired.extend(key_strs)
        else:
            key_strs = [self.node.key_str(s) for s in specs]
        payload: Dict[str, int] = {}
        total = 0
        for spec, ks in zip(specs, key_strs):
            kid = self._key_id_for(ks, tuple(spec))
            n = int(counts[spec])
            payload[str(kid)] = n
            w.interest.add(kid)
            w.sim_keys.add(kid)
            total += n
        w.sim_sessions += total
        w.send_json(b"S", {"sessions": payload})
        self._flush_all()
        if w.writer is not None:
            await w.writer.drain()
        return total

    # -------------------------------------------------------------- real SSE
    async def listen(self, host: str = "127.0.0.1", port: int = 0,
                     heartbeat_interval: float = 15.0) -> int:
        """Start the SSE surface on the configured accept plane.

        ``send_fds`` (default): the PARENT binds one listener, reads each
        accepted connection's request head, routes by the resume token's
        worker ordinal (tokenless conns round-robin) and hands the fd to
        that worker — resume tokens are portable across the whole pool.
        ``reuseport``: every worker binds the same (host, port) with
        SO_REUSEPORT and the kernel places connections (PR 10's shape).
        Returns the bound port."""
        loop = asyncio.get_event_loop()
        if self.accept_plane == "send_fds":
            for w in self._workers:
                w.send_json(b"G", {"heartbeat": heartbeat_interval,
                                   "resume_ttl": self.resume_ttl})
            self._flush_all()
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((host, port))
            sock.listen(256)
            sock.setblocking(False)
            self._listen_sock = sock
            self._accept_task = loop.create_task(self._accept_loop(sock))
            self.listen_port = sock.getsockname()[1]
            return self.listen_port
        first = self._workers[0]
        first.port_future = loop.create_future()
        first.send_json(b"L", {"host": host, "port": port,
                               "heartbeat": heartbeat_interval,
                               "resume_ttl": self.resume_ttl})
        self._flush_all()
        bound = await asyncio.wait_for(first.port_future, self.stats_timeout)
        for w in self._workers[1:]:
            w.port_future = loop.create_future()
            w.send_json(b"L", {"host": host, "port": bound,
                               "heartbeat": heartbeat_interval,
                               "resume_ttl": self.resume_ttl})
            self._flush_all()
            await asyncio.wait_for(w.port_future, self.stats_timeout)
        self.listen_port = bound
        return bound

    async def _accept_loop(self, sock: socket.socket) -> None:
        """The send_fds plane's parent accept loop: accept, then route
        each connection in its own task — a slow client reading its head
        never delays the next accept."""
        loop = asyncio.get_event_loop()
        try:
            while True:
                try:
                    conn, _addr = await loop.sock_accept(sock)
                except OSError:
                    return  # listener closed
                task = loop.create_task(self._route_conn(conn))
                self._route_tasks.add(task)
                task.add_done_callback(self._route_tasks.discard)
        except asyncio.CancelledError:
            raise

    async def _route_conn(self, conn: socket.socket) -> None:
        """Read one accepted connection's request head (bounded), admit
        or shed it (the node's AdmissionController — tenant from the head,
        reconnects on the resume lane), pick the worker — the resume
        token's minted ordinal when present, else round-robin — and hand
        the fd + head over ``socket.send_fds``. The worker receives a
        DUPLICATE fd; the parent's copy closes either way, so a handoff
        failure costs the client one ANSWERED 503 (never a hung socket —
        ISSUE 12 satellite: a dropped handoff is pressure, not a silent
        failure)."""
        loop = asyncio.get_event_loop()
        try:
            conn.setblocking(False)
            head = b""
            # 64 KB cap = the reuseport path's StreamReader limit: a key
            # list that fits max_keys_per_session in the URL must route
            # the same on both planes
            while b"\r\n\r\n" not in head and len(head) < 65536:
                chunk = await asyncio.wait_for(loop.sock_recv(conn, 8192), 10.0)
                if not chunk:
                    return
                head += chunk
            if b"\r\n\r\n" not in head:
                self.route_errors += 1  # oversized/garbage head: drop, counted
                return
            token, tenant = self._extract_route_info(head)
            index, by_token = self._route_index(token)
            admission = getattr(self.node, "admission", None)
            if admission is not None:
                # the resume lane is only for tokens this parent KNOWS a
                # worker parked (disconnect messages report them): a
                # forged/expired ?resume= is a cold attach and must ride
                # the cold lane's buckets, pressure shed and ceiling —
                # the token shape alone is guessable and proves nothing
                decision = admission.admit(
                    tenant_id=tenant,
                    lane="resume" if self._token_parked(token) else None,
                )
                if not decision.admitted:
                    self.shed_conns += 1
                    self.node._note_shed_event(
                        decision.reason, lane=decision.lane
                    )
                    await self._answer_reject(
                        conn, decision.reason, decision.retry_after
                    )
                    return
            w = self._workers[index]
            if w.fd_sock is None:
                # the owner's fd channel died (torn handoff): fail over
                # to any live sibling — the resume token misses there and
                # the session fresh-attaches, the documented fallback
                by_token = False
                for offset in range(1, self.n_workers):
                    sibling = self._workers[(index + offset) % self.n_workers]
                    if sibling.fd_sock is not None:
                        w = sibling
                        break
                else:
                    # every delivery worker's channel is gone: shed with
                    # an answer + Retry-After, count it as pipe pressure
                    self.route_errors += 1
                    self._note_drop()
                    self.node.count_shed("worker_pipe_drop")
                    await self._answer_reject(conn, "worker_unavailable", None)
                    return
            payload = json.dumps(
                {"head": base64.b64encode(head).decode()}
            ).encode()
            framed = struct.pack("!I", len(payload)) + payload
            try:
                await self._send_handoff(w, framed, conn.fileno())
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — wedged worker / torn channel
                # the PR 11 dropped-handoff path: the client used to get a
                # closed-without-answer socket; now the PARENT answers 503
                # with Retry-After, the drop feeds the admission pressure
                # signal, and the count is never silent
                self.route_errors += 1
                self._note_drop()
                self.node.count_shed("worker_pipe_drop")
                log.exception(
                    "edge accept plane: fd handoff to worker %d dropped",
                    w.index,
                )
                await self._answer_reject(conn, "worker_pipe_drop", None)
                return
            self.routed_conns += 1
            if by_token:
                self.routed_by_token += 1
                # one shot: the worker consumes the park on resume, so a
                # replayed token is a cold attach from here on
                self._parked_tokens.pop(token, None)
        except (asyncio.TimeoutError, asyncio.CancelledError):
            pass
        except Exception:  # noqa: BLE001 — one conn must not kill the plane
            self.route_errors += 1
            log.exception("edge accept plane: routing a connection failed")
        finally:
            try:
                conn.close()
            except OSError:
                pass

    async def _answer_reject(
        self, conn: socket.socket, reason: str, retry_after: Optional[float],
    ) -> None:
        """Best-effort 503 on a raw accepted socket — the SAME responder
        bytes (headers, Retry-After, Connection: close) as the SSE
        server's unified rejection path, so a client cannot tell which
        plane shed it."""
        from .admission import rejection_bytes

        data = rejection_bytes(
            "503 Service Unavailable",
            {"error": {"type": "AdmissionRejected", "reason": reason,
                       "retry_after": retry_after}},
            retry_after if retry_after is not None else 1.0,
        )
        try:
            await asyncio.wait_for(
                asyncio.get_event_loop().sock_sendall(conn, data), 2.0
            )
        except Exception:  # noqa: BLE001 — the peer is gone; count stands
            pass

    async def _send_handoff(self, w: _Worker, framed: bytes, fd: int,
                            timeout: float = 10.0) -> None:
        """One fd handoff over the NON-blocking channel: per-worker
        lock-serialized (a partially-sent frame must never interleave
        with a sibling's), waiting out transient backpressure and giving
        up — counted by the caller's error path — after ``timeout``
        rather than ever blocking the parent's event loop on a wedged
        worker."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        async with w.fd_lock:
            while True:
                try:
                    sent = socket.send_fds(w.fd_sock, [framed], [fd])
                    break
                except (BlockingIOError, InterruptedError):
                    if loop.time() > deadline:
                        raise TimeoutError(
                            f"worker {w.index} fd channel backpressured"
                        )
                    await self._wait_writable(w.fd_sock, 0.25)
            if sent < len(framed):
                # the fd rode the first sendmsg's ancillary data; finish
                # the frame bytes (still under the lock). A MID-FRAME
                # failure leaves a torn length-prefixed frame on the wire
                # — every later handoff would desync and mispair fds — so
                # the channel dies with it: routing fails over to live
                # siblings (counted; a token miss is a fresh attach).
                try:
                    await asyncio.wait_for(
                        loop.sock_sendall(w.fd_sock, framed[sent:]),
                        max(0.1, deadline - loop.time()),
                    )
                except BaseException:
                    sock, w.fd_sock = w.fd_sock, None
                    try:
                        sock.close()
                    except OSError:
                        pass
                    raise

    def _token_parked(self, token: Optional[str]) -> bool:
        """Is this a token a worker reported parked (and unexpired)?
        Amortized prune, the gateway's sweep shape."""
        if token is None or not self._parked_tokens:
            return False
        now = time.monotonic()
        if now >= self._next_token_prune:
            self._next_token_prune = now + max(1.0, self.resume_ttl / 4)
            self._parked_tokens = {
                t: dl for t, dl in self._parked_tokens.items() if dl >= now
            }
        deadline = self._parked_tokens.get(token)
        return deadline is not None and deadline >= now

    @staticmethod
    async def _wait_writable(sock: socket.socket, timeout: float) -> None:
        loop = asyncio.get_event_loop()
        future = loop.create_future()
        fd = sock.fileno()

        def _on_writable() -> None:
            if not future.done():
                future.set_result(None)

        loop.add_writer(fd, _on_writable)
        try:
            await asyncio.wait_for(future, timeout)
        except (asyncio.TimeoutError, TimeoutError):
            pass  # the caller's deadline decides when to give up
        finally:
            loop.remove_writer(fd)

    @staticmethod
    def _extract_route_info(head: bytes):
        """ONE pass over the request head for the accept plane's two
        identities: the resume token (``resume=`` / ``Last-Event-ID``,
        the routing AND lane identity) and the tenant id (``tenant=`` /
        ``X-Tenant`` — the SAME wire contract as EdgeHttpServer's
        admission hop). Returns ``(token, tenant)``."""
        from urllib.parse import unquote

        token = None
        tenant = ""
        request_line, _, rest = head.partition(b"\r\n")
        parts = request_line.decode("latin-1", "replace").split(" ")
        if len(parts) >= 2:
            _path, _, query = parts[1].partition("?")
            for pair in query.split("&"):
                k, _, v = pair.partition("=")
                if k == "resume" and v and token is None:
                    token = unquote(v)
                elif k == "tenant" and v and not tenant:
                    tenant = unquote(v)
        if token is None or not tenant:
            for line in rest.split(b"\r\n"):
                low = line.lower()
                if token is None and low.startswith(b"last-event-id:"):
                    token = line.split(b":", 1)[1].strip().decode("latin-1")
                elif not tenant and low.startswith(b"x-tenant:"):
                    tenant = line.split(b":", 1)[1].strip().decode("latin-1")
        return token, tenant

    def _route_index(self, token: Optional[str]):
        """(worker index, routed-by-token) for one extracted token. The
        token's ``es-w<N>-`` prefix names the worker that minted (and
        parked) it; anything else round-robins."""
        if token is not None and token.startswith("es-w"):
            ordinal, _, _tail = token[4:].partition("-")
            if ordinal.isdigit():
                index = int(ordinal)
                if index < self.n_workers:
                    return index, True
        index = self._accept_rr % self.n_workers
        self._accept_rr += 1
        return index, False

    # -------------------------------------------------------------- frames
    def _on_frame(self, key_str: str, frame, encoded) -> None:
        """EdgeNode broadcast hook: ship the SHARED encoded body to every
        worker with sessions on this key — the message bytes are built
        once and written to W pipes, never per session."""
        kid = self._key_ids.get(key_str)
        if kid is None:
            return
        t0 = frame[4] if frame[4] is not None else -1.0
        payload = _FRAME.pack(kid, frame[1], t0) + encoded.body
        msg = _HEADER.pack(ord("F"), len(payload)) + payload
        for w in self._workers:
            if kid in w.interest:
                w.outbuf.append(msg)
        self._kick_flush()

    # -------------------------------------------------------------- stats
    async def stats(self) -> List[dict]:
        """Pull per-worker stats; merges the workers' delivery-histogram
        DELTAS into the process ``fusion_edge_delivery_ms`` histogram and
        refreshes :attr:`deliveries_seen` + each worker's
        ``last_stats`` (what ``/edge/stats`` embeds)."""
        loop = asyncio.get_event_loop()
        self._stats_seq += 1
        seq = self._stats_seq
        futures = []
        for w in self._workers:
            fut = loop.create_future()
            w.stats_futures[seq] = fut
            w.send_json(b"Q", {"seq": seq})
            futures.append(fut)
        self._flush_all()
        replies = await asyncio.wait_for(
            asyncio.gather(*futures), self.stats_timeout
        )
        from ..diagnostics.metrics import global_metrics

        hist = global_metrics().histogram(
            "fusion_edge_delivery_ms",
            help="server fence (wave apply) -> edge session client-visible",
        )
        total = 0
        for w, stats in zip(self._workers, replies):
            w.last_stats = stats
            total += int(stats.get("deliveries", 0))
            buckets = stats.get("hist") or []
            prev = w.last_hist or [0] * len(buckets)
            for i, count in enumerate(buckets):
                delta = count - (prev[i] if i < len(prev) else 0)
                if delta <= 0:
                    continue
                # the bucket's upper edge re-buckets to the same slot in
                # the registry histogram (mirrored edges)
                if i < len(self._hist_edges):
                    hist.record_many(self._hist_edges[i], delta)
                else:
                    hist.record_many(self._hist_edges[-1] * 2.0, delta)
            w.last_hist = list(buckets)
        self.deliveries_seen = total
        return replies

    def snapshot(self) -> dict:
        """Sync view for ``EdgeNode.snapshot()`` — the last pulled
        per-worker stats (call :meth:`stats` to refresh)."""
        return {
            "workers": self.n_workers,
            "listen_port": self.listen_port,
            "accept_plane": self.accept_plane,
            "routed_conns": self.routed_conns,
            "routed_by_token": self.routed_by_token,
            "route_errors": self.route_errors,
            "shed_conns": self.shed_conns,
            "pipe_pressure": round(self._pipe_pressure(), 4),
            "deliveries": self.deliveries_seen,
            "per_worker": [w.last_stats for w in self._workers],
        }

    # -------------------------------------------------------------- inbound
    async def _read_worker(self, w: _Worker) -> None:
        try:
            while True:
                head = await w.reader.readexactly(_HEADER.size)
                mtype, length = _HEADER.unpack(head)
                payload = await w.reader.readexactly(length) if length else b""
                ch = chr(mtype)
                if ch == "R":
                    stats = json.loads(payload)
                    fut = w.stats_futures.pop(stats.get("seq", 0), None)
                    if fut is not None and not fut.done():
                        fut.set_result(stats)
                elif ch == "P":
                    info = json.loads(payload)
                    if w.port_future is not None and not w.port_future.done():
                        if "error" in info:
                            w.port_future.set_exception(
                                RuntimeError(info["error"])
                            )
                        else:
                            w.port_future.set_result(info["port"])
                elif ch == "U":
                    self._handle_subscribe(w, json.loads(payload))
                elif ch == "D":
                    self._handle_disconnect(w, json.loads(payload))
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass  # worker exited
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — reader must not die silently
            log.exception("edge worker %d reader failed", w.index)

    def _handle_subscribe(self, w: _Worker, req: dict) -> None:
        """A worker's real SSE connection asked for keys: acquire the
        upstream subs, assign key ids, ack with the ids + the current
        cached frames (the attach replay, base64 over the control
        channel)."""
        conn = req.get("conn")
        admission = getattr(self.node, "admission", None)
        specs = [tuple(k) for k in req.get("keys", [])]
        if admission is not None and not req.get("resumed") and specs:
            # the per-tenant subscribe-rate debit this plane DEFERRED at
            # the accept hop (the key specs were not readable there):
            # same bucket as the SSE plane; resumed sessions replay and
            # are exempt. Counted ONCE (admit_keys moved the per-reason
            # counter — this must NOT fall into the bad_request path
            # below, which would double-count the one rejection under
            # two reasons); the worker answers the unified 503 shape.
            verdict = admission.admit_keys(
                tenant_id=req.get("tenant") or "", keys=len(specs)
            )
            if not verdict.admitted:
                self.node._note_shed_event(verdict.reason)
                w.send_json(b"A", {
                    "conn": conn,
                    "error": f"admission rejected ({verdict.reason})",
                    "status": 503,
                    "retry_after": verdict.retry_after,
                })
                self._kick_flush()
                return
        try:
            if not specs:
                raise ValueError("no keys")
            if len(specs) > self.node.max_keys_per_session:
                raise ValueError(
                    f"session asks for {len(specs)} keys; this edge caps "
                    f"at {self.node.max_keys_per_session} per session"
                )
            key_strs = self.node.acquire_keys(specs)
        except Exception as e:  # noqa: BLE001 — the CLIENT's bad input
            # counted on the SAME shed taxonomy as the SSE plane's 400s
            # (the worker answers the HTTP 400; the parent owns the count)
            self.node.count_shed("bad_request")
            w.send_json(b"A", {"conn": conn, "error": str(e)})
            self._kick_flush()
            return
        keys_out = []
        replays = []
        kids = []
        for spec, ks in zip(specs, key_strs):
            kid = self._key_id_for(ks, spec)
            w.interest.add(kid)
            w.conn_refs[kid] = w.conn_refs.get(kid, 0) + 1
            kids.append(kid)
            keys_out.append({"id": kid, "key": ks})
            sub = self.node._subs.get(ks)
            if sub is not None and sub.last_frame is not None:
                # replayed frames ship WITHOUT the stale origin_ts — same
                # contract as EdgeNode._deliver_contained (the encode
                # cache keeps the stripped twin beside the canonical)
                lf = sub.last_frame
                if lf[4] is not None:
                    lf = (lf[0], lf[1], lf[2], lf[3], None, lf[5])
                encoded = self.node.encode_frame(lf)
                replays.append({
                    "id": kid,
                    "ver": encoded.version,
                    "body": base64.b64encode(encoded.body).decode(),
                })
        self._conn_keys[(w.index, conn)] = (key_strs, kids)
        w.send_json(b"A", {"conn": conn, "keys": keys_out, "replay": replays})
        self._kick_flush()

    def _handle_disconnect(self, w: _Worker, req: dict) -> None:
        token = req.get("token")
        if token:
            # the worker parked this session's versions under its token:
            # a reconnect carrying it is a GENUINE resume — eligible for
            # the reserved lane at the accept hop
            self._parked_tokens[token] = time.monotonic() + self.resume_ttl
        entry = self._conn_keys.pop((w.index, req.get("conn")), None)
        if entry is None:
            return
        key_strs, kids = entry
        self.node.release_keys(key_strs)
        for kid in kids:
            left = w.conn_refs.get(kid, 0) - 1
            if left > 0:
                w.conn_refs[kid] = left
            else:
                # last real connection for this key on this worker: stop
                # shipping its frames there (sim populations keep theirs)
                w.conn_refs.pop(kid, None)
                if kid not in w.sim_keys:
                    w.interest.discard(kid)


# ======================================================================
# worker side (stdlib only — this file runs as a standalone script)
# ======================================================================


class _WorkerHist:
    """The worker's delivery histogram: same log-scale buckets as the
    parent registry's Histogram so counts merge bucket-for-bucket."""

    def __init__(self):
        self.edges = _hist_edges()
        self.buckets = [0] * (len(self.edges) + 1)
        self.count = 0
        self.sum = 0.0
        self.max = 0.0

    def record_many(self, value: float, n: int) -> None:
        if n <= 0:
            return
        v = max(0.0, float(value))
        self.buckets[_bisect_left(self.edges, v)] += n
        self.count += n
        self.sum += v * n
        if v > self.max:
            self.max = v


class _WorkerMain:
    """One delivery worker: control-channel loop + local session tables +
    (optionally) the SO_REUSEPORT SSE listener."""

    def __init__(self, index: int, fd: int, fd_channel: Optional[int] = None):
        self.index = index
        sock = socket.socket(fileno=fd)
        sock.setblocking(False)
        self.sock = sock
        #: the send_fds handoff channel (accepted-connection fds + their
        #: pre-read request heads arrive here, outside the framed stream)
        self.fd_sock: Optional[socket.socket] = None
        if fd_channel is not None:
            self.fd_sock = socket.socket(fileno=fd_channel)
            self.fd_sock.setblocking(False)
        self._fd_buf = b""
        self._fd_pending: list = []  # fds awaiting their framed head
        #: in-flight handoff serving tasks — retained (the loop holds
        #: tasks weakly; an unreferenced one can vanish mid-accept) and
        #: cancelled at teardown so a dying worker can't leak half-served
        #: connections (fusionlint FL003). Stdlib-only: no TaskSet import
        #: here — workers run as `python <this file> --worker`.
        self._handoff_tasks: set = set()
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.keys: Dict[int, str] = {}
        #: key_id -> list of per-session SSE id-prefix bytes (the sim
        #: population: everything per-session the delivery pays for)
        self.sim: Dict[int, List[bytes]] = {}
        #: key_id -> set of live real connections
        self.conns_by_key: Dict[int, set] = {}
        self.conn_seq = 0
        self.pending_subscribes: Dict[int, asyncio.Future] = {}
        #: conn_id -> not-yet-open _SseConn: registered into conns_by_key
        #: by the CONTROL LOOP the moment the subscribe ack arrives, so a
        #: frame in the same pipe batch as the ack lands in the conn's
        #: backlog instead of being dropped before the handler resumes
        self.pending_conns: Dict[int, "_SseConn"] = {}
        self.deliveries = 0
        self.delivery_bytes = 0
        self.busy_ms = 0.0
        self.frames = 0
        self.evictions = 0
        self.connections = 0
        self.hist = _WorkerHist()
        self.heartbeat_interval = 15.0
        self.resume_ttl = 60.0
        #: token -> ({kid: delivered version}, deadline) — what a resumed
        #: connection replays AGAINST (only newer versions ship). Under
        #: the send_fds plane the parent routes a token back HERE, so the
        #: park is reachable from any listener port.
        self.parked: Dict[str, tuple] = {}
        self.resumes = 0
        self.server: Optional[asyncio.AbstractServer] = None
        self._sim_minted = 0
        #: write-buffer bound per real connection: a peer that stops
        #: reading past this is evicted (aborted), never blocks siblings
        self.max_buffer = 1 << 20

    # ---------------------------------------------------------- control
    def send(self, mtype: str, payload: bytes) -> None:
        self.writer.write(_HEADER.pack(ord(mtype), len(payload)) + payload)

    def send_json(self, mtype: str, obj: Any) -> None:
        self.send(mtype, json.dumps(obj).encode())

    async def run(self) -> None:
        self.reader, self.writer = await asyncio.open_connection(sock=self.sock)
        if self.fd_sock is not None:
            asyncio.get_event_loop().add_reader(
                self.fd_sock.fileno(), self._on_fd_readable
            )
        try:
            while True:
                head = await self.reader.readexactly(_HEADER.size)
                mtype, length = _HEADER.unpack(head)
                payload = (
                    await self.reader.readexactly(length) if length else b""
                )
                ch = chr(mtype)
                if ch == "F":
                    self.on_frame(payload)
                elif ch == "K":
                    info = json.loads(payload)
                    self.keys[int(info["id"])] = info["key"]
                elif ch == "S":
                    self.on_sim(json.loads(payload))
                elif ch == "A":
                    self.on_subscribe_ack(json.loads(payload))
                elif ch == "L":
                    await self.on_listen(json.loads(payload))
                elif ch == "G":
                    cfg = json.loads(payload)
                    self.heartbeat_interval = float(cfg.get("heartbeat", 15.0))
                    self.resume_ttl = float(cfg.get("resume_ttl", 60.0))
                elif ch == "Q":
                    self.on_stats(json.loads(payload))
                elif ch == "Y":
                    self.on_drain(json.loads(payload))
                elif ch == "X":
                    break
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass  # parent died: exit
        finally:
            if self.fd_sock is not None:
                try:
                    asyncio.get_event_loop().remove_reader(self.fd_sock.fileno())
                except (OSError, RuntimeError):
                    pass
            for task in list(self._handoff_tasks):
                if not task.done():
                    task.cancel()
            if self.server is not None:
                self.server.close()

    # ---------------------------------------------------------- fd handoff
    def _on_fd_readable(self) -> None:
        """The send_fds accept plane's inbound side: each parent sendmsg
        carries one ``!I``-framed {head} JSON + the connection fd as
        ancillary data. Linux delivers ancillary data as a read barrier,
        so fds pair with their frames FIFO even under coalesced reads."""
        try:
            msg, fds, _flags, _addr = socket.recv_fds(self.fd_sock, 65536, 8)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            try:
                asyncio.get_event_loop().remove_reader(self.fd_sock.fileno())
            except (OSError, RuntimeError):
                pass
            return
        if not msg and not fds:
            try:  # parent closed the channel
                asyncio.get_event_loop().remove_reader(self.fd_sock.fileno())
            except (OSError, RuntimeError):
                pass
            return
        self._fd_buf += msg
        self._fd_pending.extend(fds)
        while len(self._fd_buf) >= 4:
            (length,) = struct.unpack_from("!I", self._fd_buf)
            if len(self._fd_buf) < 4 + length:
                break
            payload = self._fd_buf[4: 4 + length]
            self._fd_buf = self._fd_buf[4 + length:]
            if not self._fd_pending:
                continue  # frame without its fd (handoff raced a close)
            fd = self._fd_pending.pop(0)
            try:
                info = json.loads(payload)
                head = base64.b64decode(info["head"])
                conn_sock = socket.socket(fileno=fd)
                conn_sock.setblocking(False)
            except Exception:  # noqa: BLE001 — drop the broken handoff
                try:
                    os.close(fd)
                except OSError:
                    pass
                continue
            task = asyncio.get_event_loop().create_task(
                self._handle_handoff(conn_sock, head)
            )
            self._handoff_tasks.add(task)
            task.add_done_callback(self._handoff_tasks.discard)

    async def _handle_handoff(self, conn_sock: socket.socket, head: bytes) -> None:
        try:
            reader, writer = await asyncio.open_connection(sock=conn_sock)
        except Exception:  # noqa: BLE001 — peer vanished during handoff
            try:
                conn_sock.close()
            except OSError:
                pass
            return
        await self._serve_conn(reader, writer, head)

    # ---------------------------------------------------------- sim
    def on_sim(self, req: dict) -> None:
        for kid_str, count in req.get("sessions", {}).items():
            kid = int(kid_str)
            lst = self.sim.setdefault(kid, [])
            for _ in range(int(count)):
                self._sim_minted += 1
                lst.append(
                    f"id: es-w{self.index}-{self._sim_minted}\n".encode()
                )

    # ---------------------------------------------------------- frames
    def on_frame(self, payload: bytes) -> None:
        kid, version, t0 = _FRAME.unpack_from(payload)
        body = payload[_FRAME.size:]
        # the shared tail is assembled ONCE per (worker, frame); each
        # session pays only its envelope prefix + the concat/write
        tail = b"event: update\ndata: " + body + b"\n\n"
        t_start = time.perf_counter()
        n = 0
        nbytes = 0
        prefixes = self.sim.get(kid)
        if prefixes:
            for prefix in prefixes:
                chunk = prefix + tail  # the per-session delivery assembly
                nbytes += len(chunk)
            n += len(prefixes)
        conns = self.conns_by_key.get(kid)
        if conns:
            dead = None
            for conn in conns:
                if conn.deliver(kid, version, tail):
                    n += 1
                    nbytes += len(conn.prefix) + len(tail)
                else:
                    dead = dead or []
                    dead.append(conn)
            for conn in dead or ():
                conn.abort()
                self.evictions += 1
        now = time.perf_counter()
        self.deliveries += n
        self.delivery_bytes += nbytes
        self.frames += 1
        self.busy_ms += (now - t_start) * 1e3
        if t0 >= 0.0 and n:
            # perf_counter is CLOCK_MONOTONIC — one timeline across the
            # processes of one host, so fence -> worker-visible is real
            self.hist.record_many((now - t0) * 1e3, n)

    # ---------------------------------------------------------- stats
    def on_stats(self, req: dict) -> None:
        rss = 0.0
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        rss = int(line.split()[1]) / 1024.0
                        break
        except OSError:
            pass
        self.send_json("R", {
            "seq": req.get("seq", 0),
            "worker": self.index,
            "pid": os.getpid(),
            "deliveries": self.deliveries,
            "delivery_bytes": self.delivery_bytes,
            "frames": self.frames,
            "busy_ms": round(self.busy_ms, 3),
            "rss_mb": round(rss, 1),
            "sim_sessions": sum(len(v) for v in self.sim.values()),
            "connections": self.connections,
            "evictions": self.evictions,
            "resumes": self.resumes,
            "parked": len(self.parked),
            "hist": self.hist.buckets,
            "hist_count": self.hist.count,
            "hist_sum": round(self.hist.sum, 3),
            "hist_max": round(self.hist.max, 3),
        })

    # ---------------------------------------------------------- drain
    def on_drain(self, req: dict) -> None:
        """Graceful drain (ISSUE 12c, the worker half): stop accepting,
        write every live SSE connection ONE ``event: reconnect`` hint
        (the session's resume token rides both the ``id:`` line and the
        data payload) and CLOSE the stream cleanly — the handler's
        teardown parks the delivered-version map under the token, so a
        reconnect to this worker resumes, and a reconnect to a RESTARTED
        pool misses the park and fresh-attaches at the current values
        (latest-wins: still zero deliveries lost)."""
        if self.server is not None:
            self.server.close()
            self.server = None
        conns = set()
        for peers in self.conns_by_key.values():
            conns.update(peers)
        conns.update(self.pending_conns.values())
        drained = 0
        for conn in conns:
            token = conn.prefix[4:-1].decode("latin-1")
            try:
                if conn.open:
                    hint = json.dumps({
                        "key": "$edge/drain", "ver": 0,
                        "value": {"resume": token},
                        "cause": f"drain:worker-{self.index}",
                    }).encode()
                    conn.writer.write(
                        conn.prefix + b"event: reconnect\ndata: " + hint
                        + b"\n\n"
                    )
                    drained += 1
                else:
                    # mid-attach (headers not yet written): answer the
                    # unified 503 shape instead of a status-less closed
                    # socket; NOT counted as drained — it never streamed
                    conn.writer.write(_DRAIN_503)
                conn.writer.close()  # graceful: flushes the hint; the
                # handler's finally parks versions + pairs the D
            except Exception:  # noqa: BLE001 — a dying peer mid-drain
                pass
        self.send_json("R", {"seq": req.get("seq", 0),
                             "worker": self.index, "drained": drained})

    # ---------------------------------------------------------- real SSE
    async def on_listen(self, req: dict) -> None:
        try:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            sock.bind((req.get("host", "127.0.0.1"), int(req.get("port", 0))))
            sock.listen(128)
            self.heartbeat_interval = float(req.get("heartbeat", 15.0))
            self.resume_ttl = float(req.get("resume_ttl", 60.0))
            self.server = await asyncio.start_server(self._handle_conn, sock=sock)
            self.send_json("P", {"port": sock.getsockname()[1]})
        except Exception as e:  # noqa: BLE001 — report, don't die
            self.send_json("P", {"error": f"{type(e).__name__}: {e}"})

    def on_subscribe_ack(self, ack: dict) -> None:
        conn_id = ack.get("conn")
        fut = self.pending_subscribes.pop(conn_id, None)
        if "error" not in ack:
            # register in the CONTROL LOOP, synchronously: any frame the
            # parent fanned right after the ack (possibly in the same
            # coalesced pipe write) must find the conn and backlog, not
            # vanish before the handler task resumes
            conn = self.pending_conns.get(conn_id)
            if conn is not None:
                conn.key_ids = [k["id"] for k in ack.get("keys", [])]
                for kid in conn.key_ids:
                    self.conns_by_key.setdefault(kid, set()).add(conn)
        if fut is not None and not fut.done():
            fut.set_result(ack)

    async def _handle_conn(self, reader, writer) -> None:
        """REUSEPORT-plane entry: read the head here, then serve. (The
        send_fds plane arrives through ``_handle_handoff`` with the head
        the PARENT already read off the socket.)"""
        try:
            request = await asyncio.wait_for(
                reader.readuntil(b"\r\n\r\n"), 30.0
            )
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                ConnectionResetError, asyncio.LimitOverrunError):
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass
            return
        await self._serve_conn(reader, writer, request)

    def _sweep_parked(self) -> None:
        now = time.monotonic()
        expired = [t for t, (_v, dl) in self.parked.items() if dl < now]
        for t in expired:
            self.parked.pop(t, None)

    async def _serve_conn(self, reader, writer, request: bytes) -> None:
        conn_id = self.conn_seq = self.conn_seq + 1
        self.connections += 1
        conn = None
        sent_u = False
        token = None
        try:
            line = request.split(b"\r\n", 1)[0].decode("latin-1")
            parts = line.split(" ")
            if len(parts) < 2 or parts[0] != "GET":
                writer.write(b"HTTP/1.1 405 Method Not Allowed\r\n\r\n")
                return
            target = parts[1]
            path, _, query = target.partition("?")
            if path != "/edge/sse":
                writer.write(b"HTTP/1.1 404 Not Found\r\n\r\n")
                return
            keys_raw = ""
            resume_token = None
            tenant = ""
            for pair in query.split("&"):
                k, _, v = pair.partition("=")
                if k == "keys":
                    from urllib.parse import unquote

                    keys_raw = unquote(v)
                elif k == "resume" and v:
                    from urllib.parse import unquote

                    resume_token = unquote(v)
                elif k == "tenant" and v:
                    from urllib.parse import unquote

                    tenant = unquote(v)
            if not tenant:
                for hline in request.split(b"\r\n")[1:]:
                    if hline.lower().startswith(b"x-tenant:"):
                        tenant = (
                            hline.split(b":", 1)[1].strip().decode("latin-1")
                        )
                        break
            if resume_token is None:
                # the browser's own reconnect handle (EventSource re-sends
                # the original URL + this header)
                for hline in request.split(b"\r\n")[1:]:
                    if hline.lower().startswith(b"last-event-id:"):
                        resume_token = (
                            hline.split(b":", 1)[1].strip().decode("latin-1")
                        )
                        break
            try:
                specs = json.loads(keys_raw) if keys_raw else []
                assert isinstance(specs, list) and specs
            except Exception:  # noqa: BLE001
                writer.write(
                    b"HTTP/1.1 400 Bad Request\r\n\r\n"
                )
                return
            # resume: a token this worker parked replays only what the
            # session missed, and the session keeps its identity. Under
            # the send_fds plane the PARENT routed the token here, so a
            # reconnect through any port finds its park; a miss (expired,
            # reuseport cross-worker hash) is the documented fresh-attach
            # fallback.
            self._sweep_parked()
            parked_versions: Optional[Dict[int, int]] = None
            if resume_token is not None:
                entry = self.parked.pop(resume_token, None)
                if entry is not None and entry[1] >= time.monotonic():
                    parked_versions = entry[0]
                    token = resume_token
                    self.resumes += 1
            if token is None:
                token = f"es-w{self.index}-c{conn_id}"
            conn = _SseConn(self, conn_id, token, [], writer)
            if parked_versions:
                conn.versions.update(parked_versions)
            self.pending_conns[conn_id] = conn
            fut = asyncio.get_event_loop().create_future()
            self.pending_subscribes[conn_id] = fut
            self.send_json("U", {
                "conn": conn_id, "keys": specs, "tenant": tenant,
                # resumed sessions replay — the parent exempts them from
                # the subscribe-rate debit (they mint no new state)
                "resumed": parked_versions is not None,
            })
            sent_u = True
            ack = await asyncio.wait_for(fut, 30.0)
            if "error" in ack:
                # the parent's verdict names the status: bad input stays
                # 400, an admission shed answers the unified 503 shape
                # (Retry-After + Connection: close) — a rate-limited
                # client must not be told its request was malformed
                status = int(ack.get("status", 400))
                status_line = (
                    b"HTTP/1.1 503 Service Unavailable\r\n"
                    if status == 503
                    else b"HTTP/1.1 400 Bad Request\r\n"
                )
                body = json.dumps({"error": ack["error"]}).encode()
                head = (
                    status_line
                    + b"Content-Type: application/json\r\nContent-Length: "
                    + str(len(body)).encode()
                    + b"\r\nConnection: close"
                )
                retry = ack.get("retry_after")
                if status == 503 and isinstance(retry, (int, float)):
                    head += (
                        b"\r\nRetry-After: "
                        + str(max(1, min(3600, int(retry + 1)))).encode()
                    )
                writer.write(head + b"\r\n\r\n" + body)
                return
            writer.write(
                b"HTTP/1.1 200 OK\r\nContent-Type: text/event-stream\r\n"
                b"Cache-Control: no-cache\r\nConnection: close\r\n\r\n"
            )
            hello = json.dumps(
                {"token": token, "keys": [k["key"] for k in ack["keys"]],
                 "worker": self.index,
                 "resumed": parked_versions is not None}
            )
            writer.write(
                f"id: {token}\nevent: hello\ndata: {hello}\n\n".encode()
            )
            replayed: Dict[int, int] = dict(conn.versions)
            for rep in ack.get("replay", []):
                kid = rep["id"]
                ver = rep.get("ver", 0)
                if parked_versions is not None and ver <= conn.versions.get(kid, 0):
                    # the session already saw this version before its
                    # disconnect: latest-wins resume ships nothing
                    replayed[kid] = max(replayed.get(kid, 0), ver)
                    continue
                tail = (b"event: update\ndata: "
                        + base64.b64decode(rep["body"]) + b"\n\n")
                conn.write_frame(tail)
                conn.versions[kid] = ver
                replayed[kid] = max(replayed.get(kid, 0), ver)
                self.deliveries += 1
            # open the stream: ship backlogged frames that raced in
            # between the ack and now, skipping versions the replay
            # already covered (the control loop registered the conn at
            # ack time so nothing was dropped)
            conn.open_stream(replayed)
            hb = asyncio.get_event_loop().create_task(self._heartbeat(conn))
            try:
                while await reader.read(4096):
                    pass  # inbound ignored; the stream is one-way
            finally:
                hb.cancel()
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                ConnectionResetError, asyncio.LimitOverrunError):
            pass
        except Exception:  # noqa: BLE001 — one bad conn never kills the worker
            pass
        finally:
            self.connections -= 1
            self.pending_conns.pop(conn_id, None)
            self.pending_subscribes.pop(conn_id, None)
            if conn is not None:
                for kid in conn.key_ids:
                    peers = self.conns_by_key.get(kid)
                    if peers is not None:
                        peers.discard(conn)
                        if not peers:
                            self.conns_by_key.pop(kid, None)
                if token is not None:
                    # park the delivered-version map under the token: the
                    # resume replay source (portable across the pool under
                    # the send_fds plane — the parent routes it back here)
                    self.parked[token] = (
                        dict(conn.versions),
                        time.monotonic() + self.resume_ttl,
                    )
            if sent_u:
                # ALWAYS pair the U with a D once sent — even on an ack
                # timeout where the parent may have acquired the pins
                # after we stopped waiting (an unpaired U leaks the
                # upstream pins until pool.stop())
                self.send_json(
                    "D",
                    {"conn": conn_id,
                     "key_ids": conn.key_ids if conn is not None else [],
                     # the parked token: the parent's accept plane grants
                     # the reserved resume lane only to tokens it SAW
                     # parked (a forged token rides the cold lane)
                     "token": token if conn is not None else None},
                )
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _heartbeat(self, conn: "_SseConn") -> None:
        try:
            while True:
                await asyncio.sleep(self.heartbeat_interval)
                conn.writer.write(b": hb\n\n")
        except (asyncio.CancelledError, ConnectionResetError):
            pass


class _SseConn:
    __slots__ = ("worker", "conn_id", "prefix", "key_ids", "writer",
                 "open", "backlog", "versions")

    def __init__(self, worker, conn_id, token, key_ids, writer):
        self.worker = worker
        self.conn_id = conn_id
        self.prefix = f"id: {token}\n".encode()
        self.key_ids = key_ids
        self.writer = writer
        #: False until the handler wrote headers + hello + replay: frames
        #: arriving meanwhile (registered by the control loop at ack
        #: time) buffer in ``backlog`` instead of corrupting the HTTP
        #: preamble or being dropped
        self.open = False
        self.backlog: List[tuple] = []
        #: kid -> highest version this peer was sent — parked under the
        #: resume token at disconnect (the resume replay gate)
        self.versions: Dict[int, int] = {}

    def deliver(self, kid: int, version: int, tail: bytes) -> bool:
        if not self.open:
            self.backlog.append((kid, version, tail))
            return True
        if self.write_frame(tail):
            self.versions[kid] = version
            return True
        return False

    def open_stream(self, replayed: Dict[int, int]) -> None:
        backlog, self.backlog = self.backlog, []
        self.open = True
        for kid, version, tail in backlog:
            if version > replayed.get(kid, 0):
                if self.write_frame(tail):
                    self.versions[kid] = version

    def write_frame(self, tail: bytes) -> bool:
        """Write one shared-tail frame with this conn's envelope; False
        when the peer stopped draining (evict)."""
        transport = self.writer.transport
        if transport is None or transport.is_closing():
            return False
        if transport.get_write_buffer_size() > self.worker.max_buffer:
            return False  # slow consumer: the caller aborts us
        self.writer.write(self.prefix + tail)
        return True

    def abort(self) -> None:
        transport = self.writer.transport
        if transport is not None:
            transport.abort()


def _worker_entry(argv: List[str]) -> None:
    index = int(argv[0])
    fd = int(argv[1])
    fd_channel = int(argv[2]) if len(argv) > 2 else None
    asyncio.run(_WorkerMain(index, fd, fd_channel).run())


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--worker":
        _worker_entry(sys.argv[2:])
    else:
        sys.exit("usage: worker_pool.py --worker <index> <fd> [<fd-channel>]")
