"""Edge admission control — overload safety at the serving boundary.

ISSUE 12 tentpole (a): the reference Stl.Fusion survives overload by
bounding the work any one node accepts (bounded compute retries, pruner
backpressure — PAPER.md §L1/§2.6); this module is that discipline applied
to the edge tier's FRONT door. An :class:`AdmissionController` sits in
front of :class:`~.gateway.EdgeNode` and both transports
(:class:`~.server.EdgeHttpServer` / :class:`~.server.EdgeWebSocketServer`)
and decides, per connection/attach, one of ADMIT or SHED — before the
request has cost a watch loop, an upstream subscription or a fan-shard
slot. The pieces:

- **per-tenant token buckets** — connection-rate and subscribe-rate
  limits, resolved through the existing
  :class:`~...ext.multitenancy.TenantResolver` (default tenant in
  single-tenant deployments). One tenant's flash crowd exhausts ITS
  bucket; every other tenant's lane keeps its full rate.
- **priority lanes** — ``resume`` (reconnects replaying a resume token)
  and ``priority`` (tenants flagged ``priority=True``) are admitted ahead
  of ``anonymous`` cold attaches: the global concurrent-attach gate keeps
  reserved headroom per lane (anonymous fills at most
  ``1 - resume_reserve - priority_reserve`` of it), and pressure-shedding
  cuts the anonymous lane first. A reconnect storm after a deploy never
  queues behind a cold flash crowd.
- **global concurrent-attach gate** — bounds attach operations IN FLIGHT
  (head read → attach → replay) across every transport, with a per-tenant
  share cap so one tenant cannot occupy the whole gate.
- **pressure feedback** — downstream saturation signals (worker-pipe
  handoff drops, fan-shard queue depth — registered as pull-time sources)
  raise :meth:`pressure`; above ``shed_pressure`` the anonymous lane
  sheds, and the owning EdgeNode widens its upstream re-read batching
  window (``effective_reread_window``) so overload degrades to higher
  latency before it degrades to evictions.

Every decision is COUNTED, never silent: ``fusion_edge_admitted_total``
per lane, ``fusion_edge_shed_total`` per reason, the live pressure and
in-flight gauges. Rejections answer 503 with ``Retry-After`` (SSE) or a
clean WS error — see :func:`rejection_bytes`, the ONE responder both the
SSE server and the worker pool's parent accept plane write. Admission
applies only at the boundary: an already-admitted session is NEVER torn
down by the controller (eviction stays what it always was — a slow
consumer's own backpressure story).

A drain (:meth:`EdgeNode.drain`) flips :attr:`draining`: everything sheds
with reason ``draining`` while live sessions are hinted to reconnect
elsewhere — the rolling-deploy runbook in EDGE.md.
"""
from __future__ import annotations

import json
import logging
import math
import time
from typing import Callable, Dict, Optional

from ..diagnostics.hotkeys import global_hotkeys
from ..diagnostics.metrics import global_metrics

log = logging.getLogger("stl_fusion_tpu")

__all__ = [
    "AdmissionController",
    "AdmissionDecision",
    "AdmissionRejected",
    "rejection_bytes",
    "LANE_RESUME",
    "LANE_PRIORITY",
    "LANE_ANONYMOUS",
]

LANE_RESUME = "resume"
LANE_PRIORITY = "priority"
LANE_ANONYMOUS = "anonymous"
_LANES = (LANE_RESUME, LANE_PRIORITY, LANE_ANONYMOUS)


def rejection_bytes(
    status: str, payload: dict, retry_after: Optional[float] = None
) -> bytes:
    """The ONE HTTP rejection responder (ISSUE 12 satellite): admission
    503s, key-allowlist 400s and replay-evicted 409s all ship this shape —
    a JSON body, ``Connection: close`` (a shed connection must not be
    kept-alive into a retry loop on the same socket), and ``Retry-After``
    when the shed is retryable. Shared by the SSE server and the worker
    pool's parent accept plane, so the two planes' rejections cannot
    drift."""
    body = json.dumps(payload, separators=(",", ":")).encode()
    head = [
        f"HTTP/1.1 {status}",
        "Content-Type: application/json",
        f"Content-Length: {len(body)}",
        "Cache-Control: no-cache",
        "Connection: close",
    ]
    if retry_after is not None and math.isfinite(retry_after):
        # a non-finite retry (a zero-rate bucket's honest "never") must
        # not turn the answered 503 into an OverflowError-dropped socket;
        # the header is simply omitted and the client treats it as opaque
        head.append(
            f"Retry-After: {max(1, min(3600, int(math.ceil(retry_after))))}"
        )
    return ("\r\n".join(head) + "\r\n\r\n").encode() + body


class TokenBucket:
    """Deterministic token bucket: ``rate`` tokens/second up to ``burst``
    capacity, refilled lazily from an injectable monotonic ``clock`` (the
    tests drive a fake clock — no sleeps, no flakes)."""

    __slots__ = ("rate", "burst", "tokens", "_last", "clock")

    def __init__(self, rate: float, burst: float, clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.clock = clock
        self._last = clock()

    def _refill(self) -> None:
        now = self.clock()
        elapsed = now - self._last
        if elapsed > 0:
            self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        self._last = now

    def try_take(self, n: float = 1.0) -> bool:
        self._refill()
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def retry_after(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will be available (0 when they
        already are) — the honest ``Retry-After`` a shed client gets."""
        self._refill()
        missing = n - self.tokens
        if missing <= 0:
            return 0.0
        if self.rate <= 0:
            return float("inf")
        return missing / self.rate


class AdmissionDecision:
    """One admit/shed verdict. Truthy iff admitted. A ``hold=True``
    admission occupies a gate slot until :meth:`AdmissionController.release`
    (the transports hold across head-read → attach → replay); ``hold=False``
    checks the gate against current holds without occupying it (the
    synchronous in-process attach path)."""

    __slots__ = ("admitted", "lane", "tenant_id", "reason", "retry_after", "_held")

    def __init__(self, admitted, lane, tenant_id, reason=None, retry_after=None):
        self.admitted = admitted
        self.lane = lane
        self.tenant_id = tenant_id
        self.reason = reason
        self.retry_after = retry_after
        self._held = False

    def __bool__(self) -> bool:
        return self.admitted

    def __repr__(self) -> str:  # operator/debug surface
        if self.admitted:
            return f"<admitted lane={self.lane} tenant={self.tenant_id!r}>"
        return (
            f"<shed reason={self.reason} lane={self.lane} "
            f"tenant={self.tenant_id!r} retry_after={self.retry_after}>"
        )


class AdmissionRejected(RuntimeError):
    """Raised by EdgeNode.attach/resume when the installed controller
    sheds the request (in-process callers; the transports answer 503/WS
    errors instead of raising)."""

    def __init__(self, decision: AdmissionDecision):
        super().__init__(
            f"admission rejected ({decision.reason}; lane={decision.lane}, "
            f"tenant={decision.tenant_id!r})"
        )
        self.decision = decision


class AdmissionController:
    """Admit/shed decisions for one edge process.

    ``registry``/``resolver`` are the existing multitenancy pieces
    (``ext/multitenancy.py``); omitted, a single-tenant registry is
    minted and every request resolves to the default tenant. Knobs:

    - ``connect_rate``/``connect_burst``: per-tenant connection token
      bucket (attaches/second sustained, burst capacity).
    - ``subscribe_rate``/``subscribe_burst``: per-tenant KEY-subscribe
      bucket — an attach naming N keys takes N tokens, bounding the
      upstream-subscription minting rate per tenant.
    - ``resume_rate``/``resume_burst``: the resume lane's own (global)
      bucket — reconnects replay parked state and mint no new upstream
      subs, so they ride a wider pipe and never compete with cold
      attaches for tenant tokens.
    - ``max_concurrent``: the global concurrent-attach gate.
      ``resume_reserve``/``priority_reserve`` carve reserved headroom:
      anonymous admits while holds < max*(1-rr-pr), priority while
      holds < max*(1-rr), resume up to the full gate — the lane ORDER.
    - ``tenant_gate_share``: max fraction of the gate one non-default
      tenant may hold (isolation; not applied in single-tenant mode).
    - ``shed_pressure``: anonymous cold attaches shed once
      :meth:`pressure` crosses this (priority/resume lanes keep
      admitting — overload cuts the cheapest-to-retry lane first).
    - ``retry_after``: the default Retry-After for non-rate sheds.
    - ``clock``: injectable monotonic clock (tests).
    """

    def __init__(
        self,
        registry=None,
        resolver=None,
        *,
        connect_rate: float = 500.0,
        connect_burst: float = 1000.0,
        subscribe_rate: float = 5000.0,
        subscribe_burst: float = 10000.0,
        resume_rate: float = 5000.0,
        resume_burst: float = 10000.0,
        max_concurrent: int = 1024,
        resume_reserve: float = 0.25,
        priority_reserve: float = 0.25,
        tenant_gate_share: float = 0.5,
        shed_pressure: float = 0.9,
        retry_after: float = 1.0,
        clock=time.monotonic,
        name: str = "edge",
    ):
        from ..ext.multitenancy import TenantRegistry, TenantResolver

        self.registry = registry if registry is not None else TenantRegistry()
        self.resolver = (
            resolver if resolver is not None else TenantResolver(self.registry)
        )
        self.connect_rate = connect_rate
        self.connect_burst = connect_burst
        self.subscribe_rate = subscribe_rate
        self.subscribe_burst = subscribe_burst
        self.max_concurrent = int(max_concurrent)
        if not 0.0 <= resume_reserve + priority_reserve < 1.0:
            raise ValueError("lane reserves must leave anonymous headroom")
        self.resume_reserve = resume_reserve
        self.priority_reserve = priority_reserve
        self.tenant_gate_share = tenant_gate_share
        self.shed_pressure = shed_pressure
        self.retry_after = retry_after
        self.clock = clock
        self.name = name
        self.draining = False
        self._resume_bucket = TokenBucket(resume_rate, resume_burst, clock)
        #: tenant id -> (connect bucket, subscribe bucket), minted lazily
        self._buckets: Dict[str, tuple] = {}
        #: gate occupancy: held (hold=True, unreleased) admissions
        self._in_flight = 0
        self._tenant_in_flight: Dict[str, int] = {}
        #: pull-time pressure sources: name -> fn() -> 0..1 (fan-shard
        #: depth, worker-pipe drops, ...); set_pressure() installs a
        #: constant (tests, external signals)
        self._pressure_sources: Dict[str, Callable[[], float]] = {}
        # -- counters (collector-exported) --------------------------------
        self.admitted_by_lane: Dict[str, int] = {lane: 0 for lane in _LANES}
        self.shed_by_reason: Dict[str, int] = {}
        reg = global_metrics()
        # non-additive gauges combine by MAX across controllers (two
        # half-loaded controllers are half loaded, not fully loaded)
        reg.set_aggregation("fusion_edge_admission_pressure", "max")
        reg.register_collector(self, AdmissionController._collect_metrics)

    # ------------------------------------------------------------- pressure
    def add_pressure_source(self, name: str, fn: Callable[[], float]) -> None:
        self._pressure_sources[name] = fn

    def set_pressure(self, name: str, value: float) -> None:
        """Install a constant pressure source (or overwrite one)."""
        v = float(value)
        self._pressure_sources[name] = lambda: v

    def clear_pressure(self, name: str) -> None:
        self._pressure_sources.pop(name, None)

    def pressure(self) -> float:
        """The load signal, 0..1: the MAX over registered sources — one
        saturated plane is enough to start shedding; a healthy plane never
        hides a wedged one behind an average."""
        worst = 0.0
        for fn in list(self._pressure_sources.values()):
            try:
                worst = max(worst, float(fn()))
            except Exception:  # noqa: BLE001 — a dying source must not
                # turn admission into an exception path
                log.exception("admission %s: pressure source failed", self.name)
        return min(1.0, max(0.0, worst))

    # ------------------------------------------------------------- tenants
    def _tenant_buckets(self, tenant_id: str) -> tuple:
        buckets = self._buckets.get(tenant_id)
        if buckets is None:
            buckets = self._buckets[tenant_id] = (
                TokenBucket(self.connect_rate, self.connect_burst, self.clock),
                TokenBucket(self.subscribe_rate, self.subscribe_burst, self.clock),
            )
        return buckets

    def _resolve(self, tenant_id: Optional[str]):
        """Tenant id (wire string) -> registered Tenant; None/"" is the
        default tenant. Returns None when the id names no registered
        tenant (shed, counted — a typo'd tenant must not mint unbounded
        per-tenant bucket state). The registry lookup is the fast path
        (what the default resolver does after parsing the id back out of
        a session suffix — minting a Session per admit() would put a
        urandom read on the hot accept path); a CUSTOM resolver still
        gets consulted for ids the registry does not key directly."""
        from ..ext.multitenancy import Session, TenantNotFoundError

        tenant = self.registry.try_get(tenant_id or "")
        if tenant is not None:
            return tenant
        if not tenant_id:
            return None
        try:
            return self.resolver.resolve(Session.new(tenant_id))
        except TenantNotFoundError:
            return None

    # ------------------------------------------------------------- admit
    def _shed(
        self, lane: str, tenant_id: str, reason: str,
        retry_after: Optional[float] = None,
    ) -> AdmissionDecision:
        self.shed_by_reason[reason] = self.shed_by_reason.get(reason, 0) + 1
        # attribution (ISSUE 19): the per-tenant shed sketch is what the
        # edge_shed_rate SLO names in its /health attribution block
        global_hotkeys().offer("tenant_sheds", tenant_id or "(default)")
        if retry_after is None:
            retry_after = self.retry_after
        elif not math.isfinite(retry_after):
            # a zero-rate bucket answers "an hour", not Infinity (which
            # is not even valid JSON on the wire)
            retry_after = 3600.0
        return AdmissionDecision(
            False, lane, tenant_id, reason=reason, retry_after=retry_after,
        )

    def note_shed(self, reason: str) -> None:
        """Count a shed decided OUTSIDE admit() — the transports' unified
        rejection path (bad_request / replay_evicted / resume_expired) and
        the worker pool's dropped fd-handoffs ride the same counter."""
        self.shed_by_reason[reason] = self.shed_by_reason.get(reason, 0) + 1

    def _lane_ceiling(self, lane: str) -> int:
        if lane == LANE_RESUME:
            return self.max_concurrent
        if lane == LANE_PRIORITY:
            return int(self.max_concurrent * (1.0 - self.resume_reserve))
        return int(
            self.max_concurrent
            * (1.0 - self.resume_reserve - self.priority_reserve)
        )

    def admit(
        self,
        tenant_id: str = "",
        lane: Optional[str] = None,
        keys: int = 0,
        hold: bool = False,
    ) -> AdmissionDecision:
        """One admission decision. ``lane=None`` derives it from the
        tenant (``priority`` tenants ride the priority lane, everything
        else is anonymous); pass ``lane="resume"`` for reconnects. With
        ``hold`` the caller occupies a gate slot until :meth:`release`."""
        tenant = self._resolve(tenant_id)
        if tenant is None:
            return self._shed(
                lane or LANE_ANONYMOUS, tenant_id, "unknown_tenant",
                retry_after=0.0,
            )
        tid = tenant.id
        if lane is None:
            lane = (
                LANE_PRIORITY
                if getattr(tenant, "priority", False)
                else LANE_ANONYMOUS
            )
        if self.draining:
            return self._shed(lane, tid, "draining")
        # -- NON-CONSUMING checks first (pressure, gate): a request shed
        # here must not burn the tenant's rate budget — otherwise a
        # client retrying per Retry-After through sustained pressure
        # drains its bucket to zero and keeps being shed ("rate") after
        # the pressure clears, on an idle node
        # -- pressure shed: the anonymous lane goes first
        if lane == LANE_ANONYMOUS and self.pressure() >= self.shed_pressure:
            return self._shed(lane, tid, "pressure")
        # -- the global gate with lane-reserved headroom ------------------
        if self._in_flight >= self._lane_ceiling(lane):
            return self._shed(lane, tid, "gate_full")
        # -- per-tenant gate share (multi-tenant only: in single-tenant
        # mode everyone IS the default tenant and a share cap would just
        # be a second, surprising gate)
        if tid and len(self.registry.all_tenants) > 1:
            share = max(1, int(self.max_concurrent * self.tenant_gate_share))
            if self._tenant_in_flight.get(tid, 0) >= share:
                return self._shed(lane, tid, "tenant_gate")
        # -- rate buckets (per tenant; the resume lane rides its own) -----
        connect, subscribe = self._tenant_buckets(tid)
        if lane == LANE_RESUME:
            if not self._resume_bucket.try_take(1.0):
                return self._shed(
                    lane, tid, "rate", self._resume_bucket.retry_after(1.0)
                )
        else:
            if not connect.try_take(1.0):
                return self._shed(lane, tid, "rate", connect.retry_after(1.0))
            if keys > 0 and not subscribe.try_take(float(keys)):
                return self._shed(
                    lane, tid, "subscribe_rate",
                    subscribe.retry_after(float(keys)),
                )
        decision = AdmissionDecision(True, lane, tid)
        self.admitted_by_lane[lane] = self.admitted_by_lane.get(lane, 0) + 1
        global_hotkeys().offer("tenant_admits", tid or "(default)")
        if hold:
            decision._held = True
            self._in_flight += 1
            self._tenant_in_flight[tid] = self._tenant_in_flight.get(tid, 0) + 1
        return decision

    def admit_keys(self, tenant_id: str = "", keys: int = 0) -> AdmissionDecision:
        """Charge ONLY the per-tenant subscribe bucket (the worker-pool
        plane: the connection was admitted at the accept hop BEFORE its
        key specs were readable, so the key debit lands when the worker
        forwards them). Resumed sessions are exempt — they replay, they
        do not mint new upstream state. Does not touch the connect
        bucket, the gate, or the admitted-per-lane counters (the
        connection already counted)."""
        tenant = self._resolve(tenant_id)
        if tenant is None:
            return self._shed(
                LANE_ANONYMOUS, tenant_id, "unknown_tenant", retry_after=0.0
            )
        if keys <= 0:
            return AdmissionDecision(True, LANE_ANONYMOUS, tenant.id)
        _connect, subscribe = self._tenant_buckets(tenant.id)
        if not subscribe.try_take(float(keys)):
            return self._shed(
                LANE_ANONYMOUS, tenant.id, "subscribe_rate",
                subscribe.retry_after(float(keys)),
            )
        return AdmissionDecision(True, LANE_ANONYMOUS, tenant.id)

    def release(self, decision: Optional[AdmissionDecision]) -> None:
        """Release a held gate slot (idempotent per decision)."""
        if decision is None or not decision._held:
            return
        decision._held = False
        self._in_flight = max(0, self._in_flight - 1)
        tid = decision.tenant_id
        left = self._tenant_in_flight.get(tid, 0) - 1
        if left > 0:
            self._tenant_in_flight[tid] = left
        else:
            self._tenant_in_flight.pop(tid, None)

    # ------------------------------------------------------------- drain
    def begin_drain(self) -> None:
        """Stop admitting (every lane sheds ``draining``); live sessions
        are untouched — EdgeNode.drain() hints and parks them."""
        self.draining = True

    # ------------------------------------------------------------- metrics
    @property
    def in_flight(self) -> int:
        return self._in_flight

    def total_admitted(self) -> int:
        return sum(self.admitted_by_lane.values())

    def total_shed(self) -> int:
        return sum(self.shed_by_reason.values())

    def snapshot(self) -> dict:
        return {
            "draining": self.draining,
            "pressure": round(self.pressure(), 4),
            "in_flight": self._in_flight,
            "max_concurrent": self.max_concurrent,
            "admitted": dict(self.admitted_by_lane),
            "shed": dict(self.shed_by_reason),
        }

    def _collect_metrics(self) -> dict:
        out = {
            "fusion_edge_admission_pressure": round(self.pressure(), 4),
            "fusion_edge_admission_in_flight": self._in_flight,
            "fusion_edge_admission_draining": 1 if self.draining else 0,
        }
        for lane, count in self.admitted_by_lane.items():
            out[f'fusion_edge_admitted_total{{lane="{lane}"}}'] = count
        for reason, count in self.shed_by_reason.items():
            out[f'fusion_edge_shed_total{{reason="{reason}"}}'] = count
        return out
