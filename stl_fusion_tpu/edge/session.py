"""Edge session core — bounded outboxes, latest-wins coalescing, eviction.

The per-connection delivery machinery every downstream surface shares
(ISSUE 8 tentpole): the edge gateway's SSE/WebSocket sessions and the UI
layer's ``LiveViewServer`` pump both ride these pieces, so backpressure,
heartbeat keep-alives and slow-consumer eviction behave identically no
matter which transport a browser arrived on.

Three pieces, smallest first:

- :class:`LatestWinsMailbox` — the single-slot render mailbox (formerly
  ``ui.web._RenderSlot``): a payload that lands while an older one is still
  pending REPLACES it, so a stalled reader holds ONE pending payload no
  matter how many renders fire.
- :class:`KeyedMailbox` — the multi-key variant the edge needs: pending
  frames coalesce PER KEY (a key fenced five times between drains ships
  once, at the newest value), preserving first-arrival order across keys.
  Bounded: a mailbox that exceeds ``max_pending`` distinct keys reports
  overflow, which the owner treats as a slow consumer (evict + resume
  token) — pending memory per session is therefore bounded by
  min(subscribed keys, max_pending) frames, never by event rate.
- :func:`pump_payloads` — the shared per-connection pump: take latest-wins
  payloads, optionally rate-limit (the newest payload at the end of the
  interval is what ships), send with a timeout, heartbeat when idle, and
  EVICT the connection when a send cannot make progress — a dead tab never
  pins its session, and (each session having its own pump) never stalls a
  sibling.

:class:`EdgeSession` is the gateway's per-subscriber state: identity
(resume token), subscribed keys, delivered-version map (the Last-Event-ID
resume source) and a delivery surface that is either a synchronous sink
(in-process consumers, the 1M-subscriber simulation) or a
:class:`KeyedMailbox` drained by a transport pump (SSE/WebSocket).

Frames are plain tuples — ``(key, version, value, cause, origin_ts, err)``
— so a million in flight stay cheap; :func:`frame_to_dict` is the wire
shape. ``cause``/``origin_ts`` ride through from the upstream ``$sys-c``
fence (ClientComputed.invalidation_cause/_origin_ts), so the delivery
histogram measures server wave apply → client-visible and ``explain()``
can span server wave → edge → session.
"""
from __future__ import annotations

import asyncio
import itertools
import json
import os
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

__all__ = [
    "EdgeSession",
    "EncodedFrame",
    "Frame",
    "KeyedMailbox",
    "LatestWinsMailbox",
    "frame_to_dict",
    "pump_payloads",
]

#: (key, version, value, cause, origin_ts, err) — err is a "Type: msg"
#: string when the upstream read failed, else None
Frame = Tuple[str, int, Any, Optional[str], Optional[float], Optional[str]]


def frame_to_dict(frame: Frame) -> dict:
    """The JSON wire shape of one frame (SSE ``data:`` payload / WS
    message). ``cause`` + ``t0`` propagate the upstream fence identity so a
    downstream consumer can extend the causal chain and the delivery
    measurement one more hop."""
    key, version, value, cause, origin_ts, err = frame
    out: dict = {"key": key, "ver": version}
    if err is not None:
        out["err"] = err
    else:
        out["value"] = value
    if cause is not None:
        out["cause"] = cause
    if origin_ts is not None:
        out["t0"] = origin_ts
    return out


class EncodedFrame:
    """One frame's wire payload, serialized EXACTLY ONCE (ISSUE 10).

    The fan path used to pay ``json.dumps(frame_to_dict(frame))`` per
    session per frame — a 250k-session hot key re-encoded the same JSON
    250k times. An :class:`EncodedFrame` is minted once per (key,
    version) and every downstream pump writes the same immutable
    ``bytes``; only the per-session envelope (the SSE ``id:`` line — the
    resume token) stays per-session, written as a cheap prefix around the
    shared body.

    ``body`` is the canonical JSON object bytes (compact separators — the
    wire shape of :func:`frame_to_dict`). ``sse`` is the shared SSE tail
    (``event: update\\ndata: <body>\\n\\n``); a transport prepends its
    session's ``id: <token>\\n`` line. ``text`` is the lazily-decoded str
    for WebSocket text frames (decoded at most once per encoded frame,
    and only when a WS session exists).

    ``lossy`` latches when the payload was not JSON-serializable and fell
    back to ``repr`` — detected HERE, at encode time, once per frame
    (counted by the node as ``fusion_edge_frames_lossy_total``) instead
    of silently repr-ing per session inside the old per-delivery dumps.

    Immutability contract: the bytes are built from the payload at encode
    time — a caller that mutates the payload dict afterwards changes
    nothing a session will see (regression-tested).
    """

    __slots__ = (
        "key", "version", "body", "sse", "lossy", "has_t0",
        "replay_variant", "_text",
    )

    def __init__(self, frame: Frame):
        self.key = frame[0]
        self.version = frame[1]
        #: whether the body carries the fence origin timestamp. Replays
        #: (attach/resume/reconnect) ship WITHOUT it — now-minus-then is a
        #: reconnect gap, not delivery latency — so a replay asks for the
        #: t0-stripped twin, cached as :attr:`replay_variant` on the
        #: canonical entry (still one encode per variant, ever).
        self.has_t0 = frame[4] is not None
        self.replay_variant: Optional["EncodedFrame"] = None
        payload = frame_to_dict(frame)
        try:
            body = json.dumps(payload, separators=(",", ":")).encode()
            self.lossy = False
        except (TypeError, ValueError):
            body = json.dumps(
                payload, separators=(",", ":"), default=repr
            ).encode()
            self.lossy = True
        self.body = body
        self.sse = b"event: update\ndata: " + body + b"\n\n"
        self._text: Optional[str] = None

    @property
    def text(self) -> str:
        """The body as str (WS text frames) — decoded at most once."""
        if self._text is None:
            self._text = self.body.decode()
        return self._text

    def sse_event(self, id_prefix: bytes) -> bytes:
        """The full per-session SSE event: the session's ``id:`` prefix
        (its resume token envelope) + the SHARED tail bytes."""
        return id_prefix + self.sse


class LatestWinsMailbox:
    """Latest-wins render mailbox (one per connection): a payload that
    lands while an older one is still pending simply REPLACES it — the
    Blazor render-current-state rule (ComputedStateComponent.cs:27-132). A
    stalled reader therefore holds ONE pending payload no matter how many
    invalidations fire; intermediate payloads nobody could have seen are
    dropped, counted in ``coalesced``."""

    _EMPTY = object()
    __slots__ = ("_payload", "_event", "pushed", "coalesced")

    def __init__(self):
        self._payload: Any = self._EMPTY
        self._event = asyncio.Event()
        self.pushed = 0
        self.coalesced = 0

    def push(self, payload: Any) -> None:
        if self._payload is not self._EMPTY:
            self.coalesced += 1
        self._payload = payload
        self.pushed += 1
        self._event.set()

    async def take(self) -> Any:
        await self._event.wait()
        self._event.clear()
        payload, self._payload = self._payload, self._EMPTY
        return payload

    def take_nowait(self, default: Any) -> Any:
        """The newest payload if one landed since, else ``default`` (used
        after a rate-limit sleep so the send is never stale)."""
        if self._payload is self._EMPTY:
            return default
        self._event.clear()
        payload, self._payload = self._payload, self._EMPTY
        return payload


class KeyedMailbox:
    """Multi-key latest-wins mailbox: pending frames coalesce PER KEY
    (dict insertion order preserves cross-key arrival order), and a drain
    takes the whole pending batch. ``overflowed`` latches when more than
    ``max_pending`` distinct keys are pending at once — the owner's signal
    that this consumer is not draining (evict with a resume token; the
    per-key version map replays what it missed)."""

    __slots__ = ("_pending", "_event", "max_pending", "pushed", "coalesced", "overflowed")

    def __init__(self, max_pending: int = 4096):
        self._pending: Dict[str, Frame] = {}
        self._event = asyncio.Event()
        self.max_pending = max_pending
        self.pushed = 0
        self.coalesced = 0
        self.overflowed = False

    def __len__(self) -> int:
        return len(self._pending)

    def push(self, frame: Frame) -> None:
        key = frame[0]
        if key in self._pending:
            self.coalesced += 1
        elif len(self._pending) >= self.max_pending:
            self.overflowed = True
        self._pending[key] = frame
        self.pushed += 1
        self._event.set()

    async def take(self) -> List[Frame]:
        while not self._pending:
            self._event.clear()
            await self._event.wait()
        self._event.clear()
        batch = list(self._pending.values())
        self._pending.clear()
        return batch

    def take_nowait(self, default: Any) -> Any:
        """Newest pending frames MERGED over ``default`` (the batch a
        rate-limited pump already took): latest-wins is per KEY here, so a
        taken frame whose key has no newer pending frame must still ship —
        wholesale replacement (the single-slot mailbox's semantics) would
        silently drop another key's only update."""
        if not self._pending:
            return default
        self._event.clear()
        merged: Dict[str, Frame] = {}
        if isinstance(default, list):
            for frame in default:
                merged[frame[0]] = frame
        for key, frame in self._pending.items():
            merged[key] = frame
        self._pending.clear()
        return list(merged.values())


async def pump_payloads(
    mailbox,
    send: Callable[[Any], Awaitable[None]],
    *,
    min_send_interval: float = 0.0,
    send_timeout: Optional[float] = None,
    heartbeat_interval: Optional[float] = None,
    heartbeat: Optional[Callable[[], Awaitable[None]]] = None,
    on_evict: Optional[Callable[[], None]] = None,
) -> str:
    """Drive one connection until it dies. Returns ``"evicted"`` when a
    send (or heartbeat) could not make progress for ``send_timeout``
    seconds — the caller's ``on_evict`` has already run — or ``"closed"``
    when the transport raised (a dying socket is a normal exit).

    Semantics shared by every downstream surface:

    - **latest-wins**: payloads come from ``mailbox.take()``; whatever
      coalescing the mailbox does is the backpressure story.
    - **rate limit**: with ``min_send_interval`` set, the pump sleeps out
      the remainder of the interval and then ships the NEWEST payload
      (``take_nowait`` supersedes the taken one) — a burst collapses to
      one frame per interval, never a stale one.
    - **heartbeat**: with ``heartbeat_interval`` set, an idle connection
      gets ``heartbeat()`` calls so proxies/browsers keep it open and a
      dead peer is detected by the send timeout instead of never.
    - **eviction**: a send that cannot complete within ``send_timeout``
      means the peer stopped draining; the pump runs ``on_evict`` (abort
      the transport, park the session) and exits. Each connection has its
      OWN pump, so one stalled peer never delays a sibling.
    """
    loop = asyncio.get_event_loop()
    last_send = -float("inf")
    while True:
        if heartbeat_interval is not None and heartbeat_interval > 0:
            try:
                payload = await asyncio.wait_for(mailbox.take(), heartbeat_interval)
            except (asyncio.TimeoutError, TimeoutError):
                if heartbeat is None:
                    continue
                try:
                    await asyncio.wait_for(heartbeat(), send_timeout)
                except (asyncio.TimeoutError, TimeoutError):
                    if on_evict is not None:
                        on_evict()
                    return "evicted"
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — dying socket: normal exit
                    return "closed"
                continue
        else:
            payload = await mailbox.take()
        if min_send_interval > 0:
            wait = min_send_interval - (loop.time() - last_send)
            if wait > 0:
                await asyncio.sleep(wait)
                payload = mailbox.take_nowait(payload)  # newest at send time
        try:
            await asyncio.wait_for(send(payload), send_timeout)
        except (asyncio.TimeoutError, TimeoutError):
            # the peer stopped draining: evict it rather than letting a
            # dead tab pin the session forever
            if on_evict is not None:
                on_evict()
            return "evicted"
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — a dying socket is a normal exit
            return "closed"
        last_send = loop.time()


_token_counter = itertools.count(1)


def _mint_token() -> str:
    """Resume-token id: unguessable (a token authorizes replaying a
    session's stream) and cheap. 8 random bytes + a process-local ordinal."""
    return f"es-{os.urandom(8).hex()}-{next(_token_counter)}"


class EdgeSession:
    """One downstream subscriber: identity, keys, delivered versions, and
    a delivery surface.

    Two delivery flavors, chosen at attach time:

    - ``sink`` (synchronous callable ``sink(frame)``): the frame is
      client-visible the moment the callable returns — in-process
      consumers and the 1M-subscriber simulation, where a per-session
      pump task would be 1M tasks. Delivered versions update inline.
    - ``mailbox`` (:class:`KeyedMailbox`): frames coalesce per key until a
      transport pump drains them; the pump calls :meth:`mark_delivered`
      AFTER the transport accepted the batch, so the resume map never
      claims a frame the peer did not receive.

    ``versions`` is the Last-Event-ID-style resume source: key → highest
    version delivered. ``track_versions=False`` (the simulation's memory
    knob) skips the map; such a session resumes from zero (every key
    replays), which is correct, just not minimal.

    Slotted: a million of these must stay in the hundreds of megabytes.
    """

    __slots__ = (
        "token",
        "keys",
        "versions",
        "sink",
        "mailbox",
        "evicted",
        "delivered",
        "on_evicted",
        "on_drain",
        "shard",
    )

    def __init__(
        self,
        keys: Tuple[str, ...],
        sink: Optional[Callable[[Frame], None]] = None,
        mailbox: Optional[KeyedMailbox] = None,
        token: Optional[str] = None,
        track_versions: bool = True,
    ):
        if (sink is None) == (mailbox is None):
            raise ValueError("EdgeSession needs exactly one of sink= or mailbox=")
        self.token = token or _mint_token()
        self.keys = tuple(keys)
        self.versions: Optional[Dict[str, int]] = {} if track_versions else None
        self.sink = sink
        self.mailbox = mailbox
        self.evicted = False
        self.delivered = 0
        #: fan-shard index (assigned by EdgeNode at attach/resume): which
        #: of the node's parallel fan workers delivers to this session
        self.shard = 0
        #: transport shutdown hook the owning connection handler installs:
        #: EdgeNode.evict() calls it after parking, so an eviction that did
        #: NOT originate in the transport pump (mailbox overflow, broken
        #: sink) still aborts the connection instead of leaving the peer
        #: on a silent, heartbeat-alive stream that will never update
        self.on_evicted: Optional[Callable[[], None]] = None
        #: drain hook (ISSUE 12c): EdgeNode.drain() calls it with the
        #: reconnect hint frame INSTEAD of the sink/mailbox — transports
        #: write the hint and close the stream CLEANLY (the peer must
        #: receive its resume token, so this is never an abort); sessions
        #: without a hook get the hint through their normal surface
        self.on_drain: Optional[Callable[[Frame], None]] = None

    def deliver(self, frame: Frame) -> bool:
        """Hand one frame to this session. Returns False when the session
        should be EVICTED (its mailbox overflowed — a slow consumer whose
        pending set outgrew the bound). Never blocks: the sink flavor is
        synchronous by contract, the mailbox flavor just coalesces."""
        if self.evicted:
            return True
        if self.sink is not None:
            self.sink(frame)
            self.delivered += 1
            if self.versions is not None:
                self.versions[frame[0]] = frame[1]
            return True
        mailbox = self.mailbox
        mailbox.push(frame)
        return not mailbox.overflowed

    def mark_delivered(self, frames: List[Frame]) -> None:
        """Transport pump callback: the batch reached the peer — advance
        the resume map (mailbox-flavor sessions only; sink delivery
        advances inline)."""
        self.delivered += len(frames)
        if self.versions is not None:
            for frame in frames:
                self.versions[frame[0]] = frame[1]

    def resume_state(self) -> Dict[str, int]:
        """key → delivered version, as parked on eviction (empty when
        version tracking is off: resume replays every key)."""
        return dict(self.versions) if self.versions is not None else {}
