"""Checkpoint/resume — durable snapshots of the computed graph.

The reference has no training-style checkpoints; its two restart-survival
mechanisms are (a) the persistent client computed cache, version-flushed and
synchronized after boot (Client/Caching/ClientComputedCache.cs:10-49), and
(b) the DB operation log as the durable source of invalidation truth, replayed
from a commit-time watermark (Operations/DbOperationLogReader.cs:36-77).
SURVEY §5.4 maps both onto the TPU build as: **checkpoint = snapshot of
(graph + versions + values) plus op-log offset**. This module implements that:

- :func:`save_graph` / :func:`load_graph` — raw DeviceGraph array snapshots
  (npz) for standalone bench-scale graphs with no host registry.
- :class:`HubCheckpoint` — warm-boot snapshots of a FusionHub's computed
  state: every live, consistent, serializable compute-method result with its
  version, the host dependency edges between them, and the op-log position.
  ``restore`` re-creates the nodes as CONSISTENT computeds (reads hit warm
  immediately), re-links the dependency edges (so cascading invalidation
  works from turn one), and returns the op-log position to resume the
  reader from — replaying external operations committed after the snapshot
  invalidates exactly the entries that went stale while the host was down.
- :class:`CheckpointManager` — numbered snapshots in a directory with
  ``latest()`` lookup, the orbax-style save/restore loop without the
  training-framework dependency surface.
"""
from __future__ import annotations

import logging
import os
import re
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.computed import Computed
from ..core.hub import FusionHub
from ..core.inputs import ComputeMethodInput, KwArgsTail
from ..graph.device_graph import DeviceGraph
from ..utils.ltag import LTag
from ..utils.result import Result
from ..utils.serialization import dumps, encode, decode, loads

log = logging.getLogger("stl_fusion_tpu")

__all__ = [
    "save_graph",
    "load_graph",
    "HubCheckpoint",
    "RestoreResult",
    "CheckpointManager",
]

_FORMAT_VERSION = 1


from ..utils.serialization import deep_tuple as _deep_tuple


# ---------------------------------------------------------------- device graph
def save_graph(graph: DeviceGraph, path: str) -> None:
    """Snapshot a DeviceGraph's authoritative host arrays (live prefixes only)."""
    np.savez_compressed(
        path,
        format=np.int32(_FORMAT_VERSION),
        n_nodes=np.int64(graph.n_nodes),
        n_edges=np.int64(graph.n_edges),
        edge_src=graph._h_edge_src[: graph.n_edges],
        edge_dst=graph._h_edge_dst[: graph.n_edges],
        edge_dst_epoch=graph._h_edge_dst_epoch[: graph.n_edges],
        node_epoch=graph._h_node_epoch[: graph.n_nodes],
        invalid=graph._h_invalid[: graph.n_nodes],
    )


def load_graph(path: str) -> DeviceGraph:
    """Rebuild a DeviceGraph from :func:`save_graph` output. Device arrays
    re-materialize lazily on first use (the mirror derives from host state)."""
    with np.load(path) as z:
        n_nodes = int(z["n_nodes"])
        n_edges = int(z["n_edges"])
        graph = DeviceGraph(node_capacity=max(n_nodes, 16), edge_capacity=max(n_edges, 16))
        graph.add_nodes(n_nodes)
        graph._h_node_epoch[:n_nodes] = z["node_epoch"]
        graph._h_invalid[:n_nodes] = z["invalid"]
        # edges carry their recorded capture epochs (stale edges stay stale);
        # any entry at/above the old capacity was a dummy-slot pad — re-point
        # it at the NEW dummy slot
        src = z["edge_src"].copy()
        dst = z["edge_dst"].copy()
        src[src >= n_nodes] = graph.n_cap
        dst[dst >= n_nodes] = graph.n_cap
        graph.add_edges(src, dst, dst_epoch=z["edge_dst_epoch"])
    graph._dirty = True
    return graph


# ---------------------------------------------------------------- hub snapshot
def _service_name(hub: FusionHub, service: Any) -> str:
    """Stable name for a service: explicit str key in the hub container,
    else its type name (deterministic across restarts for one-instance-per-
    class services, which is the framework's normal shape)."""
    for key, svc in hub._services.items():
        if svc is service:
            return key if isinstance(key, str) else key.__name__
    return type(service).__name__


def _services_by_name(hub: FusionHub) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, svc in hub._services.items():
        out[key if isinstance(key, str) else key.__name__] = svc
    return out


@dataclass
class RestoreResult:
    """Outcome of :meth:`HubCheckpoint.restore`.

    Holds STRONG references to the restored computeds — the registry interns
    weakly, so drop this object only once something else (keep-alive timers,
    dependents, states) anchors the entries you care about.
    """

    computeds: List[Computed] = field(default_factory=list)
    skipped: int = 0
    edges: int = 0
    tables: int = 0  # MemoTables restored warm (columnar twin state)
    oplog_position: int = 0
    saved_at: float = 0.0

    @property
    def count(self) -> int:
        return len(self.computeds)


class HubCheckpoint:
    """Snapshot/restore of a hub's warm computed state (SURVEY §5.4)."""

    @staticmethod
    def snapshot(hub: FusionHub, oplog_position: int = 0) -> dict:
        """Capture every live CONSISTENT compute-method node whose arguments
        and value serialize. Error outputs and mid-compute nodes are skipped
        (they recompute cold — same rule as the reference's client cache,
        which only persists successful results)."""
        nodes: List[dict] = []
        index_of: Dict[Any, int] = {}
        live = hub.registry.live_computeds()
        skipped = 0
        for c in live:
            if not c.is_consistent or not isinstance(c.input, ComputeMethodInput):
                skipped += 1
                continue
            out = c._output
            if out is None or out.has_error:
                skipped += 1
                continue
            service = c.input.service
            svc_name = _service_name(hub, service)
            method_name = c.input.method_def.original.__name__
            try:
                entry = {
                    "s": svc_name,
                    "m": method_name,
                    "a": encode(list(c.input.args)),
                    "v": int(c.version),
                    "o": encode(out.value),
                }
            except TypeError:
                skipped += 1  # unserializable args/value — recomputes cold
                continue
            index_of[c.input] = len(nodes)
            nodes.append(entry)
        # host dependency edges among snapshot nodes: (dependent, used,
        # used-version) — the version lets restore detect that a LIVE node
        # displaced the snapshotted dependency and the dependent is stale
        edges: List[Tuple[int, int, int]] = []
        for c in live:
            di = index_of.get(c.input)
            if di is None:
                continue
            for used in c.used:
                ui = index_of.get(used.input)
                if ui is not None:
                    edges.append((di, ui, int(used.version)))
        return {
            "format": _FORMAT_VERSION,
            "saved_at": time.time(),
            "oplog_position": int(oplog_position),
            "nodes": nodes,
            "edges": edges,
            "tables": HubCheckpoint._snapshot_tables(hub),
            "skipped": skipped,
        }

    @staticmethod
    def _snapshot_tables(hub: FusionHub) -> List[dict]:
        """Columnar twin state (VERDICT r2 #6): every MATERIALIZED MemoTable
        behind a table-backed compute method — values, per-row validity,
        version, and (for codec-backed tables) the interned key layout, so
        a warm boot serves ``read_batch``/``read_keys`` hits without
        re-fetching a single row."""
        tables: List[dict] = []
        for service in hub._services.values():
            svc_name = _service_name(hub, service)
            for mname in dir(type(service)):
                method = getattr(type(service), mname, None)
                mdef = getattr(method, "__compute_method_def__", None)
                if mdef is None or mdef.table is None:
                    continue
                table = mdef.peek_table(service)
                if table is None:
                    continue  # never materialized: nothing to save
                entry = {"s": svc_name, "m": mname, "state": table.export_state()}
                codec = table.key_codec
                if codec is not None:
                    entry["keys"] = encode([list(codec.decode(r)) for r in range(len(codec))])
                tables.append(entry)
        return tables

    @staticmethod
    def _restore_tables(hub: FusionHub, services: Dict[str, Any], snap: dict) -> int:
        restored = 0
        for entry in snap.get("tables", ()):
            service = services.get(entry["s"])
            if service is None:
                log.warning("checkpoint: service %r missing; table skipped", entry["s"])
                continue
            method = getattr(service, entry["m"], None)
            mdef = getattr(method, "__compute_method_def__", None)
            if mdef is None or mdef.table is None:
                log.warning("checkpoint: %s.%s is not table-backed; skipped",
                            entry["s"], entry["m"])
                continue
            table = mdef.get_table(service)  # fresh wiring: hooks + codec
            if table.key_codec is not None:
                # re-intern the saved key layout IN ORDER so saved rows land
                # on the same ids (wire transport turns tuples into lists —
                # deep-tuple them back into hashable keys). If ANY key lands
                # on a different row — something was interned before the
                # restore, or the codec overflowed — the saved value arrays
                # would map to the WRONG keys: leave the table cold (it
                # refetches correctly) rather than serve silently wrong rows
                layout_ok = True
                try:
                    for row, args in enumerate(decode(entry.get("keys", []))):
                        if table.key_codec.acquire(_deep_tuple(args)) != row:
                            layout_ok = False
                            break
                except KeyError:
                    layout_ok = False
                if not layout_ok:
                    log.warning(
                        "checkpoint: table %s.%s key layout diverged from the "
                        "snapshot (keys interned before restore?); left cold",
                        entry["s"], entry["m"],
                    )
                    continue
            try:
                table.import_state(entry["state"])
            except ValueError as e:
                log.warning("checkpoint: table %s.%s shape mismatch (%s); "
                            "left cold", entry["s"], entry["m"], e)
                continue
            restored += 1
        return restored

    @staticmethod
    def save(hub: FusionHub, path: str, oplog_position: int = 0) -> dict:
        snap = HubCheckpoint.snapshot(hub, oplog_position)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(dumps(snap))
        os.replace(tmp, path)
        return snap

    @staticmethod
    def restore(
        hub: FusionHub,
        path: str,
        services: Optional[Dict[str, Any]] = None,
    ) -> RestoreResult:
        """Warm-boot ``hub`` from a snapshot file.

        Each snapshot node becomes a registered CONSISTENT computed carrying
        its ORIGINAL version, so op-log replay's version-matched invalidation
        semantics hold across the restart. Dependency edges re-link through
        the normal ``add_used`` path, which also feeds the device mirror
        hooks — the TPU CSR rebuilds itself from restored host truth.

        ``services`` maps snapshot service names to live instances; defaults
        to the hub's service container keyed by type name.
        """
        with open(path, "rb") as f:
            snap = loads(f.read())
        if snap.get("format") != _FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint format {snap.get('format')!r}")
        if services is None:
            services = _services_by_name(hub)
        result = RestoreResult(
            oplog_position=int(snap.get("oplog_position", 0)),
            saved_at=float(snap.get("saved_at", 0.0)),
        )
        restored: List[Optional[Computed]] = []
        for entry in snap["nodes"]:
            c = HubCheckpoint._restore_node(hub, services, entry)
            restored.append(c)
            if c is None:
                result.skipped += 1
            else:
                result.computeds.append(c)
        # tables restore BEFORE the edge-version invalidation loop: the
        # restored nodes carry the scalar→table mark_row_stale hook (they are
        # created through ComputeMethodFunction.create_computed), so any
        # provably-stale invalidation below must find the warm rows already
        # materialized and mark them stale — not land on a cold table and
        # then get overwritten by a later warm import
        result.tables = HubCheckpoint._restore_tables(hub, services, snap)
        for di, ui, used_version in snap.get("edges", ()):
            dep, used = restored[di], restored[ui]
            if dep is None or used is None:
                continue
            dep.add_used(used)
            result.edges += 1
            if int(used.version) != used_version:
                # a live computed displaced the snapshotted dependency: the
                # dependent's warm value was produced against a version that
                # no longer exists — it is provably stale
                dep.invalidate(immediately=True)
        return result

    @staticmethod
    def _restore_node(hub: FusionHub, services: Dict[str, Any], entry: dict) -> Optional[Computed]:
        service = services.get(entry["s"])
        if service is None:
            log.warning("checkpoint: service %r not registered; node skipped", entry["s"])
            return None
        method = getattr(service, entry["m"], None)
        method_def = getattr(method, "__compute_method_def__", None)
        if method_def is None:
            log.warning("checkpoint: %s.%s is not a compute method; node skipped",
                        entry["s"], entry["m"])
            return None
        args = tuple(decode(entry["a"]))
        if not (args and type(args[-1]) is KwArgsTail):  # already canonical
            try:
                # snapshots from before a key-normalization change store
                # args under the OLD canonical form (e.g. a defaulted
                # call's short tuple); re-normalizing keeps restored nodes
                # reachable by post-restore reads instead of orphaning them
                args = method_def.bind_args(service, args, {})
            except Exception:  # noqa: BLE001 — legacy key: keep raw
                pass
        function = method_def.get_function(service)
        input = ComputeMethodInput(method_def, service, args, function)
        existing = hub.registry.get(input)
        if existing is not None and existing.is_consistent:
            return existing  # live state wins over the snapshot
        # route through the function's create_computed — NOT a bare
        # Computed() — so restored nodes carry the same lifecycle hooks a
        # freshly computed node gets (in particular the table-backed
        # scalar→table mark_row_stale hook; a bare node would let post-
        # restore invalidations recompute the scalar while read_batch/
        # read_keys kept serving the stale warm row forever)
        computed = function.create_computed(input, LTag(entry["v"]))
        computed.try_set_output(Result.ok(decode(entry["o"])))
        hub.registry.register(computed)
        computed.renew_timeouts(True)  # arm keep-alive so warm entries survive
        return computed


# ---------------------------------------------------------------- manager
class CheckpointManager:
    """Numbered hub snapshots in a directory: ``fusion-ckpt-{n}.bin``."""

    _PATTERN = re.compile(r"fusion-ckpt-(\d+)\.bin$")

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def _steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.directory):
            m = self._PATTERN.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def path_of(self, step: int) -> str:
        return os.path.join(self.directory, f"fusion-ckpt-{step}.bin")

    def latest_step(self) -> Optional[int]:
        steps = self._steps()
        return steps[-1] if steps else None

    def save(self, hub: FusionHub, oplog_position: int = 0) -> int:
        step = (self.latest_step() or 0) + 1
        HubCheckpoint.save(hub, self.path_of(step), oplog_position)
        for old in self._steps()[: -self.keep]:
            try:
                os.remove(self.path_of(old))
            except OSError:
                pass
        return step

    def restore_latest(
        self, hub: FusionHub, services: Optional[Dict[str, Any]] = None
    ) -> Optional[RestoreResult]:
        step = self.latest_step()
        if step is None:
            return None
        return HubCheckpoint.restore(hub, self.path_of(step), services)
