"""Checkpoint/resume — durable snapshots of the computed graph.

The reference has no training-style checkpoints; its two restart-survival
mechanisms are (a) the persistent client computed cache, version-flushed and
synchronized after boot (Client/Caching/ClientComputedCache.cs:10-49), and
(b) the DB operation log as the durable source of invalidation truth, replayed
from a commit-time watermark (Operations/DbOperationLogReader.cs:36-77).
SURVEY §5.4 maps both onto the TPU build as: **checkpoint = snapshot of
(graph + versions + values) plus op-log offset**. This module implements that:

- :func:`save_graph` / :func:`load_graph` — raw DeviceGraph array snapshots
  (npz) for standalone bench-scale graphs with no host registry.
- :class:`HubCheckpoint` — warm-boot snapshots of a FusionHub's computed
  state: every live, consistent, serializable compute-method result with its
  version, the host dependency edges between them, and the op-log position.
  ``restore`` re-creates the nodes as CONSISTENT computeds (reads hit warm
  immediately), re-links the dependency edges (so cascading invalidation
  works from turn one), and returns the op-log position to resume the
  reader from — replaying external operations committed after the snapshot
  invalidates exactly the entries that went stale while the host was down.
- :class:`CheckpointManager` — numbered snapshots in a directory with
  ``latest()`` lookup, the orbax-style save/restore loop without the
  training-framework dependency surface. Since ISSUE 6 the manager is the
  durability layer proper: snapshots are checksummed + fsynced (see
  checkpoint/durable.py for the envelope), ``restore_latest`` falls back
  PAST a corrupt/torn latest snapshot to the newest valid one (quarantine-
  logging what it skipped), ``save_durable`` captures the epoch-consistent
  ``(shard-map epoch, oplog watermark)`` state the cluster warm-rejoin
  path (cluster/rejoin.py) restores, and ``snapshot_floor()`` feeds the
  oplog trimmer's clamp so a replay tail is never trimmed away.
"""
from __future__ import annotations

import logging
import os
import re
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.computed import Computed
from ..core.hub import FusionHub
from ..core.inputs import ComputeMethodInput, KwArgsTail
from ..graph.device_graph import DeviceGraph
from ..utils.ltag import LTag
from ..utils.result import Result
from ..utils.serialization import encode, decode
from .durable import (
    CorruptSnapshotError,
    DurableHubState,
    atomic_write,
    read_snapshot_file,
    read_snapshot_header,
    write_snapshot_file,
)

log = logging.getLogger("stl_fusion_tpu")

# distinguishes "caller did not choose a floor" from an explicit None
_FLOOR_UNSET = object()

__all__ = [
    "save_graph",
    "load_graph",
    "save_mesh_shards",
    "restore_mesh_shards",
    "CorruptSnapshotError",
    "DurableHubState",
    "HubCheckpoint",
    "RestoreResult",
    "CheckpointManager",
]

_FORMAT_VERSION = 1


from ..utils.serialization import deep_tuple as _deep_tuple


# ---------------------------------------------------------------- device graph
def save_graph(graph: DeviceGraph, path: str) -> None:
    """Snapshot a DeviceGraph's authoritative host arrays (live prefixes
    only). Written through :func:`durable.atomic_write` so a crash
    mid-save never leaves a truncated npz where the last good snapshot
    stood."""

    def _write(f):
        np.savez_compressed(
            f,
            format=np.int32(_FORMAT_VERSION),
            n_nodes=np.int64(graph.n_nodes),
            n_edges=np.int64(graph.n_edges),
            edge_src=graph._h_edge_src[: graph.n_edges],
            edge_dst=graph._h_edge_dst[: graph.n_edges],
            edge_dst_epoch=graph._h_edge_dst_epoch[: graph.n_edges],
            node_epoch=graph._h_node_epoch[: graph.n_nodes],
            invalid=graph._h_invalid[: graph.n_nodes],
        )

    atomic_write(path, _write)


def load_graph(path: str) -> DeviceGraph:
    """Rebuild a DeviceGraph from :func:`save_graph` output. Device arrays
    re-materialize lazily on first use (the mirror derives from host state)."""
    with np.load(path) as z:
        n_nodes = int(z["n_nodes"])
        n_edges = int(z["n_edges"])
        graph = DeviceGraph(node_capacity=max(n_nodes, 16), edge_capacity=max(n_edges, 16))
        graph.add_nodes(n_nodes)
        graph._h_node_epoch[:n_nodes] = z["node_epoch"]
        graph._h_invalid[:n_nodes] = z["invalid"]
        # edges carry their recorded capture epochs (stale edges stay stale);
        # any entry at/above the old capacity was a dummy-slot pad — re-point
        # it at the NEW dummy slot
        src = z["edge_src"].copy()
        dst = z["edge_dst"].copy()
        src[src >= n_nodes] = graph.n_cap
        dst[dst >= n_nodes] = graph.n_cap
        graph.add_edges(src, dst, dst_epoch=z["edge_dst_epoch"])
    graph._dirty = True
    return graph


# ----------------------------------------------------------- mesh shard state
def save_mesh_shards(routed_graph, path: str) -> int:
    """Snapshot a routed mesh mirror's node state keyed PER VIRTUAL SHARD
    (ISSUE 9): the unit that survives a reshard. The restoring process
    re-pins each shard under whatever :class:`~..cluster.placement.
    DevicePlacement` it derives from ITS current map — a warm restart
    after a reshard (PR 7's scenario on the mesh path) lands every
    shard's epochs/invalid marks on the right device regardless of how
    the slots moved in between. Returns the number of shards written."""
    snap = routed_graph.export_shard_state()
    shards = sorted(snap["shards"])
    offs = np.zeros(len(shards) + 1, dtype=np.int64)
    eps, invs = [], []
    for i, s in enumerate(shards):
        ep, inv = snap["shards"][s]
        offs[i + 1] = offs[i] + len(ep)
        eps.append(ep)
        invs.append(inv)

    def _write(f):
        np.savez_compressed(
            f,
            format=np.int32(_FORMAT_VERSION),
            map_epoch=np.int64(snap["epoch"]),
            n_nodes=np.int64(snap["n_nodes"]),
            n_shards=np.int64(snap["n_shards"]),
            shard_ids=np.asarray(shards, dtype=np.int64),
            offsets=offs,
            node_epoch=np.concatenate(eps) if eps else np.empty(0, np.int32),
            invalid=np.concatenate(invs) if invs else np.empty(0, bool),
        )

    atomic_write(path, _write)
    return len(shards)


def restore_mesh_shards(routed_graph, path: str) -> dict:
    """Re-pin a :func:`save_mesh_shards` snapshot onto a live routed graph
    under ITS placement. Shards the snapshot lacks (or that moved off this
    mesh) keep their built state. Returns ``{"restored": n, "map_epoch":
    e}`` — the caller compares ``map_epoch`` against its current epoch to
    decide what the PR 7 rejoin fence must cover."""
    with np.load(path) as z:
        shard_ids = z["shard_ids"]
        offs = z["offsets"]
        ep = z["node_epoch"]
        inv = z["invalid"]
        snap = {
            "epoch": int(z["map_epoch"]),
            "n_nodes": int(z["n_nodes"]),
            "n_shards": int(z["n_shards"]),
            "shards": {
                int(s): (ep[offs[i] : offs[i + 1]], inv[offs[i] : offs[i + 1]])
                for i, s in enumerate(shard_ids)
            },
        }
    restored = routed_graph.import_shard_state(snap)
    return {"restored": restored, "map_epoch": snap["epoch"]}


# ---------------------------------------------------------------- hub snapshot
def _service_name(hub: FusionHub, service: Any) -> str:
    """Stable name for a service: explicit str key in the hub container,
    else its type name (deterministic across restarts for one-instance-per-
    class services, which is the framework's normal shape)."""
    for key, svc in hub._services.items():
        if svc is service:
            return key if isinstance(key, str) else key.__name__
    return type(service).__name__


def _services_by_name(hub: FusionHub) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, svc in hub._services.items():
        out[key if isinstance(key, str) else key.__name__] = svc
    return out


@dataclass
class RestoreResult:
    """Outcome of :meth:`HubCheckpoint.restore`.

    Holds STRONG references to the restored computeds — the registry interns
    weakly, so drop this object only once something else (keep-alive timers,
    dependents, states) anchors the entries you care about.
    """

    computeds: List[Computed] = field(default_factory=list)
    skipped: int = 0
    edges: int = 0
    tables: int = 0  # MemoTables restored warm (columnar twin state)
    oplog_position: int = 0
    saved_at: float = 0.0
    # -- durable-state extras (ISSUE 6; zero/None for legacy snapshots) --
    epoch: int = 0  # shard-map epoch the snapshot was taken under
    snapshot_map: Optional[dict] = None  # wire-form ShardMap at snapshot time
    commit_floor: Optional[float] = None  # oldest trim-safe commit time
    subscriptions: int = 0  # live fan-out subscriptions at snapshot time

    @property
    def count(self) -> int:
        return len(self.computeds)

    @property
    def watermark(self) -> int:
        """Alias for ``oplog_position`` in durable-state terms."""
        return self.oplog_position


class HubCheckpoint:
    """Snapshot/restore of a hub's warm computed state (SURVEY §5.4)."""

    @staticmethod
    def snapshot(
        hub: FusionHub,
        oplog_position: int = 0,
        *,
        commit_floor: Any = _FLOOR_UNSET,
        log_store: Any = None,
    ) -> dict:
        """Capture every live CONSISTENT compute-method node whose arguments
        and value serialize. Error outputs and mid-compute nodes are skipped
        (they recompute cold — same rule as the reference's client cache,
        which only persists successful results).

        ``commit_floor``/``log_store`` control the trim-safety floor stamped
        in the snapshot header — see ``_capture_floor`` for the rules. The
        default (neither given) stamps NO floor, which makes
        ``snapshot_floor()`` clamp every trim while the snapshot is
        retained: safe, observable (``snapshot_clamped_trims``), but the
        log grows. Deployments that trim should pass ``log_store`` (or use
        ``CheckpointManager.save_durable``, which derives the floor from
        the reader)."""
        nodes: List[dict] = []
        index_of: Dict[Any, int] = {}
        live = hub.registry.live_computeds()
        skipped = 0
        for c in live:
            if not c.is_consistent or not isinstance(c.input, ComputeMethodInput):
                skipped += 1
                continue
            out = c._output
            if out is None or out.has_error:
                skipped += 1
                continue
            service = c.input.service
            svc_name = _service_name(hub, service)
            method_name = c.input.method_def.original.__name__
            try:
                entry = {
                    "s": svc_name,
                    "m": method_name,
                    "a": encode(list(c.input.args)),
                    "v": int(c.version),
                    "o": encode(out.value),
                }
            except TypeError:
                skipped += 1  # unserializable args/value — recomputes cold
                continue
            index_of[c.input] = len(nodes)
            nodes.append(entry)
        # host dependency edges among snapshot nodes: (dependent, used,
        # used-version) — the version lets restore detect that a LIVE node
        # displaced the snapshotted dependency and the dependent is stale
        edges: List[Tuple[int, int, int]] = []
        for c in live:
            di = index_of.get(c.input)
            if di is None:
                continue
            for used in c.used:
                ui = index_of.get(used.input)
                if ui is not None:
                    edges.append((di, ui, int(used.version)))
        return {
            "format": _FORMAT_VERSION,
            "saved_at": time.time(),
            "oplog_position": int(oplog_position),
            "oplog": {
                "watermark": int(oplog_position),
                "commit_floor": HubCheckpoint._capture_floor(
                    int(oplog_position), commit_floor, log_store
                ),
            },
            "nodes": nodes,
            "edges": edges,
            "tables": HubCheckpoint._snapshot_tables(hub),
            "skipped": skipped,
        }

    @staticmethod
    def _capture_floor(watermark: int, commit_floor: Any, log_store: Any):
        """The trim-safety floor for a snapshot at ``watermark`` — the
        commit time of the OLDEST oplog entry its replay tail needs.

        An explicit ``commit_floor`` wins (the caller read it off a
        reader). With a ``log_store`` the floor is derived from the log
        itself: the first record ABOVE the watermark, or the capture
        instant when the tail is empty (entries appended later commit at
        or after now). With neither, None — a caller-supplied watermark
        may LAG the log head, and a floor of now would let the trimmer
        delete the lagging tail replay still needs, so no floor is the
        only safe answer (``snapshot_floor()`` turns it into a
        clamp-every-trim)."""
        if commit_floor is not _FLOOR_UNSET:
            return commit_floor
        if log_store is not None:
            try:
                tail = log_store.read_after(watermark, limit=1)
            except Exception:  # noqa: BLE001 — corrupt head row: no floor
                return None
            return tail[0].commit_time if tail else time.time()
        return None

    @staticmethod
    def _snapshot_tables(hub: FusionHub) -> List[dict]:
        """Columnar twin state (VERDICT r2 #6): every MATERIALIZED MemoTable
        behind a table-backed compute method — values, per-row validity,
        version, and (for codec-backed tables) the interned key layout, so
        a warm boot serves ``read_batch``/``read_keys`` hits without
        re-fetching a single row."""
        tables: List[dict] = []
        for service in hub._services.values():
            svc_name = _service_name(hub, service)
            for mname in dir(type(service)):
                method = getattr(type(service), mname, None)
                mdef = getattr(method, "__compute_method_def__", None)
                if mdef is None or mdef.table is None:
                    continue
                table = mdef.peek_table(service)
                if table is None:
                    continue  # never materialized: nothing to save
                entry = {"s": svc_name, "m": mname, "state": table.export_state()}
                codec = table.key_codec
                if codec is not None:
                    entry["keys"] = encode([list(codec.decode(r)) for r in range(len(codec))])
                tables.append(entry)
        return tables

    @staticmethod
    def _restore_tables(hub: FusionHub, services: Dict[str, Any], snap: dict) -> int:
        restored = 0
        for entry in snap.get("tables", ()):
            service = services.get(entry["s"])
            if service is None:
                log.warning("checkpoint: service %r missing; table skipped", entry["s"])
                continue
            method = getattr(service, entry["m"], None)
            mdef = getattr(method, "__compute_method_def__", None)
            if mdef is None or mdef.table is None:
                log.warning("checkpoint: %s.%s is not table-backed; skipped",
                            entry["s"], entry["m"])
                continue
            table = mdef.get_table(service)  # fresh wiring: hooks + codec
            if table.key_codec is not None:
                # re-intern the saved key layout IN ORDER so saved rows land
                # on the same ids (wire transport turns tuples into lists —
                # deep-tuple them back into hashable keys). If ANY key lands
                # on a different row — something was interned before the
                # restore, or the codec overflowed — the saved value arrays
                # would map to the WRONG keys: leave the table cold (it
                # refetches correctly) rather than serve silently wrong rows
                layout_ok = True
                try:
                    for row, args in enumerate(decode(entry.get("keys", []))):
                        if table.key_codec.acquire(_deep_tuple(args)) != row:
                            layout_ok = False
                            break
                except KeyError:
                    layout_ok = False
                if not layout_ok:
                    log.warning(
                        "checkpoint: table %s.%s key layout diverged from the "
                        "snapshot (keys interned before restore?); left cold",
                        entry["s"], entry["m"],
                    )
                    continue
            try:
                table.import_state(entry["state"])
            except ValueError as e:
                log.warning("checkpoint: table %s.%s shape mismatch (%s); "
                            "left cold", entry["s"], entry["m"], e)
                continue
            restored += 1
        return restored

    @staticmethod
    def save(
        hub: FusionHub,
        path: str,
        oplog_position: int = 0,
        *,
        commit_floor: Any = _FLOOR_UNSET,
        log_store: Any = None,
    ) -> dict:
        """Snapshot + persist atomically: temp file, fsync, rename, payload
        checksum in the header (checkpoint/durable.py). A crash at ANY
        point leaves either the previous snapshot or a temp file the
        restore path never looks at — never a truncated ``path``.

        Pass ``log_store`` (or an explicit ``commit_floor``) so the
        snapshot carries a trim-safety floor; without one it clamps every
        trim while retained (see ``HubCheckpoint.snapshot``)."""
        snap = HubCheckpoint.snapshot(
            hub, oplog_position, commit_floor=commit_floor, log_store=log_store
        )
        write_snapshot_file(path, snap)
        return snap

    @staticmethod
    def restore(
        hub: FusionHub,
        path: str,
        services: Optional[Dict[str, Any]] = None,
    ) -> RestoreResult:
        """Warm-boot ``hub`` from a snapshot file.

        Each snapshot node becomes a registered CONSISTENT computed carrying
        its ORIGINAL version, so op-log replay's version-matched invalidation
        semantics hold across the restart. Dependency edges re-link through
        the normal ``add_used`` path, which also feeds the device mirror
        hooks — the TPU CSR rebuilds itself from restored host truth.

        ``services`` maps snapshot service names to live instances; defaults
        to the hub's service container keyed by type name.

        Raises :class:`CorruptSnapshotError` for a torn/garbled file —
        ``CheckpointManager.restore_latest`` catches it and falls back to
        the next-newest snapshot.
        """
        snap = read_snapshot_file(path)
        if snap.get("format") != _FORMAT_VERSION:
            raise CorruptSnapshotError(
                f"unsupported checkpoint format {snap.get('format')!r}"
            )
        if services is None:
            services = _services_by_name(hub)
        cluster = DurableHubState.cluster_of(snap)
        result = RestoreResult(
            oplog_position=DurableHubState.watermark_of(snap),
            saved_at=float(snap.get("saved_at", 0.0)),
            epoch=int(cluster.get("epoch", 0) or 0),
            snapshot_map=cluster.get("shard_map"),
            commit_floor=(snap.get("oplog") or {}).get("commit_floor"),
            subscriptions=len(snap.get("subscriptions", ())),
        )
        restored: List[Optional[Computed]] = []
        for entry in snap["nodes"]:
            c = HubCheckpoint._restore_node(hub, services, entry)
            restored.append(c)
            if c is None:
                result.skipped += 1
            else:
                result.computeds.append(c)
        # tables restore BEFORE the edge-version invalidation loop: the
        # restored nodes carry the scalar→table mark_row_stale hook (they are
        # created through ComputeMethodFunction.create_computed), so any
        # provably-stale invalidation below must find the warm rows already
        # materialized and mark them stale — not land on a cold table and
        # then get overwritten by a later warm import
        result.tables = HubCheckpoint._restore_tables(hub, services, snap)
        for di, ui, used_version in snap.get("edges", ()):
            dep, used = restored[di], restored[ui]
            if dep is None or used is None:
                continue
            dep.add_used(used)
            result.edges += 1
            if int(used.version) != used_version:
                # a live computed displaced the snapshotted dependency: the
                # dependent's warm value was produced against a version that
                # no longer exists — it is provably stale
                dep.invalidate(immediately=True)
        return result

    @staticmethod
    def _restore_node(hub: FusionHub, services: Dict[str, Any], entry: dict) -> Optional[Computed]:
        service = services.get(entry["s"])
        if service is None:
            log.warning("checkpoint: service %r not registered; node skipped", entry["s"])
            return None
        method = getattr(service, entry["m"], None)
        method_def = getattr(method, "__compute_method_def__", None)
        if method_def is None:
            log.warning("checkpoint: %s.%s is not a compute method; node skipped",
                        entry["s"], entry["m"])
            return None
        args = tuple(decode(entry["a"]))
        if not (args and type(args[-1]) is KwArgsTail):  # already canonical
            try:
                # snapshots from before a key-normalization change store
                # args under the OLD canonical form (e.g. a defaulted
                # call's short tuple); re-normalizing keeps restored nodes
                # reachable by post-restore reads instead of orphaning them
                args = method_def.bind_args(service, args, {})
            except Exception:  # noqa: BLE001 — legacy key: keep raw
                pass
        function = method_def.get_function(service)
        input = ComputeMethodInput(method_def, service, args, function)
        existing = hub.registry.get(input)
        if existing is not None and existing.is_consistent:
            return existing  # live state wins over the snapshot
        # route through the function's create_computed — NOT a bare
        # Computed() — so restored nodes carry the same lifecycle hooks a
        # freshly computed node gets (in particular the table-backed
        # scalar→table mark_row_stale hook; a bare node would let post-
        # restore invalidations recompute the scalar while read_batch/
        # read_keys kept serving the stale warm row forever)
        computed = function.create_computed(input, LTag(entry["v"]))
        computed.try_set_output(Result.ok(decode(entry["o"])))
        hub.registry.register(computed)
        computed.renew_timeouts(True)  # arm keep-alive so warm entries survive
        return computed


# ---------------------------------------------------------------- manager
class CheckpointManager:
    """Numbered hub snapshots in a directory: ``fusion-ckpt-{n}.bin``.

    The durability contract (ISSUE 6): saves are atomic + checksummed
    (checkpoint/durable.py), ``restore_latest`` falls back past corrupt or
    torn snapshots to the newest VALID one (quarantining what it skipped
    as ``*.corrupt`` so the evidence survives for operators but never
    blocks the next restore), and ``snapshot_floor()`` hands the oplog
    trimmer the oldest commit time any retained snapshot's replay tail
    still needs — trimming past it would strand a warm rejoin."""

    _PATTERN = re.compile(r"fusion-ckpt-(\d+)\.bin$")

    def __init__(self, directory: str, keep: int = 3, events=None):
        self.directory = directory
        self.keep = keep
        if events is None:
            from ..resilience.events import global_events

            events = global_events()
        self.events = events
        self.saves = 0
        self.corrupt_skipped = 0
        # headerless (legacy v1) files need a FULL read to tell restorable
        # from garbage; the trimmer calls snapshot_floor() every GC cycle,
        # so the verdict is cached per (path, mtime, size)
        self._legacy_probe: Dict[str, Tuple[float, int, bool]] = {}
        os.makedirs(directory, exist_ok=True)

    def _steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.directory):
            m = self._PATTERN.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def path_of(self, step: int) -> str:
        return os.path.join(self.directory, f"fusion-ckpt-{step}.bin")

    def latest_step(self) -> Optional[int]:
        steps = self._steps()
        return steps[-1] if steps else None

    def _rotate(self) -> None:
        for old in self._steps()[: -self.keep]:
            try:
                os.remove(self.path_of(old))
            except OSError:
                pass

    def save(
        self,
        hub: FusionHub,
        oplog_position: int = 0,
        *,
        commit_floor: Any = _FLOOR_UNSET,
        log_store: Any = None,
    ) -> int:
        step = (self.latest_step() or 0) + 1
        HubCheckpoint.save(
            hub,
            self.path_of(step),
            oplog_position,
            commit_floor=commit_floor,
            log_store=log_store,
        )
        self.saves += 1
        self._rotate()
        return step

    def save_durable(
        self,
        hub: FusionHub,
        *,
        reader=None,
        log_store=None,
        member=None,
        router=None,
        rpc_hub=None,
    ) -> int:
        """Save the epoch-consistent durable snapshot: the hub body keyed
        to ``(shard-map epoch, oplog watermark)`` plus live fan-out
        subscriptions — what :func:`~stl_fusion_tpu.cluster.rejoin.
        warm_rejoin` restores. Any cluster/oplog handle may be None (a
        standalone hub snapshots with epoch 0)."""
        snap = DurableHubState.snapshot(
            hub,
            reader=reader,
            log_store=log_store,
            member=member,
            router=router,
            rpc_hub=rpc_hub,
        )
        step = (self.latest_step() or 0) + 1
        write_snapshot_file(self.path_of(step), snap)
        self.saves += 1
        self._rotate()
        return step

    def _quarantine(self, step: int, error: Exception) -> None:
        """Skip-and-log a snapshot restore_latest could not trust: ledger
        event + rename to ``*.corrupt`` (kept on disk as evidence, invisible
        to ``_steps`` so it never blocks the fallback again)."""
        self.corrupt_skipped += 1
        path = self.path_of(step)
        log.warning("checkpoint: snapshot %s unreadable (%s); falling back",
                    path, error)
        self.events.record("snapshot_corrupt", f"{os.path.basename(path)}: {error}")
        try:
            os.replace(path, f"{path}.corrupt")
        except OSError:
            pass

    def restore_latest(
        self, hub: FusionHub, services: Optional[Dict[str, Any]] = None
    ) -> Optional[RestoreResult]:
        """Restore from the newest VALID snapshot: corrupt/torn files are
        quarantined and the walk falls back to the next-newest. Returns
        None when no restorable snapshot exists (cold boot)."""
        for step in reversed(self._steps()):
            try:
                return HubCheckpoint.restore(hub, self.path_of(step), services)
            except CorruptSnapshotError as e:
                self._quarantine(step, e)
            except FileNotFoundError:
                continue  # rotated away between _steps() and open
            except OSError as e:
                # transient I/O error (EIO under load, NFS hiccup) on a
                # possibly-VALID snapshot: fall back for this restore but
                # leave the file in place — quarantining would permanently
                # demote a good snapshot over a one-off read failure
                log.warning("checkpoint: snapshot %s unreadable (%s); "
                            "skipping without quarantine", self.path_of(step), e)
                self.events.record(
                    "snapshot_skipped",
                    f"{os.path.basename(self.path_of(step))}: {e}",
                )
        return None

    def snapshot_floor(self) -> Optional[float]:
        """Oldest oplog commit time a retained snapshot still needs for
        its replay tail — the trimmer's snapshot clamp (min over retained
        READABLE headers: a snapshot the restore walk would quarantine
        contributes nothing, so a corrupt file never pins GC forever).
        None when no durable snapshot exists."""
        floors = []
        for step in self._steps():
            path = self.path_of(step)
            header = read_snapshot_header(path)
            if header is None:
                # no v2 header: either garbage (restore would quarantine
                # it — contributes nothing) or a RESTORABLE legacy v1 file,
                # which restore_latest happily loads; only the full read
                # can tell them apart, and a restorable snapshot with no
                # floor must clamp ALL trims or the trimmer eats the tail
                # its warm rejoin needs. The full read is cached per
                # (mtime, size): the trimmer polls this every GC cycle and
                # legacy payloads can be large.
                if self._probe_legacy(path):
                    return 0.0
                continue
            floor = header.get("commit_floor")
            if floor is None:
                # v2 but FLOOR-LESS: a plain save() with no log_store/
                # commit_floor (the snapshot's watermark may lag the head,
                # so no floor is derivable). Replay needs are unbounded
                # below — no trim is safe while it is retained. None would
                # instead mean "no clamp" and lose the tail; deployments
                # that trim should snapshot via save_durable or pass
                # log_store= (see HubCheckpoint.snapshot).
                return 0.0
            floors.append(floor)
        return min(floors) if floors else None

    def _probe_legacy(self, path: str) -> bool:
        """Whether a headerless snapshot file is RESTORABLE legacy v1 (it
        must clamp trims) as opposed to garbage (it must not pin the log).
        One full read per (mtime, size); a transient OSError is NOT cached
        — it says nothing about the file."""
        try:
            st = os.stat(path)
        except OSError:
            return False
        key = (st.st_mtime, st.st_size)
        cached = self._legacy_probe.get(path)
        if cached is not None and (cached[0], cached[1]) == key:
            return cached[2]
        try:
            read_snapshot_file(path)
            verdict = True
        except CorruptSnapshotError:
            verdict = False
        except OSError:
            return False
        self._legacy_probe[path] = (key[0], key[1], verdict)
        return verdict
