"""Durable snapshot envelope + epoch-consistent cluster state (ISSUE 6).

The on-disk contract the warm-rejoin path (cluster/rejoin.py) restores
through. Two layers:

- **Envelope v2** — every snapshot file is written atomically (temp +
  flush + fsync + rename + directory fsync) and carries a one-line header
  ``FUSNAP2 <sha256> <watermark> <commit_floor>`` over the payload. A torn
  or bit-flipped file fails the checksum and raises
  :class:`CorruptSnapshotError` instead of deserializing garbage; the
  header alone is enough for the oplog trimmer's snapshot clamp
  (``CheckpointManager.snapshot_floor``) without reading the payload.
  Files written before this format (no magic) still load as legacy v1.
- **DurableHubState** — the epoch-consistent snapshot the issue names:
  the :class:`~stl_fusion_tpu.checkpoint.HubCheckpoint` body (computeds +
  dependency edges + MemoTable columnar state, i.e. the host truth the
  CSR mirror re-derives from) keyed to a ``(shard-map epoch, oplog
  watermark)`` pair, plus the server's live fan-out subscriptions (which
  keys which peers were subscribed to at snapshot time — the sockets die
  with the process, but the restore report and flight recorder name what
  was being served, and the rejoin fence can reason about them).

Consistency note: the pair is captured with the watermark read FIRST and
the hub state after — so the snapshot's warm values reflect *at least*
every oplog entry at/below the watermark. Replaying the tail above the
watermark on restore can re-invalidate an entry that was already fresh
(idempotent, version-matched) but can never miss a committed operation —
the same at-least-once rule the reader's own watermark advance follows.
"""
from __future__ import annotations

import hashlib
import logging
import os
import time
from typing import Any, Dict, List, Optional

from ..utils.serialization import dumps, loads

log = logging.getLogger("stl_fusion_tpu")

__all__ = [
    "CorruptSnapshotError",
    "DurableHubState",
    "atomic_write",
    "read_snapshot_file",
    "read_snapshot_header",
    "write_snapshot_file",
]

_MAGIC = b"FUSNAP2"


class CorruptSnapshotError(Exception):
    """A snapshot file that exists but cannot be trusted: truncated mid-
    write, checksum mismatch, or an undecodable payload. Restore paths
    catch this and fall back to the next-newest snapshot instead of
    serving (or crashing on) garbage."""


# ---------------------------------------------------------------- envelope
def _fsync_dir(path: str) -> None:
    """Durability for the RENAME itself — without the directory fsync a
    crash can forget the new name while keeping the inode (best-effort:
    not every platform lets you open a directory)."""
    try:
        fd = os.open(os.path.dirname(os.path.abspath(path)) or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, write_fn) -> None:
    """THE crash-safe write sequence — temp file, ``write_fn(f)`` produces
    the bytes, flush + fsync, rename over ``path``, directory fsync. A
    crash at any point leaves either the previous file or an ignored temp,
    never a truncated ``path``. Envelope snapshots and graph npz snapshots
    both ride this one copy so durability fixes can't drift apart."""
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):  # crash-path hygiene for tests/retries
            try:
                os.remove(tmp)
            except OSError:
                pass
    _fsync_dir(path)


def write_snapshot_file(path: str, snap: dict) -> int:
    """Atomically persist ``snap``: temp file + fsync + rename, payload
    checksummed in the header. Returns the bytes written."""
    payload = dumps(snap)
    digest = hashlib.sha256(payload).hexdigest()
    oplog = snap.get("oplog") or {}
    watermark = int(oplog.get("watermark", snap.get("oplog_position", 0)) or 0)
    floor = oplog.get("commit_floor")
    header = b"%s %s %d %s\n" % (
        _MAGIC,
        digest.encode(),
        watermark,
        (b"-" if floor is None else repr(float(floor)).encode()),
    )

    def _write(f):
        f.write(header)
        f.write(payload)

    atomic_write(path, _write)
    return len(header) + len(payload)


def _parse_header(line: bytes) -> Optional[dict]:
    parts = line.strip().split(b" ")
    if len(parts) != 4 or parts[0] != _MAGIC:
        return None
    try:
        return {
            "checksum": parts[1].decode(),
            "watermark": int(parts[2]),
            "commit_floor": None if parts[3] == b"-" else float(parts[3]),
        }
    except (ValueError, UnicodeDecodeError):
        return None


def read_snapshot_header(path: str) -> Optional[dict]:
    """The ``(watermark, commit_floor, checksum)`` header of a v2 snapshot
    WITHOUT reading the payload — the trimmer's clamp reads this on every
    GC cycle. None for legacy/garbled files (they contribute no floor: a
    file the restore path would skip must not pin the log forever)."""
    try:
        with open(path, "rb") as f:
            return _parse_header(f.readline(256))
    except OSError:
        return None


def read_snapshot_file(path: str) -> dict:
    """Load + verify a snapshot. Raises :class:`CorruptSnapshotError` for
    anything untrustworthy; ``OSError`` passes through for a missing file."""
    with open(path, "rb") as f:
        first = f.readline(256)
        header = _parse_header(first)
        if header is not None:
            payload = f.read()
            digest = hashlib.sha256(payload).hexdigest()
            if digest != header["checksum"]:
                raise CorruptSnapshotError(
                    f"{path}: checksum mismatch (torn write?) — "
                    f"header {header['checksum'][:12]}…, payload {digest[:12]}…"
                )
        else:
            payload = first + f.read()  # legacy v1: bare serialized dict
    try:
        snap = loads(payload)
    except Exception as e:  # noqa: BLE001 — any decode failure is corruption
        raise CorruptSnapshotError(f"{path}: undecodable payload: {e!r}") from e
    if not isinstance(snap, dict):
        raise CorruptSnapshotError(f"{path}: payload is not a snapshot dict")
    return snap


# ---------------------------------------------------------------- state
class DurableHubState:
    """Builds/consumes the epoch-consistent snapshot dict. Pure functions
    over the existing :class:`HubCheckpoint` body — cluster/oplog objects
    are optional so a standalone (non-cluster) hub snapshots the same way
    with epoch 0 and watermark from its log store."""

    @staticmethod
    def snapshot(
        hub,
        *,
        reader=None,
        log_store=None,
        member=None,
        router=None,
        rpc_hub=None,
    ) -> dict:
        from . import HubCheckpoint  # late: __init__ imports this module

        # watermark FIRST, hub state second — see the consistency note in
        # the module docstring (tail replay is at-least-once, never lossy)
        if reader is not None:
            watermark = int(reader.watermark)
            commit_floor = reader._last_commit_time
        elif log_store is not None:
            watermark = int(log_store.last_index())
            commit_floor = None
        else:
            watermark = 0
            commit_floor = None
        if commit_floor is None:
            # no processed-record timestamp to anchor on: the snapshot
            # moment itself is the floor (entries above the watermark are
            # appended at/after now, modulo cross-host clock skew — the
            # trimmer's max_age slack absorbs reasonable skew)
            commit_floor = time.time()
        snap = HubCheckpoint.snapshot(hub, oplog_position=watermark)
        snap["oplog"] = {"watermark": watermark, "commit_floor": float(commit_floor)}
        smap = None
        if member is not None:
            smap = member.shard_map
        elif router is not None:
            smap = router.shard_map
        if smap is not None:
            snap["cluster"] = {
                "epoch": int(smap.epoch),
                "member_id": getattr(member, "member_id", None),
                "shard_map": smap.to_wire(),
            }
        if rpc_hub is not None:
            snap["subscriptions"] = DurableHubState.snapshot_subscriptions(rpc_hub)
        return snap

    @staticmethod
    def snapshot_subscriptions(rpc_hub) -> List[dict]:
        """Every live inbound ``$sys-c`` subscription this server holds:
        which peer, which call shape, at which version. The links die with
        the process — clients re-subscribe on reconnect — but the record
        makes the restore report honest about what was being served and
        gives the auditor a before/after population to compare."""
        from ..utils.serialization import encode

        subs: List[dict] = []
        for ref, peer in list(getattr(rpc_hub, "peers", {}).items()):
            for call in list(getattr(peer, "inbound_calls", {}).values()):
                computed = getattr(call, "computed", None)
                message = getattr(call, "message", None)
                if computed is None or message is None:
                    continue
                try:
                    args = encode(loads(message.argument_data))
                except Exception:  # noqa: BLE001 — unserializable: count, don't die
                    args = None
                subs.append(
                    {
                        "peer": ref,
                        "s": message.service,
                        "m": message.method,
                        "a": args,
                        "v": computed.version.format(),
                    }
                )
        return subs

    @staticmethod
    def cluster_of(snap: dict) -> Dict[str, Any]:
        return snap.get("cluster") or {"epoch": 0, "member_id": None, "shard_map": None}

    @staticmethod
    def watermark_of(snap: dict) -> int:
        oplog = snap.get("oplog") or {}
        return int(oplog.get("watermark", snap.get("oplog_position", 0)) or 0)
