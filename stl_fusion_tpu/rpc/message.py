"""RpcMessage — the wire format.

Re-expression of src/Stl.Rpc/Infrastructure/RpcMessage.cs:3-35:
``{CallTypeId, CallId, Service, Method, ArgumentData, Headers}``. Arguments
travel pre-serialized (TextOrBytes ≈ bytes here) so the message envelope is
codec-agnostic; headers are (key, value) string pairs (the Fusion client
rides its ``@version`` LTag header here, FusionRpcHeaders.cs).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

from ..utils.serialization import wire_type

__all__ = [
    "RpcMessage",
    "SYSTEM_SERVICE",
    "COMPUTE_SYSTEM_SERVICE",
    "TABLE_SYSTEM_SERVICE",
    "DIAG_SYSTEM_SERVICE",
    "MEMBER_SYSTEM_SERVICE",
    "VERSION_HEADER",
]

SYSTEM_SERVICE = "$sys"
COMPUTE_SYSTEM_SERVICE = "$sys-c"
TABLE_SYSTEM_SERVICE = "$sys-t"  # per-TABLE row fences (remote_table.py)
DIAG_SYSTEM_SERVICE = "$sys-d"  # cross-peer introspection (diagnostics/explain.py)
MEMBER_SYSTEM_SERVICE = "$sys-m"  # cluster membership + shard-map frames (cluster/)
VERSION_HEADER = "@version"

CALL_TYPE_PLAIN = 0
CALL_TYPE_COMPUTE = 1


@wire_type
@dataclass(frozen=True)
class RpcMessage:
    call_type_id: int
    call_id: int
    service: str
    method: str
    argument_data: bytes
    headers: tuple = ()  # ((key, value), ...)

    def header(self, key: str) -> Optional[str]:
        for k, v in self.headers:
            if k == key:
                return v
        return None

    def __repr__(self) -> str:
        return (
            f"RpcMessage(#{self.call_id} {self.service}.{self.method} "
            f"type={self.call_type_id} {len(self.argument_data)}B)"
        )
