"""RPC layer — distributed communication backend (SURVEY.md §2.4)."""
from .calls import RpcCallTypeRegistry, RpcInboundCall, RpcOutboundCall
from .fanout import (
    ComputeFanoutIndex,
    WaveValuePublisher,
    install_compute_fanout,
    install_value_publisher,
)
from .hub import RpcClientProxy, RpcHub, consistent_hash_router
from .outbox import PeerOutbox
from .message import COMPUTE_SYSTEM_SERVICE, SYSTEM_SERVICE, VERSION_HEADER, RpcMessage
from .peer import ConnectionState, RpcClientPeer, RpcPeer, RpcServerPeer
from .registry import RpcMethodDef, RpcServiceDef, RpcServiceRegistry, rpc_no_wait
from .http_gateway import FusionHttpServer, HttpSessionMiddleware, RestClient, RestError
from .middleware import (
    bind_peer_session,
    call_logging_middleware,
    default_session_replacer_middleware,
    peer_session,
)
from .testing import RpcMultiServerTestTransport, RpcTestTransport

__all__ = [
    "ComputeFanoutIndex",
    "PeerOutbox",
    "WaveValuePublisher",
    "install_compute_fanout",
    "install_value_publisher",
    "RpcCallTypeRegistry",
    "RpcInboundCall",
    "RpcOutboundCall",
    "RpcClientProxy",
    "RpcHub",
    "consistent_hash_router",
    "COMPUTE_SYSTEM_SERVICE",
    "SYSTEM_SERVICE",
    "VERSION_HEADER",
    "RpcMessage",
    "ConnectionState",
    "RpcClientPeer",
    "RpcPeer",
    "RpcServerPeer",
    "RpcMethodDef",
    "RpcServiceDef",
    "RpcServiceRegistry",
    "rpc_no_wait",
    "RpcTestTransport",
    "RpcMultiServerTestTransport",
    "FusionHttpServer",
    "HttpSessionMiddleware",
    "RestClient",
    "RestError",
    "bind_peer_session",
    "call_logging_middleware",
    "default_session_replacer_middleware",
    "peer_session",
]
