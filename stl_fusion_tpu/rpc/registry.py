"""Service/method registry.

Re-expression of src/Stl.Rpc/Configuration/RpcServiceRegistry.cs:9-50 +
RpcServiceDef/RpcMethodDef: name ↔ implementation mapping with conflict
checks, per-method metadata (no-wait), and the invocation path the inbound
side uses. A service is any object; its RPC surface is its public async
methods (or an explicit method list).
"""
from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

__all__ = ["RpcMethodDef", "RpcServiceDef", "RpcServiceRegistry", "rpc_no_wait"]


def rpc_no_wait(fn: Callable) -> Callable:
    """Marks a method fire-and-forget (≈ RpcNoWait return type, RpcNoWait.cs):
    no call registration, no result message."""
    fn.__rpc_no_wait__ = True  # type: ignore[attr-defined]
    return fn


@dataclass(frozen=True)
class RpcMethodDef:
    name: str
    fn: Callable  # bound async callable
    no_wait: bool = False


class RpcServiceDef:
    def __init__(self, name: str, implementation: Any):
        self.name = name
        self.implementation = implementation
        self.methods: Dict[str, RpcMethodDef] = {}
        for mname in dir(type(implementation)):
            if mname.startswith("_"):
                continue
            attr = getattr(type(implementation), mname, None)
            if attr is None or not inspect.iscoroutinefunction(attr):
                continue
            bound = getattr(implementation, mname)
            self.methods[mname] = RpcMethodDef(
                mname, bound, no_wait=getattr(attr, "__rpc_no_wait__", False)
            )

    def method(self, name: str) -> RpcMethodDef:
        m = self.methods.get(name)
        if m is None and not name.startswith("_") and getattr(
            self.implementation, "__rpc_dynamic__", False
        ):
            # dynamic services (routing proxies) synthesize methods via
            # __getattr__. Never cached: remote callers control `name`, and
            # caching would let them grow this dict without bound.
            try:
                fn = getattr(self.implementation, name)
            except AttributeError:
                fn = None
            if fn is None or not inspect.iscoroutinefunction(fn):
                raise LookupError(f"method {self.name}.{name} is not registered")
            return RpcMethodDef(name, fn)
        if m is None:
            raise LookupError(f"method {self.name}.{name} is not registered")
        return m


class RpcServiceRegistry:
    def __init__(self):
        self._services: Dict[str, RpcServiceDef] = {}

    def add(self, name: str, implementation: Any) -> RpcServiceDef:
        if name in self._services:
            raise ValueError(f"service {name!r} is already registered")
        sd = RpcServiceDef(name, implementation)
        self._services[name] = sd
        return sd

    def get(self, name: str) -> Optional[RpcServiceDef]:
        return self._services.get(name)

    def require(self, name: str) -> RpcServiceDef:
        sd = self._services.get(name)
        if sd is None:
            raise LookupError(f"service {name!r} is not registered")
        return sd

    async def invoke(self, service: str, method: str, args: list) -> Any:
        return await self.require(service).method(method).fn(*args)

    def dump(self) -> str:
        lines = []
        for name, sd in sorted(self._services.items()):
            lines.append(f"{name} -> {type(sd.implementation).__name__}: "
                         + ", ".join(sorted(sd.methods)))
        return "\n".join(lines)
