"""RpcHub — root of the RPC stack + client proxies + call routing.

Re-expression of src/Stl.Rpc/RpcHub.cs:7-93 (peer registry, lazy peer
start), Configuration/RpcDefaultDelegates.cs (the ``RpcCallRouter`` — THE
sharding/routing point: route a call to a peer by key, e.g. consistent
hash over a server pool, samples/MultiServerRpc/Program.cs:58-76), and
Infrastructure/RpcClientInterceptor.cs (proxy → outbound call, with local
fallback when the router returns None — the basis of Router/Distributed
service modes, FusionBuilder.cs:222-320).
"""
from __future__ import annotations

import itertools
import logging
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional, Sequence

from ..diagnostics.metrics import global_metrics
from ..utils.async_utils import ChannelPair, TaskSet
from .calls import RpcCallTypeRegistry, RpcOutboundCall
from .message import RpcMessage
from .peer import RpcClientPeer, RpcPeer, RpcServerPeer
from .registry import RpcServiceRegistry

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["RpcHub", "RpcClientProxy", "RpcConfigurationError", "consistent_hash_router"]

#: router: (service, method, args) -> peer ref (str) or None for local
RpcCallRouter = Callable[[str, str, tuple], Optional[str]]


class RpcConfigurationError(RuntimeError):
    """A peer cannot ever connect because the hub is misconfigured (no
    client connector, unknown peer ref, ...). The default
    ``unrecoverable_error_detector`` treats exactly this class (plus the
    ``LookupError`` connectors raise for unknown refs) as terminal — a
    transient transport failure surfacing as a broad builtin such as
    ``RuntimeError`` keeps the reconnect loop alive, matching the
    reference's narrow connection-unrecoverable set
    (Configuration/RpcDefaultDelegates.cs)."""


class RpcHub:
    def __init__(self, name: str = "rpc"):
        self.name = name
        self.service_registry = RpcServiceRegistry()
        self.call_types = RpcCallTypeRegistry()
        self.peers: Dict[str, RpcPeer] = {}
        #: hub-lifetime outbound call id sequence, shared by every peer
        #: (see RpcPeer._call_id_counter for why per-peer counters are a
        #: stale-read bug after peer re-creation)
        self._outbound_call_ids = itertools.count(1)
        #: transport factory for client peers: async (peer) -> ChannelPair
        self.client_connector: Optional[Callable[[RpcClientPeer], Awaitable[ChannelPair]]] = None
        #: hub-lifecycle owner for fire-and-forget side tasks (cache
        #: synchronize, etc. — the fusionlint FL003 contract): strong refs
        #: until settled, cancelled at stop()
        self.side_tasks = TaskSet(name=f"rpc-hub:{name}")
        self.call_router: RpcCallRouter = lambda service, method, args: "default"
        #: 0 = unlimited; n ≥ 1 serializes non-system inbound calls per peer
        #: through an n-permit gate (≈ InboundConcurrencyLevel, RpcPeer.cs:20)
        self.inbound_concurrency_level: int = 0
        self.max_connect_attempts = 10_000
        #: connect errors this returns True for abort the reconnect loop at
        #: once instead of backing off (≈ RpcUnrecoverableErrorDetector,
        #: Configuration/RpcDefaultDelegates.cs; RpcPeer.cs:268-274).
        #: Default: ONLY declared configuration errors are terminal —
        #: RpcConfigurationError ("no client connector") and the
        #: LookupError connectors raise for unknown peer refs
        #: (websocket_multi_connector). Everything else, including
        #: RuntimeError/ValueError bubbling out of third-party transport
        #: internals, is treated as transient and retried with backoff.
        self.unrecoverable_error_detector: Callable[[BaseException], bool] = (
            lambda e: isinstance(e, (RpcConfigurationError, LookupError))
            and not isinstance(e, (ConnectionError, OSError, TimeoutError))
        )
        #: $sys-c dispatch hook, installed by the fusion client layer
        self.compute_system_handler: Optional[Callable[[RpcPeer, RpcMessage], None]] = None
        #: True (default): server-side invalidation pushes coalesce through
        #: each peer's outbox into one ``$sys-c.invalidate_batch`` frame per
        #: drain tick (version-deduped). False: the original one-frame-per-
        #: key ``$sys-c.invalidate`` path — kept for wire compat with old
        #: clients and as the A/B baseline (perf/fanout_path.py). Clients
        #: always understand BOTH frame kinds regardless of this flag.
        self.coalesce_invalidations: bool = True
        #: optional ComputeFanoutIndex (rpc/fanout.py): lets a device
        #: wave's newly-mask drain straight into per-peer batches
        self.compute_fanout: Optional[Any] = None
        #: optional WaveValuePublisher (rpc/fanout.py, ISSUE 11 level 2):
        #: SERVER side of the publish-on-wave value plane — keys with a
        #: standing publish registration answer wave fences with pushed
        #: ``$sys-c.value_block`` frames instead of plain invalidations
        self.value_publisher: Optional[Any] = None
        #: CLIENT side of the value plane (the EdgeNode installs itself):
        #: routes inbound ``value_block`` frames + fallback fences for
        #: retired publish-mode calls (``on_value_block`` /
        #: ``on_block_fence``)
        self.value_plane_client: Optional[Any] = None
        #: $sys-t dispatch hook (per-table row fences + subscriptions),
        #: installed by client/remote_table.py on both ends
        self.table_system_handler: Optional[Callable[[RpcPeer, RpcMessage], None]] = None
        #: $sys-d dispatch hook (cross-peer explain/introspection), installed
        #: by diagnostics.explain.install_explain on both ends; may be an
        #: ASYNC callable (the server side awaits a registry peek + a reply
        #: send) — the peer dispatch awaits coroutine results
        self.diag_system_handler: Optional[Callable[[RpcPeer, RpcMessage], Any]] = None
        #: $sys-m dispatch hook (cluster membership: heartbeats, suspicions,
        #: shard-map pushes), installed by cluster.membership.ClusterMember
        #: on members and cluster.router.install_cluster_client on clients;
        #: may be async (map replies) — dispatched like $sys-d
        self.member_system_handler: Optional[Callable[[RpcPeer, RpcMessage], Any]] = None
        #: composable middleware chains (≈ RpcInboundMiddleware /
        #: RpcOutboundMiddleware, Stl.Rpc/Infrastructure/): each entry is
        #: ``async (peer, message, nxt)`` where ``await nxt(message)``
        #: continues the chain (pass a modified message to rewrite).
        #: Inbound runs around message dispatch; outbound around ``send``
        #: (first sends only — reconnect re-sends replay the original call
        #: messages without re-running the chain).
        self.inbound_middlewares: List[Callable] = []
        self.outbound_middlewares: List[Callable] = []
        #: dial gates: each is ``async (peer) -> None``, awaited before every
        #: client dial. A gate that parks is a quarantine — the peer circuit
        #: breaker (resilience/breaker.py) holds flapping peers here so
        #: reconnect re-send storms can't amplify
        self.connect_gates: List[Callable[[RpcClientPeer], Awaitable[None]]] = []
        #: local service fallback for routing proxies
        self.local_services: Dict[str, Any] = {}
        # /metrics exposure: weak-registered pull-time collector — counters
        # stay plain attributes on the hot paths; the registry sums across
        # every live hub only when someone actually scrapes (ISSUE 3)
        global_metrics().register_collector(self, RpcHub._collect_metrics)
        # non-additive: the worst pending age across hubs, never the sum
        global_metrics().set_aggregation("fusion_outbox_pending_age_ms", "max")

    def _collect_metrics(self) -> dict:
        s = self.fanout_stats()
        out = {
            "fusion_outbox_queued": s["queued"],
            "fusion_outbox_pending_invalidations": s["pending_invalidations"],
            "fusion_outbox_messages_sent_total": s["messages_sent"],
            "fusion_invalidations_posted_total": s["invalidations_posted"],
            "fusion_invalidations_coalesced_total": s["invalidations_coalesced"],
            "fusion_batch_frames_sent_total": s["batch_frames_sent"],
            "fusion_batch_keys_sent_total": s["batch_keys_sent"],
            "fusion_outbox_pending_dropped_total": s["pending_dropped"],
            "fusion_outbox_drain_faults_total": s["drain_faults"],
            "fusion_rpc_peers": len(self.peers),
        }
        fi = s.get("fanout_index")
        if fi is not None:
            out["fusion_fanout_subscriptions"] = fi["subscriptions"]
            out["fusion_fanout_drained_total"] = fi["drained_total"]
            out["fusion_fanout_waves_seen_total"] = fi["waves_seen"]
        # flush-tick lag gauge: how long the OLDEST pending invalidation has
        # sat coalescing (0 when nothing is pending). The shipped-frame lag
        # distribution is the fusion_outbox_flush_lag_ms histogram.
        oldest = None
        for peer in self.peers.values():
            ob = peer._outbox
            if ob is not None and ob._pending_since is not None:
                if oldest is None or ob._pending_since < oldest:
                    oldest = ob._pending_since
        out["fusion_outbox_pending_age_ms"] = (
            (time.perf_counter() - oldest) * 1e3 if oldest is not None else 0.0
        )
        return out

    # ------------------------------------------------------------------ server side
    def add_service(self, name: str, implementation: Any):
        """Expose a service to inbound calls."""
        self.service_registry.add(name, implementation)
        self.local_services[name] = implementation
        return implementation

    def server_peer(self, ref: str) -> RpcServerPeer:
        peer = self.peers.get(ref)
        if peer is None:
            peer = RpcServerPeer(self, ref)
            self.peers[ref] = peer
        return peer  # type: ignore[return-value]

    # ------------------------------------------------------------------ client side
    def client_peer(self, ref: str = "default") -> RpcClientPeer:
        peer = self.peers.get(ref)
        if peer is None:
            peer = RpcClientPeer(self, ref)
            self.peers[ref] = peer
            peer.start()
        return peer  # type: ignore[return-value]

    async def connect_client(self, peer: RpcClientPeer) -> ChannelPair:
        if self.client_connector is None:
            raise RpcConfigurationError(
                f"hub {self.name!r} has no client connector configured"
            )
        for gate in self.connect_gates:
            await gate(peer)
        return await self.client_connector(peer)

    def client(self, service_name: str, peer_ref: Optional[str] = None) -> "RpcClientProxy":
        """A call proxy for a remote service; without an explicit peer the
        call router picks one per call (routing proxy)."""
        return RpcClientProxy(self, service_name, peer_ref)

    # ------------------------------------------------------------------ calls
    async def call(
        self,
        service: str,
        method: str,
        args: tuple,
        peer_ref: Optional[str] = None,
        call_type_id: int = 0,
        no_wait: bool = False,
    ) -> Any:
        attempts = 0
        while True:
            attempts += 1
            router = self.call_router
            headers: tuple = ()
            if peer_ref is not None:
                # an explicit pin opts OUT of cluster routing — no shard
                # stamp, so the guard never second-guesses the caller
                ref = peer_ref
            elif hasattr(router, "route"):
                # shard-map router: the routing decision carries its own
                # @shard/@epoch stamp (cluster/router.py); a command whose
                # owner is down fails fast RIGHT HERE (never retried below)
                ref, headers = router.route(service, method, args)
            else:
                ref = router(service, method, args)
            if ref is None:
                # router says local (≈ RpcClientInterceptor local fallback)
                local = self.local_services.get(service)
                if local is None:
                    raise LookupError(f"no local implementation for {service!r}")
                return await getattr(local, method)(*args)
            peer = self.client_peer(ref)
            await peer.when_connected()
            outbound_cls = self.call_types.outbound(call_type_id)
            call = outbound_cls(peer, service, method, args, no_wait=no_wait, headers=headers)
            try:
                return await call.invoke()
            except Exception as e:  # noqa: BLE001 — only ShardMovedError is special
                from ..cluster.shard_map import ShardMovedError

                if (
                    not isinstance(e, ShardMovedError)
                    or peer_ref is not None
                    or attempts >= 2
                ):
                    raise
                # the rejection carries the server's current map: apply it
                # and retry ONCE against the new owner (bounded — a second
                # rejection surfaces to the caller)
                if hasattr(router, "note_moved"):
                    router.note_moved(e)

    async def stop(self) -> None:
        # cancel in-flight side tasks, then re-arm: stop() means "stop the
        # current work", and tests reuse a stopped hub for a fresh connect
        await self.side_tasks.aclose()
        self.side_tasks = TaskSet(name=f"rpc-hub:{self.name}")
        for peer in list(self.peers.values()):
            await peer.stop()

    # ------------------------------------------------------------------ diagnostics
    def fanout_stats(self) -> dict:
        """Aggregate outbox/coalescer counters over every peer (plus the
        fanout index's, when installed) — exported through
        ``FusionMonitor.report()`` so the fan-out path is observable."""
        totals = {
            "messages_sent": 0,
            "invalidations_posted": 0,
            "invalidations_coalesced": 0,
            "batch_frames_sent": 0,
            "batch_keys_sent": 0,
            "pending_dropped": 0,
            "drain_faults": 0,
            "queued": 0,
            "pending_invalidations": 0,
        }
        for peer in self.peers.values():
            ob = peer._outbox
            if ob is None:
                continue
            for k, v in ob.stats().items():
                totals[k] += v
        if self.compute_fanout is not None:
            totals["fanout_index"] = self.compute_fanout.stats()
        return totals


class RpcClientProxy:
    """Dynamic proxy: attribute access → remote (or routed) call."""

    def __init__(self, hub: RpcHub, service: str, peer_ref: Optional[str] = None):
        self._hub = hub
        self._service = service
        self._peer_ref = peer_ref

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)

        async def call(*args):
            return await self._hub.call(self._service, method, args, peer_ref=self._peer_ref)

        call.__name__ = method
        return call

    def __repr__(self) -> str:
        return f"RpcClientProxy({self._service} @ {self._peer_ref or '<routed>'})"


def consistent_hash_router(
    peer_refs: Sequence[str], key_arg: int = 0
) -> RpcCallRouter:
    """Shard calls over a peer pool by hashing an argument — the reference's
    MultiServerRpc routing pattern (Program.cs:58-76).

    Since ISSUE 5 this is a thin shim over the cluster's
    :class:`~stl_fusion_tpu.cluster.shard_map.ShardMap` with a STATIC
    member list: same public name and signature, but routing goes
    key → virtual shard → rendezvous owner instead of sha1-mod-N, so
    removing one member from the pool moves only that member's shards
    (~V/N keys) rather than remapping ~(N-1)/N of everything. Routes stay
    sha1-stable across process restarts (never the salted builtin
    ``hash()``). For an ELASTIC pool — membership, epochs, failover,
    fencing — install a ``cluster.ShardMapRouter`` instead."""
    from ..cluster.shard_map import ShardMap

    shard_map = ShardMap.initial(peer_refs)

    def route(service: str, method: str, args: tuple) -> str:
        key = repr(args[key_arg]) if len(args) > key_arg else service
        return shard_map.owner_of(key)

    route.shard_map = shard_map  # introspectable by tests/diagnostics
    return route
