"""PeerOutbox — the per-peer outbound drain queue + invalidation coalescer.

Motivation (ISSUE 2 / VERDICT r5 missing #4): the server's invalidation
fan-out was one awaited ``RpcMessage`` per subscription per peer, each send
serialized through ``RpcPeer.send()`` — at N clients × K subscriptions a
burst paid N·K awaited channel round trips of pure Python. This module
replaces that shape with the coalescing principle the reduction-tree papers
in PAPERS.md argue for, applied to fan-out frames:

- **FIFO drain**: every outbound message routes through one drain task per
  peer, so per-peer delivery order is a property of the QUEUE, not of which
  sender task the event loop woke first (the pre-outbox send() interleaved
  concurrent senders on the raw channel). The awaited-send error contract
  is preserved exactly: ``send()`` resolves when its message hit the
  channel and raises what the channel raised.
- **Invalidation coalescing**: invalidations are not messages until flush
  time. ``post_invalidation(call_id, version)`` drops into a pending map
  (version-deduped — a key invalidated twice between flushes ships once,
  at the latest version); each drain tick flushes the whole map as ONE
  ``$sys-c.invalidate_batch`` frame. A burst that fences 10k subscriptions
  on a peer costs one frame, not 10k.

Ordering guarantees relied on by the fusion client (result-then-invalidate
per call): queued messages always flush BEFORE the pending invalidation
map in a tick, and a call's result is causally enqueued before its
invalidation is posted, so a client never sees its invalidation overtake a
result that was already on the way out. (When it does lose a result to a
reconnect, the ``ResultMissedError`` retry covers it — unchanged.)

Pending invalidations survive reconnects: flush failures park the map until
the link returns (bounded — after ``RECONNECT_GIVE_UP_S`` disconnected the
map drops; the client's reconnect re-send / version-mismatch machinery
restores coherence, same contract as the pre-outbox per-key retry loop).
"""
from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import TYPE_CHECKING, Deque, Dict, Optional, Tuple

from ..utils.serialization import dumps
from .message import CALL_TYPE_COMPUTE, COMPUTE_SYSTEM_SERVICE, RpcMessage

if TYPE_CHECKING:
    from .peer import RpcPeer

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["PeerOutbox"]


class PeerOutbox:
    #: how long a disconnected peer may hold pending invalidations before
    #: they drop (the client is gone; it resubscribes on return — matches
    #: the pre-outbox per-key sender's 30 s give-up)
    RECONNECT_GIVE_UP_S = 30.0

    def __init__(self, peer: "RpcPeer"):
        self.peer = peer
        # home loop, for marshalling posts from OFF-loop callers (a device
        # wave applied from a worker thread must not lose its invalidation
        # push — the pre-outbox watch task got this via the threadsafe
        # wakeup inside when_invalidated). None when constructed with no
        # loop at all (pure-sync tests: nothing is connected there anyway).
        try:
            self._home_loop: Optional[asyncio.AbstractEventLoop] = (
                asyncio.get_event_loop()
            )
        except RuntimeError:
            self._home_loop = None
        self._fifo: Deque[Tuple[RpcMessage, Optional[asyncio.Future]]] = deque()
        #: call_id → (version | None, cause id | None, origin ts | None);
        #: insertion-order flush, last-posted entry wins — the latest by
        #: causality. cause/origin ride into the batch frame entries so a
        #: client fence can name its originating server wave and measure
        #: true end-to-end delivery (ISSUE 3).
        self._pending_inval: Dict[int, Tuple[Optional[str], Optional[str], Optional[float]]] = {}
        #: perf_counter of the oldest un-flushed post — the flush-tick lag
        #: gauge/histogram source (how long invalidations sat coalescing)
        self._pending_since: Optional[float] = None
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        #: True while the drain task (or a bypassing direct send) is mid-
        #: channel-write: bypass is only legal when nothing is in flight,
        #: or FIFO order would break
        self._in_flight = False
        self._stopped = False
        # -- counters (exported via RpcHub.fanout_stats / FusionMonitor) --
        self.messages_sent = 0
        self.invalidations_posted = 0  # post_invalidation() calls
        self.invalidations_coalesced = 0  # posts absorbed by a pending entry
        self.batch_frames_sent = 0
        self.batch_keys_sent = 0
        self.pending_dropped = 0  # give-up drops while disconnected
        self.drain_faults = 0  # drain-loop crashes (counted, never just logged)

    # ------------------------------------------------------------------ enqueue
    def can_bypass(self) -> bool:
        """True when a direct send preserves FIFO order: the drain has no
        backlog and nothing is mid-write. Keeps the single-message hot path
        (one awaited channel write) at its pre-outbox cost."""
        return not self._fifo and not self._in_flight and not self._pending_inval

    async def send(self, message: RpcMessage) -> None:
        """Enqueue + await delivery. Raises exactly what the channel write
        raised (the pre-outbox ``RpcPeer.send`` contract)."""
        if self._stopped:
            raise ConnectionError(f"peer {self.peer.ref} outbox is stopped")
        future: asyncio.Future = asyncio.get_event_loop().create_future()
        self._fifo.append((message, future))
        self._kick()
        await future

    def post_invalidation(
        self,
        call_id: int,
        version: Optional[str],
        cause: Optional[str] = None,
        origin_ts: Optional[float] = None,
    ) -> None:
        """Coalesce one subscription invalidation into the next batch frame.
        Synchronous — the caller never awaits a channel. Posting the same
        call twice between flushes ships once, at the latest version.
        Safe from off-loop callers (the kick marshals to the home loop).

        ``cause`` is the originating wave/span id and ``origin_ts`` the
        server-side wave-apply timestamp (``time.perf_counter()``): both
        ride the frame entry to the client, which links its fence back to
        the server wave and records the end-to-end delivery histogram."""
        if self._stopped:
            self.pending_dropped += 1
            return
        self.invalidations_posted += 1
        if call_id in self._pending_inval:
            self.invalidations_coalesced += 1
        elif not self._pending_inval:
            self._pending_since = time.perf_counter()
        self._pending_inval[call_id] = (version, cause, origin_ts)
        self._kick()

    def post_invalidations(self, entries) -> None:
        """Batch :meth:`post_invalidation`: ``entries`` is an iterable of
        ``(call_id, version, cause, origin_ts)`` tuples, merged into the
        pending map under ONE drain wake-up. The overlap drain
        (rpc/fanout.py riding a WavePipeline harvest, ISSUE 7) ships a
        whole wave's fences for a peer with one kick instead of one per
        subscription — the kick marshals to the home loop, so per-call
        kicks from the wave-apply thread were a measurable share of the
        drain."""
        if self._stopped:
            self.pending_dropped += sum(1 for _ in entries)
            return
        posted = False
        for call_id, version, cause, origin_ts in entries:
            self.invalidations_posted += 1
            if call_id in self._pending_inval:
                self.invalidations_coalesced += 1
            elif not self._pending_inval:
                self._pending_since = time.perf_counter()
            self._pending_inval[call_id] = (version, cause, origin_ts)
            posted = True
        if posted:
            self._kick()

    def _kick(self) -> None:
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            # off-loop caller (wave applied from a worker thread, or a
            # sync context): marshal the wakeup onto the home loop. A home
            # loop that never runs simply leaves the entries pending —
            # with no running loop there is no live link to starve.
            if self._home_loop is not None and not self._home_loop.is_closed():
                try:
                    self._home_loop.call_soon_threadsafe(self._kick_on_loop)
                except RuntimeError:
                    pass  # loop closed mid-call: peer is gone
            return
        self._kick_on_loop()

    def _kick_on_loop(self) -> None:
        if self._stopped:
            return
        if self._task is None or self._task.done():
            self._task = asyncio.get_event_loop().create_task(self._drain())
        self._wake.set()

    # ------------------------------------------------------------------ drain
    async def _drain(self) -> None:
        peer = self.peer
        try:
            while True:
                await self._wake.wait()
                self._wake.clear()
                if self._stopped:
                    return
                while self._fifo or self._pending_inval:
                    if self._in_flight:
                        # a bypassing direct send is mid-channel-write;
                        # draining now would interleave with it. Its
                        # finally-block re-kicks us once it clears.
                        break
                    # snapshot length: entries appended mid-tick go next
                    # tick, so a hot FIFO can never starve the batch flush
                    for _ in range(len(self._fifo)):
                        message, future = self._fifo.popleft()
                        self._in_flight = True
                        try:
                            await peer._send_now(message)
                        except asyncio.CancelledError:
                            if future is not None and not future.done():
                                future.cancel()
                            raise
                        except BaseException as e:  # noqa: BLE001
                            if future is not None and not future.done():
                                future.set_exception(e)
                            else:  # pragma: no cover — all entries carry futures
                                log.debug("outbox %s: dropped send: %s", peer.ref, e)
                        else:
                            self.messages_sent += 1
                            if future is not None and not future.done():
                                future.set_result(None)
                        finally:
                            self._in_flight = False
                    if self._pending_inval:
                        await self._flush_invalidations()
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — the drain must never die silently
            # counted (FL002): a dead drain is a peer whose fences stop
            # flowing while the link looks healthy — the next _kick
            # re-spawns, but the fault must be visible on a scrape
            self.drain_faults += 1
            log.exception("outbox %s: drain loop failed", peer.ref)

    async def _flush_invalidations(self) -> None:
        peer = self.peer
        state = peer.connection_state.latest().value
        if state.is_terminated:
            self.pending_dropped += len(self._pending_inval)
            self._pending_inval.clear()
            self._pending_since = None
            return
        if not peer.is_connected:
            # park until the link returns; pending survives the reconnect.
            # New posts merge into the SAME map meanwhile (last wins).
            ev = peer.connection_state.latest()
            try:
                await asyncio.wait_for(
                    ev.when(lambda s: s.is_connected or s.is_terminated),
                    self.RECONNECT_GIVE_UP_S,
                )
            except asyncio.TimeoutError:
                self.pending_dropped += len(self._pending_inval)
                self._pending_inval.clear()
                self._pending_since = None
                return
            if not peer.is_connected:
                return  # terminated; next tick drops
        batch, self._pending_inval = self._pending_inval, {}
        pending_since, self._pending_since = self._pending_since, None
        message = RpcMessage(
            call_type_id=CALL_TYPE_COMPUTE,
            call_id=0,
            service=COMPUTE_SYSTEM_SERVICE,
            method="invalidate_batch",
            # entry = [call_id, version, cause, origin_ts]; clients also
            # accept the pre-ISSUE-3 2-element shape (wire compat)
            argument_data=dumps(
                [[[cid, ver, cause, ts] for cid, (ver, cause, ts) in batch.items()]]
            ),
        )
        self._in_flight = True
        try:
            await peer._send_now(message)
        except asyncio.CancelledError:
            self._merge_back(batch, pending_since)
            raise
        except Exception:  # noqa: BLE001 — link died mid-flush: the batch
            # stays pending and the next tick parks on the reconnect above
            self._merge_back(batch, pending_since)
        else:
            self.batch_frames_sent += 1
            self.batch_keys_sent += len(batch)
            if pending_since is not None:
                from ..diagnostics.metrics import global_metrics

                global_metrics().histogram(
                    "fusion_outbox_flush_lag_ms",
                    help="oldest pending invalidation -> batch frame on the wire",
                ).record((time.perf_counter() - pending_since) * 1e3)
        finally:
            self._in_flight = False

    def _merge_back(self, batch: Dict[int, Tuple], pending_since: Optional[float] = None) -> None:
        """Re-pend a failed batch WITHOUT clobbering newer posts: anything
        posted since the flush snapshot is newer than the snapshot entry.
        A batch whose flush was cancelled by stop() is dropped — re-pending
        onto a permanently dead drain would report phantom pending entries
        forever."""
        if self._stopped:
            self.pending_dropped += len(batch)
            return
        for call_id, entry in batch.items():
            self._pending_inval.setdefault(call_id, entry)
        # the snapshot's entries are back: the lag clock resumes from the
        # ORIGINAL oldest post, not from the failed flush
        if pending_since is not None and (
            self._pending_since is None or pending_since < self._pending_since
        ):
            self._pending_since = pending_since
        self._wake.set()

    # ------------------------------------------------------------------ lifecycle
    def stop(self) -> None:
        self._stopped = True
        if self._task is not None and not self._task.done():
            self._task.cancel()
        err = ConnectionError(f"peer {self.peer.ref} outbox stopped")
        while self._fifo:
            _, future = self._fifo.popleft()
            if future is not None and not future.done():
                future.set_exception(err)
        self.pending_dropped += len(self._pending_inval)
        self._pending_inval.clear()
        self._pending_since = None  # the age gauge must not report a ghost

    def stats(self) -> dict:
        return {
            "messages_sent": self.messages_sent,
            "invalidations_posted": self.invalidations_posted,
            "invalidations_coalesced": self.invalidations_coalesced,
            "batch_frames_sent": self.batch_frames_sent,
            "batch_keys_sent": self.batch_keys_sent,
            "pending_dropped": self.pending_dropped,
            "drain_faults": self.drain_faults,
            "queued": len(self._fifo),
            "pending_invalidations": len(self._pending_inval),
        }
