"""ComputeFanoutIndex — newly-mask → subscribed-key extraction.

The missing half of the coalesced fan-out (ISSUE 2 tentpole): the burst
path already ships its newly-invalid set as a device-packed 1-bit/node
mask (graph/backend.py ``_apply_newly_mask``); this index maps backend
node ids to live ``$sys-c`` subscriptions so a wave's mask drains STRAIGHT
into per-peer pending invalidation sets (``PeerOutbox.post_invalidation``)
— one vectorized intersection per wave, no per-subscription watch-task
wakeup on the burst path.

The per-computed watch task (``RpcInboundComputeCall._watch_invalidation``)
stays as the correctness backstop: host-led invalidations cascade through
the host graph, not through a device wave, so only the watch task sees
them. Both paths post into the same per-peer pending map, which dedups —
a subscription fenced by the mask AND its watch task ships once per flush.

Install with :func:`install_compute_fanout` on the SERVER rpc hub whose
fusion hub has a :class:`~stl_fusion_tpu.graph.TpuGraphBackend` attached.
"""
from __future__ import annotations

import logging
import weakref
from typing import TYPE_CHECKING, Dict, Optional, Tuple

import numpy as np

from ..diagnostics.flight_recorder import RECORDER

if TYPE_CHECKING:
    from ..graph.backend import TpuGraphBackend
    from .hub import RpcHub
    from .peer import RpcPeer

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["ComputeFanoutIndex", "install_compute_fanout"]


class ComputeFanoutIndex:
    def __init__(self, rpc_hub: "RpcHub", backend: "TpuGraphBackend"):
        self.rpc_hub = rpc_hub
        self.backend = backend
        #: nid → {(id(peer), call_id): (weakref(peer), version,
        #: weakref(inbound call) | None)} — weak so a dead peer/call never
        #: pins its connection machinery through the index
        self._by_nid: Dict[
            int, Dict[Tuple[int, int], Tuple[object, Optional[str], Optional[object]]]
        ] = {}
        self._nid_arr: Optional[np.ndarray] = None  # cache of _by_nid keys
        backend.newly_hooks.append(self._on_newly)
        self.subscriptions = 0  # live entries
        self.registered_total = 0
        self.drained_total = 0  # subscriptions fenced via the mask path
        #: fences drained INSIDE a WavePipeline overlap window — i.e. the
        #: host shipped wave N-1's invalidations into per-peer outboxes
        #: while wave N executed on device (ISSUE 7 stage c); zero means
        #: the fan-out still serializes with device execution
        self.drained_overlapped = 0
        self.waves_seen = 0
        #: ISSUE 9 relay scoping. Members co-located on this process's
        #: mesh observe cross-shard frontiers through the collectives —
        #: a per-key relay post to one of them means the mesh path
        #: DISENGAGED (the CI mesh smoke fails on it). Members NOT on the
        #: mesh are cross-host: the relay is their legitimate DCN
        #: fallback, counted separately. Everything else is an ordinary
        #: external client subscription (the system's edge).
        self.mesh_members: frozenset = frozenset()
        self.cluster_members: frozenset = frozenset()
        self.mesh_member_relays = 0  # must stay 0 while the mesh path serves
        self.dcn_fallback_relays = 0  # cross-host members: expected
        self._disposed = False

    def dispose(self) -> None:
        """Detach from the backend's wave hooks and the hub (idempotent) —
        the same contract FusionMonitor.dispose() has: without it every
        constructed index keeps itself (and its hub) alive through the
        backend's hook list forever."""
        if self._disposed:
            return
        self._disposed = True
        try:
            self.backend.newly_hooks.remove(self._on_newly)
        except ValueError:
            pass
        if self.rpc_hub.compute_fanout is self:
            self.rpc_hub.compute_fanout = None
        from ..diagnostics.metrics import global_metrics

        global_metrics().unregister_collector(self)
        self._by_nid.clear()
        self._nid_arr = None
        self.subscriptions = 0

    # ------------------------------------------------------------------ registry
    def register(
        self,
        nid: int,
        peer: "RpcPeer",
        call_id: int,
        version: Optional[str],
        call=None,
    ) -> None:
        """Index one live subscription. ``call`` (the inbound compute call)
        lets the drain stamp ``_invalidation_pushed`` so the per-computed
        watch task doesn't send the same invalidation a second time."""
        subs = self._by_nid.get(nid)
        if subs is None:
            subs = self._by_nid[nid] = {}
            self._nid_arr = None
        subs[(id(peer), call_id)] = (
            weakref.ref(peer),
            version,
            weakref.ref(call) if call is not None else None,
        )
        self.subscriptions += 1
        self.registered_total += 1

    def unregister(self, nid: int, peer: "RpcPeer", call_id: int) -> None:
        subs = self._by_nid.get(nid)
        if subs is None:
            return
        if subs.pop((id(peer), call_id), None) is not None:
            self.subscriptions -= 1
        if not subs:
            del self._by_nid[nid]
            self._nid_arr = None

    # ------------------------------------------------------------------ drain
    def _subscribed_nids(self) -> np.ndarray:
        if self._nid_arr is None:
            self._nid_arr = np.fromiter(
                self._by_nid.keys(), dtype=np.int64, count=len(self._by_nid)
            )
        return self._nid_arr

    def _on_newly(self, newly) -> None:
        """Wave-application hook: intersect the newly-invalid set with the
        subscribed nids (vectorized) and post each hit's (call_id, version)
        into its peer's outbox pending map (the outbox marshals posts from
        off-loop callers onto its home loop). Runs inside wave application
        — O(subscribed) + one mask gather, never O(wave)."""
        if not self._by_nid:
            return
        if not getattr(self.rpc_hub, "coalesce_invalidations", True):
            # wire-compat mode flipped ON after registrations were made:
            # leave delivery to the per-key invalidation handlers (the
            # pushed-flag is never set, so nothing is lost)
            return
        self.waves_seen += 1
        # the wave's identity + apply timestamp: stamped into every posted
        # entry so the client fence links back to this wave and the e2e
        # delivery histogram measures from the apply moment (ISSUE 3)
        cause = getattr(self.backend, "last_cause_id", None)
        origin_ts = getattr(self.backend, "last_wave_applied_ts", None)
        nids = self._subscribed_nids()
        if isinstance(newly, np.ndarray) and newly.dtype == np.bool_:
            n = len(newly)
            in_range = nids[nids < n]
            hits = in_range[newly[in_range]]
        else:
            newly_ids = np.asarray(newly)
            if newly_ids.size == 0:
                return
            hits = nids[np.isin(nids, newly_ids)]
        # entries batch PER PEER and post under one outbox kick each (the
        # overlap drain shape: a wave's whole fence set for a peer is one
        # wake-up, not one per subscription)
        per_peer: Dict[int, Tuple[object, list]] = {}
        total_posted = 0
        for nid in hits.tolist():
            subs = self._by_nid.pop(nid, None)
            if subs is None:
                continue
            self._nid_arr = None
            self.subscriptions -= len(subs)
            self.drained_total += len(subs)
            posted = 0
            for (_pid, call_id), (peer_ref, version, call_ref) in subs.items():
                peer = peer_ref()
                if peer is None:
                    continue
                if call_ref is not None:
                    call = call_ref()
                    if call is not None:
                        # the watch-task backstop will still wake (the
                        # computed invalidates host-side too) but must not
                        # ship this subscription a second time
                        call._invalidation_pushed = True
                entry = per_peer.get(id(peer))
                if entry is None:
                    entry = per_peer[id(peer)] = (peer, [])
                entry[1].append((call_id, version, cause, origin_ts))
                posted += 1
                ref = getattr(peer, "ref", None)
                if ref in self.mesh_members:
                    self.mesh_member_relays += 1
                elif ref in self.cluster_members:
                    self.dcn_fallback_relays += 1
            total_posted += posted
            if posted and RECORDER.enabled:
                # one event per fenced KEY (never per subscription), with
                # the count of fences actually POSTED — dead peers skipped
                # above must not inflate explain()'s "fenced N clients"
                c = self.backend.computed_for(nid)
                RECORDER.note(
                    "client_fenced",
                    key=repr(c.input) if c is not None else f"nid:{nid}",
                    cause=cause,
                    count=posted,
                    detail=f"{posted} subscription(s) via mask drain",
                )
        for peer, entries in per_peer.values():
            peer.outbox.post_invalidations(entries)
        if total_posted and getattr(self.backend, "overlap_active", False):
            # this drain ran inside a pipeline harvest with the next chain
            # already executing on device — the ISSUE 7 overlap in action
            self.drained_overlapped += total_posted

    def set_mesh_scope(self, mesh_members, cluster_members=None) -> None:
        """Name the members co-located on this process's mesh (their
        cross-shard traffic must ride the collectives, never this relay)
        and, optionally, the full cluster membership (members off the mesh
        are counted as DCN fallback rather than plain client fan-out)."""
        from ..diagnostics.metrics import global_metrics

        self.mesh_members = frozenset(mesh_members)
        self.cluster_members = frozenset(
            cluster_members if cluster_members is not None else mesh_members
        )
        reg = global_metrics()
        reg.unregister_collector(self)  # idempotent re-scope
        reg.register_collector(self, ComputeFanoutIndex._collect_mesh_metrics)

    def _collect_mesh_metrics(self) -> dict:
        return {
            "fusion_mesh_member_relays_total": self.mesh_member_relays,
            "fusion_mesh_dcn_fallback_total": self.dcn_fallback_relays,
        }

    def stats(self) -> dict:
        return {
            "subscriptions": self.subscriptions,
            "registered_total": self.registered_total,
            "drained_total": self.drained_total,
            "drained_overlapped": self.drained_overlapped,
            "waves_seen": self.waves_seen,
            "mesh_member_relays": self.mesh_member_relays,
            "dcn_fallback_relays": self.dcn_fallback_relays,
        }


def install_compute_fanout(rpc_hub: "RpcHub", backend: "TpuGraphBackend") -> ComputeFanoutIndex:
    """Wire the burst newly-mask to the hub's ``$sys-c`` subscriptions.
    Idempotent per (hub, backend) pairing; returns the index."""
    existing = rpc_hub.compute_fanout
    if existing is not None:
        if existing.backend is backend:
            return existing
        raise ValueError("this hub already has a fanout index on another backend")
    index = ComputeFanoutIndex(rpc_hub, backend)
    rpc_hub.compute_fanout = index
    return index
