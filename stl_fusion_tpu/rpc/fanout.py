"""ComputeFanoutIndex — newly-mask → subscribed-key extraction.

The missing half of the coalesced fan-out (ISSUE 2 tentpole): the burst
path already ships its newly-invalid set as a device-packed 1-bit/node
mask (graph/backend.py ``_apply_newly_mask``); this index maps backend
node ids to live ``$sys-c`` subscriptions so a wave's mask drains STRAIGHT
into per-peer pending invalidation sets (``PeerOutbox.post_invalidation``)
— one vectorized intersection per wave, no per-subscription watch-task
wakeup on the burst path.

The per-computed watch task (``RpcInboundComputeCall._watch_invalidation``)
stays as the correctness backstop: host-led invalidations cascade through
the host graph, not through a device wave, so only the watch task sees
them. Both paths post into the same per-peer pending map, which dedups —
a subscription fenced by the mask AND its watch task ships once per flush.

Install with :func:`install_compute_fanout` on the SERVER rpc hub whose
fusion hub has a :class:`~stl_fusion_tpu.graph.TpuGraphBackend` attached.

ISSUE 11 adds the :class:`WaveValuePublisher` — the SERVER half of the
publish-on-wave value plane (level 2 of the upstream value plane). A key
with a STANDING publish registration (armed by a ``recompute_batch``
entry, client/compute_call.py) answers a wave fence not with a plain
invalidation but with the recomputed VALUE: after the wave's apply the
publisher recomputes the burst's fenced hot-set once per key, serializes
each value ONCE, and ships each subscribed edge ONE columnar
``$sys-c.value_block`` frame — ``(call_id, version, seq, cause, t0,
offset, bytes)`` columns over a shared payload blob — through the same
per-peer outbox drain the invalidation batches ride. The subscribed edge
then serves the whole fence burst with ZERO per-key upstream RPCs. Every
degradation falls back to the plain invalidation fence (counted, never
silent): host-led invalidations (reshards, manual fences), recompute
errors, dead links mid-block, per-round key/byte budget overflows.
"""
from __future__ import annotations

import asyncio
import itertools
import logging
import time
import weakref
from typing import TYPE_CHECKING, Dict, Optional, Set, Tuple

import numpy as np

from ..diagnostics.flight_recorder import RECORDER
from ..diagnostics.hotkeys import global_hotkeys
from ..utils.serialization import dumps
from .message import CALL_TYPE_COMPUTE, COMPUTE_SYSTEM_SERVICE, RpcMessage

if TYPE_CHECKING:
    from ..graph.backend import TpuGraphBackend
    from .hub import RpcHub
    from .peer import RpcPeer

log = logging.getLogger("stl_fusion_tpu")

__all__ = [
    "ComputeFanoutIndex",
    "WaveValuePublisher",
    "install_compute_fanout",
    "install_value_publisher",
]


class ComputeFanoutIndex:
    def __init__(self, rpc_hub: "RpcHub", backend: "TpuGraphBackend"):
        self.rpc_hub = rpc_hub
        self.backend = backend
        #: nid → {(id(peer), call_id): (weakref(peer), version,
        #: weakref(inbound call) | None)} — weak so a dead peer/call never
        #: pins its connection machinery through the index
        self._by_nid: Dict[
            int, Dict[Tuple[int, int], Tuple[object, Optional[str], Optional[object]]]
        ] = {}
        self._nid_arr: Optional[np.ndarray] = None  # cache of _by_nid keys
        backend.newly_hooks.append(self._on_newly)
        self.subscriptions = 0  # live entries
        self.registered_total = 0
        self.drained_total = 0  # subscriptions fenced via the mask path
        #: fences drained INSIDE a WavePipeline overlap window — i.e. the
        #: host shipped wave N-1's invalidations into per-peer outboxes
        #: while wave N executed on device (ISSUE 7 stage c); zero means
        #: the fan-out still serializes with device execution
        self.drained_overlapped = 0
        self.waves_seen = 0
        #: ISSUE 9 relay scoping. Members co-located on this process's
        #: mesh observe cross-shard frontiers through the collectives —
        #: a per-key relay post to one of them means the mesh path
        #: DISENGAGED (the CI mesh smoke fails on it). Members NOT on the
        #: mesh are cross-host: the relay is their legitimate DCN
        #: fallback, counted separately. Everything else is an ordinary
        #: external client subscription (the system's edge).
        self.mesh_members: frozenset = frozenset()
        self.cluster_members: frozenset = frozenset()
        self.mesh_member_relays = 0  # must stay 0 while the mesh path serves
        self.dcn_fallback_relays = 0  # cross-host members: expected
        #: wave fences taken over by the WaveValuePublisher (ISSUE 11):
        #: these shipped as value-block entries, not plain invalidations
        self.published_diverted = 0
        self._disposed = False

    def dispose(self) -> None:
        """Detach from the backend's wave hooks and the hub (idempotent) —
        the same contract FusionMonitor.dispose() has: without it every
        constructed index keeps itself (and its hub) alive through the
        backend's hook list forever."""
        if self._disposed:
            return
        self._disposed = True
        try:
            self.backend.newly_hooks.remove(self._on_newly)
        except ValueError:
            pass
        if self.rpc_hub.compute_fanout is self:
            self.rpc_hub.compute_fanout = None
        from ..diagnostics.metrics import global_metrics

        global_metrics().unregister_collector(self)
        self._by_nid.clear()
        self._nid_arr = None
        self.subscriptions = 0

    # ------------------------------------------------------------------ registry
    def register(
        self,
        nid: int,
        peer: "RpcPeer",
        call_id: int,
        version: Optional[str],
        call=None,
    ) -> None:
        """Index one live subscription. ``call`` (the inbound compute call)
        lets the drain stamp ``_invalidation_pushed`` so the per-computed
        watch task doesn't send the same invalidation a second time."""
        subs = self._by_nid.get(nid)
        if subs is None:
            subs = self._by_nid[nid] = {}
            self._nid_arr = None
        subs[(id(peer), call_id)] = (
            weakref.ref(peer),
            version,
            weakref.ref(call) if call is not None else None,
        )
        self.subscriptions += 1
        self.registered_total += 1

    def unregister(self, nid: int, peer: "RpcPeer", call_id: int) -> None:
        subs = self._by_nid.get(nid)
        if subs is None:
            return
        if subs.pop((id(peer), call_id), None) is not None:
            self.subscriptions -= 1
        if not subs:
            del self._by_nid[nid]
            self._nid_arr = None

    # ------------------------------------------------------------------ drain
    def _subscribed_nids(self) -> np.ndarray:
        if self._nid_arr is None:
            self._nid_arr = np.fromiter(
                self._by_nid.keys(), dtype=np.int64, count=len(self._by_nid)
            )
        return self._nid_arr

    def _on_newly(self, newly) -> None:
        """Wave-application hook: intersect the newly-invalid set with the
        subscribed nids (vectorized) and post each hit's (call_id, version)
        into its peer's outbox pending map (the outbox marshals posts from
        off-loop callers onto its home loop). Runs inside wave application
        — O(subscribed) + one mask gather, never O(wave)."""
        if not self._by_nid:
            return
        if not getattr(self.rpc_hub, "coalesce_invalidations", True):
            # wire-compat mode flipped ON after registrations were made:
            # leave delivery to the per-key invalidation handlers (the
            # pushed-flag is never set, so nothing is lost)
            return
        self.waves_seen += 1
        # the wave's identity + apply timestamp: stamped into every posted
        # entry so the client fence links back to this wave and the e2e
        # delivery histogram measures from the apply moment (ISSUE 3)
        cause = getattr(self.backend, "last_cause_id", None)
        origin_ts = getattr(self.backend, "last_wave_applied_ts", None)
        nids = self._subscribed_nids()
        if isinstance(newly, np.ndarray) and newly.dtype == np.bool_:
            n = len(newly)
            in_range = nids[nids < n]
            hits = in_range[newly[in_range]]
        else:
            newly_ids = np.asarray(newly)
            if newly_ids.size == 0:
                return
            hits = nids[np.isin(nids, newly_ids)]
        # entries batch PER PEER and post under one outbox kick each (the
        # overlap drain shape: a wave's whole fence set for a peer is one
        # wake-up, not one per subscription)
        publisher = getattr(self.rpc_hub, "value_publisher", None)
        publish_nids: Dict[int, Tuple[Optional[str], Optional[float]]] = {}
        per_peer: Dict[int, Tuple[object, list]] = {}
        total_posted = 0
        hotkeys = global_hotkeys()
        for nid in hits.tolist():
            # attribution (ISSUE 19): one offer per subscribed node the
            # wave invalidated — the sketch that lets /hotkeys and
            # explain() name the keys a hot workload keeps re-fencing
            hotkeys.offer("wave_invalidations", str(nid))
            subs = self._by_nid.pop(nid, None)
            if subs is None:
                continue
            self._nid_arr = None
            self.subscriptions -= len(subs)
            self.drained_total += len(subs)
            posted = 0
            for (_pid, call_id), (peer_ref, version, call_ref) in subs.items():
                peer = peer_ref()
                if peer is None:
                    continue
                if publisher is not None:
                    standing = publisher.peek(_pid, call_id)
                    if standing is not None:
                        # publish-on-wave takeover (ISSUE 11): this
                        # subscription answers with the recomputed VALUE —
                        # the publisher posts the block (or the counted
                        # fallback fence); no plain invalidation here
                        standing.wave_pending = True
                        publish_nids[nid] = (cause, origin_ts)
                        self.published_diverted += 1
                        if call_ref is not None:
                            call = call_ref()
                            if call is not None:
                                call._invalidation_pushed = True
                        continue
                if call_ref is not None:
                    call = call_ref()
                    if call is not None:
                        # the watch-task backstop will still wake (the
                        # computed invalidates host-side too) but must not
                        # ship this subscription a second time
                        call._invalidation_pushed = True
                entry = per_peer.get(id(peer))
                if entry is None:
                    entry = per_peer[id(peer)] = (peer, [])
                entry[1].append((call_id, version, cause, origin_ts))
                posted += 1
                ref = getattr(peer, "ref", None)
                if ref in self.mesh_members:
                    self.mesh_member_relays += 1
                elif ref in self.cluster_members:
                    self.dcn_fallback_relays += 1
            total_posted += posted
            if posted and RECORDER.enabled:
                # one event per fenced KEY (never per subscription), with
                # the count of fences actually POSTED — dead peers skipped
                # above must not inflate explain()'s "fenced N clients"
                c = self.backend.computed_for(nid)
                RECORDER.note(
                    "client_fenced",
                    key=repr(c.input) if c is not None else f"nid:{nid}",
                    cause=cause,
                    count=posted,
                    detail=f"{posted} subscription(s) via mask drain",
                )
        for peer, entries in per_peer.values():
            peer.outbox.post_invalidations(entries)
        if publish_nids:
            publisher.schedule(publish_nids)
        if total_posted and getattr(self.backend, "overlap_active", False):
            # this drain ran inside a pipeline harvest with the next chain
            # already executing on device — the ISSUE 7 overlap in action
            self.drained_overlapped += total_posted

    def set_mesh_scope(self, mesh_members, cluster_members=None) -> None:
        """Name the members co-located on this process's mesh (their
        cross-shard traffic must ride the collectives, never this relay)
        and, optionally, the full cluster membership (members off the mesh
        are counted as DCN fallback rather than plain client fan-out)."""
        from ..diagnostics.metrics import global_metrics

        self.mesh_members = frozenset(mesh_members)
        self.cluster_members = frozenset(
            cluster_members if cluster_members is not None else mesh_members
        )
        reg = global_metrics()
        reg.unregister_collector(self)  # idempotent re-scope
        reg.register_collector(self, ComputeFanoutIndex._collect_mesh_metrics)

    def _collect_mesh_metrics(self) -> dict:
        return {
            "fusion_mesh_member_relays_total": self.mesh_member_relays,
            "fusion_mesh_dcn_fallback_total": self.dcn_fallback_relays,
        }

    def stats(self) -> dict:
        return {
            "subscriptions": self.subscriptions,
            "registered_total": self.registered_total,
            "drained_total": self.drained_total,
            "drained_overlapped": self.drained_overlapped,
            "waves_seen": self.waves_seen,
            "mesh_member_relays": self.mesh_member_relays,
            "dcn_fallback_relays": self.dcn_fallback_relays,
            "published_diverted": self.published_diverted,
        }


def install_compute_fanout(rpc_hub: "RpcHub", backend: "TpuGraphBackend") -> ComputeFanoutIndex:
    """Wire the burst newly-mask to the hub's ``$sys-c`` subscriptions.
    Idempotent per (hub, backend) pairing; returns the index."""
    existing = rpc_hub.compute_fanout
    if existing is not None:
        if existing.backend is backend:
            return existing
        raise ValueError("this hub already has a fanout index on another backend")
    index = ComputeFanoutIndex(rpc_hub, backend)
    rpc_hub.compute_fanout = index
    return index


# ======================================================================
# publish-on-wave value plane — the SERVER half (ISSUE 11 level 2)
# ======================================================================


class _StandingSub:
    """One standing publish subscription: (peer, call_id) → key spec.
    Survives the wave fences that retire ordinary ``$sys-c``
    subscriptions — the publisher re-binds it to each recomputed node."""

    __slots__ = (
        "pid", "call_id", "peer_ref", "service", "method", "args",
        "nid", "version", "seq", "wave_pending",
    )

    def __init__(self, peer, call_id, service, method, args, nid, version):
        self.pid = id(peer)
        self.call_id = call_id
        self.peer_ref = weakref.ref(peer)
        self.service = service
        self.method = method
        self.args = args
        self.nid = nid
        self.version = version
        #: last published block seq (the edge's monotonic gate)
        self.seq = 0
        #: set by the fanout drain when a wave fenced this key and the
        #: publisher owns the answer; cleared by the publish round. The
        #: host-led invalidation handler skips pending subs — the wave
        #: path, not it, decides between block and fallback fence.
        self.wave_pending = False


class WaveValuePublisher:
    """Publish-on-wave value blocks (ISSUE 11 level 2, the serialize-once
    thesis one hop upstream): after a wave's apply, recompute the fenced
    hot-set ONCE per key, serialize each value ONCE, and push each
    subscribed edge ONE columnar ``$sys-c.value_block`` frame through its
    outbox — the edge then serves the whole burst with zero per-key
    upstream RPCs.

    The fallback ladder is always a plain invalidation fence (counted,
    never silent): host-led invalidations (reshard fences, manual
    invalidates), recompute errors, non-graph-resident recomputes, links
    that die mid-block, and per-round budget overflows all post the
    ordinary ``invalidate_batch`` entry, which the edge answers with its
    batched re-read (level 1)."""

    def __init__(
        self,
        rpc_hub: "RpcHub",
        max_keys_per_round: int = 8192,
        max_block_bytes: int = 4 << 20,
    ):
        self.rpc_hub = rpc_hub
        #: per-round distinct-key bound: excess keys fence plain (counted)
        self.max_keys_per_round = max_keys_per_round
        #: per-frame payload bound: bigger rounds chunk into several frames
        self.max_block_bytes = max_block_bytes
        self._standing: Dict[Tuple[int, int], _StandingSub] = {}
        self._by_nid: Dict[int, Set[_StandingSub]] = {}
        #: nid → (cause, origin_ts) — the wave fences awaiting a publish
        #: round (latest-wins per nid: two waves before one round = one
        #: recompute at the newest state)
        self._pending: Dict[int, Tuple[Optional[str], Optional[float]]] = {}
        self._seq = itertools.count(1)
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        try:
            self._home_loop: Optional[asyncio.AbstractEventLoop] = (
                asyncio.get_event_loop()
            )
        except RuntimeError:
            self._home_loop = None
        self._disposed = False
        # -- counters (collector-exported as fusion_value_*) --------------
        self.standing_registered_total = 0
        self.rounds = 0
        self.recomputes = 0
        self.blocks_sent = 0
        self.block_keys_sent = 0
        self.block_bytes_sent = 0
        self.values_serialized = 0  # ONE per (key, version), shared by peers
        self.fallback_fences = 0  # plain invalidations posted by the ladder
        self.overflow_fallbacks = 0  # of which: round-budget overflow
        self.loop_faults = 0  # publisher loop crashes (FL002: counted, alertable)
        self.recompute_errors = 0  # service retired / registry miss mid-publish
        self.block_send_failures = 0  # value_block sends lost to a dead link
        from ..diagnostics.metrics import global_metrics

        # publish pressure is non-additive: two half-loaded publishers
        # are half loaded, not fully loaded
        global_metrics().set_aggregation("fusion_value_publish_pressure", "max")
        global_metrics().register_collector(
            self, WaveValuePublisher._collect_metrics
        )

    def _collect_metrics(self) -> dict:
        return {
            "fusion_value_standing_subs": len(self._standing),
            "fusion_value_blocks_sent_total": self.blocks_sent,
            "fusion_value_block_keys_total": self.block_keys_sent,
            "fusion_value_block_bytes_total": self.block_bytes_sent,
            "fusion_value_serialized_total": self.values_serialized,
            "fusion_value_publish_rounds_total": self.rounds,
            "fusion_value_fallback_fences_total": self.fallback_fences,
            "fusion_value_publisher_faults_total": self.loop_faults,
            "fusion_value_recompute_errors_total": self.recompute_errors,
            "fusion_value_block_send_failures_total": self.block_send_failures,
            "fusion_value_publish_pressure": round(self.pressure(), 4),
        }

    def pressure(self) -> float:
        """Publish-plane load, 0..1 (ISSUE 12b): fenced keys waiting for
        a publish round against the round budget. An edge-side admission
        controller (or the traffic harness's SLO gates) can read this —
        a backlog at the VALUE plane means fences are about to arrive
        late no matter how fast the edges fan, so shedding should start
        upstream of the fan, not after it."""
        return min(1.0, len(self._pending) / max(1, self.max_keys_per_round))

    # ------------------------------------------------------------------ registry
    def register_standing(
        self, peer: "RpcPeer", call_id: int, service: str, method: str,
        args, computed,
    ) -> bool:
        """Arm one standing publish subscription (a ``recompute_batch``
        entry asked for it). Returns False — publish mode declined — when
        the captured node is not graph-resident (a wave can never fence
        it, so there is nothing to publish on)."""
        if self._disposed:
            return False
        nid = getattr(computed, "_backend_nid", None)
        if nid is None:
            return False
        from ..utils.serialization import deep_tuple

        sub = _StandingSub(
            peer, call_id, service, method, deep_tuple(tuple(args)), int(nid),
            computed.version.format(),
        )
        old = self._standing.get((sub.pid, call_id))
        if old is not None:
            self._discard(old)
        # an edge holds exactly ONE subscription per key: another standing
        # sub for the SAME (peer, nid) under a different call id is a
        # superseded subscription (the edge re-read and re-armed — e.g.
        # after a reconnect or a block-budget eviction). Retire it here,
        # or every later wave would keep recomputing and shipping block
        # entries for a call id the edge only counts as orphans.
        bucket = self._by_nid.get(sub.nid)
        if bucket is not None:
            fanout = self.rpc_hub.compute_fanout
            for stale in [
                s for s in bucket
                if s.pid == sub.pid and s.call_id != call_id
            ]:
                self._discard(stale)
                if fanout is not None:
                    stale_peer = stale.peer_ref()
                    if stale_peer is not None:
                        fanout.unregister(stale.nid, stale_peer, stale.call_id)
        self._standing[(sub.pid, call_id)] = sub
        self._by_nid.setdefault(sub.nid, set()).add(sub)
        self.standing_registered_total += 1
        return True

    def peek(self, pid: int, call_id: int) -> Optional[_StandingSub]:
        return self._standing.get((pid, call_id))

    def drop_standing(self, peer: "RpcPeer", call_id: int) -> None:
        sub = self._standing.get((id(peer), call_id))
        if sub is not None:
            self._discard(sub)

    def _discard(self, sub: _StandingSub) -> None:
        self._standing.pop((sub.pid, sub.call_id), None)
        bucket = self._by_nid.get(sub.nid)
        if bucket is not None:
            bucket.discard(sub)
            if not bucket:
                self._by_nid.pop(sub.nid, None)

    def _drop_and_fence(
        self, sub: _StandingSub, cause: Optional[str], origin_ts: Optional[float],
    ) -> None:
        """The fallback rung: retire the standing registration and post
        the plain invalidation fence — the edge re-reads (batched) and
        re-arms. Counted, never silent."""
        self._discard(sub)
        self.fallback_fences += 1
        peer = sub.peer_ref()
        if peer is None:
            return
        fanout = self.rpc_hub.compute_fanout
        if fanout is not None:
            fanout.unregister(sub.nid, peer, sub.call_id)
        try:
            peer.outbox.post_invalidation(
                sub.call_id, sub.version, cause=cause,
                origin_ts=origin_ts if origin_ts is not None else time.perf_counter(),
            )
        except RuntimeError:  # no running loop: no live link to fence
            pass

    # ------------------------------------------------------------------ schedule
    def schedule(self, nids: Dict[int, Tuple[Optional[str], Optional[float]]]) -> None:
        """Fanout-drain handoff: these nids' standing subs answer this
        wave with a value block. Latest-wins per nid; safe from off-loop
        callers — the MERGE itself marshals to the home loop (not just
        the kick): an off-loop update racing the round's dict swap could
        land entries in a dict nobody reads, and a lost publish round
        here is a silently-stale edge (the drain already suppressed the
        plain invalidation for these subs)."""
        if self._disposed:
            return
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            if self._home_loop is not None and not self._home_loop.is_closed():
                try:
                    self._home_loop.call_soon_threadsafe(
                        self._schedule_on_loop, dict(nids)
                    )
                except RuntimeError:
                    pass  # loop closed: the publisher is going away
            return
        self._schedule_on_loop(nids)

    def _schedule_on_loop(
        self, nids: Dict[int, Tuple[Optional[str], Optional[float]]]
    ) -> None:
        if self._disposed:
            return
        self._pending.update(nids)
        self._kick_on_loop()

    def _kick_on_loop(self) -> None:
        if self._disposed:
            return
        if self._task is None or self._task.done():
            self._task = asyncio.get_event_loop().create_task(self._run())
        self._wake.set()

    async def _run(self) -> None:
        try:
            while not self._disposed:
                await self._wake.wait()
                self._wake.clear()
                while self._pending and not self._disposed:
                    batch, self._pending = self._pending, {}
                    await self._publish_round(batch)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — the publisher must never die silently
            # counted, not just logged: a dead publisher is every standing
            # sub silently stale (the exact class FL002 exists to catch) —
            # operators alert on this counter, and the next schedule()
            # re-spawns the loop
            self.loop_faults += 1
            log.exception("value publisher loop failed")

    # ------------------------------------------------------------------ publish
    async def _recompute(self, service: str, method: str, args: tuple):
        from ..core.context import suspend_dependency_capture, try_capture

        try:
            service_def = self.rpc_hub.service_registry.require(service)
            fn = service_def.method(method).fn
        except Exception:  # noqa: BLE001 — service retired mid-flight:
            # counted; the caller's fallback fence handles the key
            self.recompute_errors += 1
            return None
        self.recomputes += 1
        with suspend_dependency_capture():
            return await try_capture(lambda: fn(*args))

    def _invalidation_handler_for(self, nid: int):
        """Armed on each recomputed node: a HOST-LED invalidation (not a
        wave the drain diverted) retires the nid's standing subs through
        the fallback fence. Wave-pending subs are the publish round's."""

        def handler(computed) -> None:
            subs = self._by_nid.get(nid)
            if not subs:
                return
            cause = getattr(computed, "_invalidation_cause", None)
            now = time.perf_counter()
            for sub in list(subs):
                if sub.wave_pending:
                    continue
                self._drop_and_fence(sub, cause, now)

        return handler

    async def _publish_round(
        self, batch: Dict[int, Tuple[Optional[str], Optional[float]]]
    ) -> None:
        self.rounds += 1
        items = list(batch.items())
        overflow = items[self.max_keys_per_round:]
        items = items[: self.max_keys_per_round]
        for nid, (cause, t0) in overflow:
            for sub in list(self._by_nid.get(nid, ())):
                sub.wave_pending = False
                self._drop_and_fence(sub, cause, t0)
                self.overflow_fallbacks += 1
        fanout = self.rpc_hub.compute_fanout
        #: id(peer) -> (peer, [(sub, version, cause, t0, value_bytes)])
        blocks: Dict[int, Tuple[object, list]] = {}
        for nid, (cause, t0) in items:
            subs = self._by_nid.get(nid)
            if not subs:
                continue
            spec = next(iter(subs))
            computed = await self._recompute(spec.service, spec.method, spec.args)
            out = computed._output if computed is not None else None
            new_nid = (
                getattr(computed, "_backend_nid", None)
                if computed is not None
                else None
            )
            if computed is not None and computed.is_invalidated and nid in self._pending:
                # the recompute raced a NEWER wave whose drain already
                # re-scheduled this nid: the next round owns the fence —
                # publishing the superseded value would only be churn
                continue
            if (
                computed is None
                or computed.is_invalidated
                or out is None
                or out.has_error
                or new_nid is None
            ):
                # recompute failed / host-led invalidation mid-round /
                # left the graph: fence plain — the edge's batched re-read
                # owns the recovery (and re-arms publish)
                for sub in list(subs):
                    sub.wave_pending = False
                    self._drop_and_fence(sub, cause, t0)
                continue
            version = computed.version.format()
            value_bytes = dumps(out.value)  # ONCE per (key, version) —
            # every subscribed edge's block shares these bytes
            self.values_serialized += 1
            for sub in list(subs):
                sub.wave_pending = False
                peer = sub.peer_ref()
                if peer is None:
                    self._discard(sub)
                    continue
                if int(new_nid) != sub.nid:
                    # the key's row moved (rebuild): re-key the standing sub
                    bucket = self._by_nid.get(sub.nid)
                    if bucket is not None:
                        bucket.discard(sub)
                        if not bucket:
                            self._by_nid.pop(sub.nid, None)
                    sub.nid = int(new_nid)
                    self._by_nid.setdefault(sub.nid, set()).add(sub)
                sub.version = version
                sub.seq = next(self._seq)
                if fanout is not None:
                    # re-register so the NEXT wave's drain finds (and
                    # diverts) this subscription — the single-upstream
                    # count recovers without any client round trip
                    fanout.register(sub.nid, peer, sub.call_id, version, call=None)
                entry = blocks.get(id(peer))
                if entry is None:
                    entry = blocks[id(peer)] = (peer, [])
                entry[1].append((sub, version, cause, t0, value_bytes))
            computed.on_invalidated(self._invalidation_handler_for(int(new_nid)))
            if RECORDER.enabled:
                RECORDER.note(
                    "block_published",
                    key=repr(computed.input),
                    cause=cause,
                    count=len(subs),
                    detail=f"{len(value_bytes)}B to {len(subs)} edge sub(s)",
                )
        for peer, entries in blocks.values():
            await self._send_blocks(peer, entries)

    async def _send_blocks(self, peer, entries) -> None:
        """Ship one peer's round as columnar ``value_block`` frame(s):
        parallel (call_id, version, seq, cause, t0, offset) columns over
        ONE shared payload blob; chunked at ``max_block_bytes``."""
        i = 0
        n = len(entries)
        while i < n:
            cids, vers, seqs, causes, t0s = [], [], [], [], []
            offsets = [0]
            chunks = []
            size = 0
            while i < n and (not cids or size < self.max_block_bytes):
                sub, version, cause, t0, value_bytes = entries[i]
                cids.append(sub.call_id)
                vers.append(version)
                seqs.append(sub.seq)
                causes.append(cause)
                t0s.append(t0)
                chunks.append(value_bytes)
                size += len(value_bytes)
                offsets.append(offsets[-1] + len(value_bytes))
                i += 1
            message = RpcMessage(
                call_type_id=CALL_TYPE_COMPUTE,
                call_id=0,
                service=COMPUTE_SYSTEM_SERVICE,
                method="value_block",
                argument_data=dumps(
                    [cids, vers, seqs, causes, t0s, offsets, b"".join(chunks)]
                ),
            )
            try:
                await peer.send(message)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — link died mid-block: fence
                # plain; the pending invalidations ride the outbox across
                # the reconnect and the edge's re-read re-arms publish.
                # The send failure itself is counted UNCONDITIONALLY — the
                # per-sub fence below only fires for subs still standing,
                # so a flapping link could otherwise drop blocks silently
                self.block_send_failures += 1
                for cid, cause, t0 in zip(cids, causes, t0s):
                    sub = self._standing.get((id(peer), cid))
                    if sub is not None:
                        self._drop_and_fence(sub, cause, t0)
                continue
            self.blocks_sent += 1
            self.block_keys_sent += len(cids)
            self.block_bytes_sent += size

    # ------------------------------------------------------------------ lifecycle
    def dispose(self) -> None:
        if self._disposed:
            return
        self._disposed = True
        if self._task is not None and not self._task.done():
            self._task.cancel()
        if self.rpc_hub.value_publisher is self:
            self.rpc_hub.value_publisher = None
        from ..diagnostics.metrics import global_metrics

        global_metrics().unregister_collector(self)
        self._standing.clear()
        self._by_nid.clear()
        self._pending.clear()

    def stats(self) -> dict:
        return {
            "standing_subs": len(self._standing),
            "standing_registered_total": self.standing_registered_total,
            "rounds": self.rounds,
            "recomputes": self.recomputes,
            "blocks_sent": self.blocks_sent,
            "block_keys_sent": self.block_keys_sent,
            "block_bytes_sent": self.block_bytes_sent,
            "values_serialized": self.values_serialized,
            "fallback_fences": self.fallback_fences,
            "overflow_fallbacks": self.overflow_fallbacks,
            "pending_nids": len(self._pending),
            "pressure": round(self.pressure(), 4),
        }


def install_value_publisher(
    rpc_hub: "RpcHub",
    max_keys_per_round: int = 8192,
    max_block_bytes: int = 4 << 20,
) -> WaveValuePublisher:
    """Install the publish-on-wave value plane on a SERVING hub
    (idempotent). Pair with :func:`install_compute_fanout` — the wave
    drain is what hands fences to the publisher."""
    existing = rpc_hub.value_publisher
    if existing is not None:
        return existing
    publisher = WaveValuePublisher(
        rpc_hub, max_keys_per_round=max_keys_per_round,
        max_block_bytes=max_block_bytes,
    )
    rpc_hub.value_publisher = publisher
    return publisher
