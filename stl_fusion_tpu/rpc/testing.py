"""In-memory RPC transport for protocol tests.

Re-expression of src/Stl.Rpc/Testing/RpcTestClient.cs:7-73 +
RpcTestConnection.cs: client peers connect over twisted in-memory channel
pairs instead of sockets, with scripted ``disconnect()`` / ``reconnect()``
so reliability behavior (re-send, dedup, invalidation-after-reconnect) is
testable without any network. SURVEY.md §4 calls this out as the first
transport to build.
"""
from __future__ import annotations

import asyncio
from typing import Dict, Optional

from ..utils.async_utils import ChannelClosedError, ChannelPair, create_twisted_pair
from .hub import RpcHub
from .peer import RpcClientPeer, RpcServerPeer


class _FlakySendWriter:
    """Writer that dies after N sends WITHOUT closing the pair — the
    half-open-TCP shape: sends fail while the reader hangs silently. Used
    to kill the link mid-re-send-batch (VERDICT r1 weak #7)."""

    def __init__(self, pair: ChannelPair, fail_after: int):
        self._pair = pair
        self._left = fail_after

    async def send(self, message) -> None:
        if self._left <= 0:
            raise ChannelClosedError("flaky link died mid-send")
        self._left -= 1
        await self._pair.writer.send(message)


class _FlakyPair:
    def __init__(self, pair: ChannelPair, fail_after: int):
        self._pair = pair
        self.reader = pair.reader
        self.writer = _FlakySendWriter(pair, fail_after)

    def close(self, error: Optional[BaseException] = None) -> None:
        self._pair.close(error)


class _CodecChannel:
    """One direction of a codec-faithful link: send serializes the whole
    message (envelope included), receive deserializes."""

    def __init__(self, inner, encode: bool):
        self._inner = inner
        self._encode = encode

    async def send(self, message) -> None:
        from ..utils.serialization import dumps

        await self._inner.send(dumps(message) if self._encode else message)

    async def receive(self):
        from ..utils.serialization import loads

        item = await self._inner.receive()
        return loads(item) if not self._encode else item

    def close(self, error: Optional[BaseException] = None) -> None:
        self._inner.close(error)


class _CodecPair:
    """Codec-faithful endpoint wrapper: every frame pays full envelope
    serialization both ways, like a real socket transport (the raw twisted
    channels pass Python objects, which understates per-frame cost — a
    fan-out benchmark over them would flatter the per-key baseline)."""

    def __init__(self, pair: ChannelPair):
        self._pair = pair
        self.writer = _CodecChannel(pair.writer, encode=True)
        self.reader = _CodecChannel(pair.reader, encode=False)

    def close(self, error: Optional[BaseException] = None) -> None:
        self._pair.close(error)

__all__ = ["RpcTestTransportBase", "RpcTestTransport", "RpcMultiServerTestTransport"]


class RpcTestTransportBase:
    """Channel-pair transport plumbing shared by the single- and
    multi-server variants; subclasses pick the server hub per peer ref."""

    def __init__(self, client_hub: RpcHub, wire_codec: bool = False,
                 client_name: Optional[str] = None):
        self.client_hub = client_hub
        self.connect_count: Dict[str, int] = {}
        self._blocked = False
        self._fail_next_after: Optional[int] = None
        self._chaos = None
        #: True → every frame is dumps()ed on send and loads()ed on receive
        #: (both directions, both ends) — the serialization cost a real
        #: socket transport pays per frame
        self.wire_codec = wire_codec
        #: distinguishes this client hub in the SERVER-side peer ref. The
        #: historic ref shape ``client:{target_ref}`` collides when several
        #: client hubs dial the same server (each .connect() displaces the
        #: previous link) — a cluster mesh (N members + M clients all
        #: dialing each other, cluster/) needs one server peer PER dialer.
        self.client_name = client_name
        client_hub.client_connector = self._connect

    def _server_for(self, peer_ref: str) -> RpcHub:
        raise NotImplementedError

    def server_peer_ref(self, target_ref: str) -> str:
        """The ref the target server hub knows this client hub's link by."""
        if self.client_name is not None:
            return f"client:{self.client_name}@{target_ref}"
        return f"client:{target_ref}"

    async def _connect(self, peer: RpcClientPeer) -> ChannelPair:
        if self._blocked:
            raise ConnectionError("test transport is blocked")
        server_hub = self._server_for(peer.ref)
        client_end, server_end = create_twisted_pair()
        if self.wire_codec:
            client_end = _CodecPair(client_end)
            server_end = _CodecPair(server_end)
        if self._chaos is not None:
            from ..resilience.chaos import wrap_chaos_pair

            client_end = wrap_chaos_pair(client_end, self._chaos)
            server_end = wrap_chaos_pair(server_end, self._chaos)
        server_hub.server_peer(self.server_peer_ref(peer.ref)).connect(server_end)
        self.connect_count[peer.ref] = self.connect_count.get(peer.ref, 0) + 1
        if self._fail_next_after is not None:
            fail_after, self._fail_next_after = self._fail_next_after, None
            return _FlakyPair(client_end, fail_after)
        return client_end

    # -- fault injection ---------------------------------------------------
    async def disconnect(self, peer_ref: str = "default") -> None:
        """Drop the physical link; the client peer will auto-reconnect."""
        peer = self.client_hub.peers.get(peer_ref)
        if peer is not None:
            await peer.disconnect(ConnectionError("test disconnect"))

    def block_reconnects(self, blocked: bool = True) -> None:
        self._blocked = blocked

    def fail_next_connection_after(self, sends: int) -> None:
        """The NEXT connection's writer dies after ``sends`` sends (reader
        keeps hanging) — kills the link mid-re-send-batch."""
        self._fail_next_after = sends

    def set_chaos(self, policy) -> None:
        """Apply a ``resilience.ChaosPolicy`` to every connection made from
        now on (both directions): per-message drop/duplicate/delay/reorder
        on the twisted channels. ``None`` disables for future connections
        (existing links keep their wrappers until they die)."""
        self._chaos = policy

    async def wait_connected(self, peer_ref: str = "default", timeout: float = 5.0) -> None:
        peer = self.client_hub.client_peer(peer_ref)
        await asyncio.wait_for(peer.when_connected(), timeout)


class RpcTestTransport(RpcTestTransportBase):
    """Wires a client hub to a server hub through channel pairs."""

    def __init__(self, client_hub: RpcHub, server_hub: RpcHub, wire_codec: bool = False,
                 client_name: Optional[str] = None):
        super().__init__(client_hub, wire_codec=wire_codec, client_name=client_name)
        self.server_hub = server_hub

    def _server_for(self, peer_ref: str) -> RpcHub:
        return self.server_hub


class RpcMultiServerTestTransport(RpcTestTransportBase):
    """Wires one client hub to MANY server hubs, selected by peer ref —
    the in-memory analogue of the MultiServerRpc sample's server pool
    (samples/MultiServerRpc/Program.cs:58-76): peer ref = pool member."""

    def __init__(self, client_hub: RpcHub, servers: Dict[str, RpcHub], wire_codec: bool = False,
                 client_name: Optional[str] = None):
        super().__init__(client_hub, wire_codec=wire_codec, client_name=client_name)
        self.servers = dict(servers)

    def _server_for(self, peer_ref: str) -> RpcHub:
        server_hub = self.servers.get(peer_ref)
        if server_hub is None:
            raise ConnectionError(f"no server for peer ref {peer_ref!r}")
        return server_hub
