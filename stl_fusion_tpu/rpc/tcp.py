"""Plain-TCP RPC transport — the stdlib DCN leg (ISSUE 15).

The websocket transport (rpc/websocket.py) needs the optional
``websockets`` dependency; the multi-host mesh's cross-process relay must
not. This module hosts an :class:`~.hub.RpcHub` over raw asyncio TCP
streams with the same wire contract: length-prefixed wire-serialized
:class:`~.message.RpcMessage` frames, a stable ``clientId`` handshake so a
re-dialed connection lands on the SAME server peer (reconnect dedup /
re-send work across physical connections), and reader/writer adapters
matching the peer's channel protocol.

This is what makes ``fusion_mesh_dcn_fallback_total`` an EXERCISED path:
a frontier fence for a key owned by an off-mesh member rides this socket
between real OS processes (perf/mesh_multihost.py drives it; the tier1
multihost smoke gates on the frames actually arriving).

Framing: ``<I`` length prefix per message, handshake = one line
``clientId\\n`` sent by the client before the first frame. The server
peer's ref is ``<prefix><clientId>`` — mesh workers pass ``ref_prefix=""``
so a member process's peer ref IS its member name (the fan-out index's
DCN classification keys on it).
"""
from __future__ import annotations

import asyncio
import logging
import random
import secrets
import struct
from typing import Optional

from ..utils.serialization import dumps, loads
from .hub import RpcHub
from .message import RpcMessage
from .peer import RpcClientPeer

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["RpcTcpServer", "tcp_client_connector"]

_MAX_FRAME = 64 * 1024 * 1024
_MAX_HELLO = 256
#: dial retry ladder (ISSUE 16): bounded, jittered — a refused dial during
#: a mesh re-form window is expected weather, not an instant failure, but
#: it must stay COUNTED (``tcp_dial_retry``) and bounded (the breaker owns
#: long-horizon gating; this ladder only rides out sub-second races)
_DIAL_ATTEMPTS = 4
_DIAL_BACKOFF_BASE_S = 0.05
_DIAL_BACKOFF_CAP_S = 0.5


def _record_event(kind: str, detail: str) -> None:
    """Journal a transport event into the resilience ledger (deferred
    import — rpc must stay importable without the resilience package
    initialized, the middleware.py convention)."""
    from ..resilience.events import global_events

    global_events().record(kind, detail)


class _TcpAdapter:
    """Adapts one asyncio TCP stream to the peer's reader/writer protocol.

    Sends are serialized under a lock (a partially-written length-prefixed
    frame interleaved with a sibling's would desync the whole stream — the
    PR 11 fd-channel lesson) and each ``send()`` resolves or raises with
    its own transport outcome, so the peer's re-send / failure
    disambiguation is unchanged."""

    class _Reader:
        def __init__(self, reader: asyncio.StreamReader):
            self._reader = reader

        async def receive(self) -> RpcMessage:
            try:
                head = await self._reader.readexactly(4)
                (length,) = struct.unpack("<I", head)
                if length > _MAX_FRAME:
                    raise ValueError(f"frame of {length}B exceeds cap")
                return loads(await self._reader.readexactly(length))
            except ConnectionError as e:
                _record_event("tcp_link_death", f"recv: {e}")
                raise
            except asyncio.IncompleteReadError as e:
                # EOF mid-frame: the link died under us — counted, then
                # surfaced as ConnectionError so the peer's run loop tears
                # the connection down and reconnects
                _record_event("tcp_link_death", "recv: eof mid-frame")
                raise ConnectionError(str(e)) from e
            except Exception as e:  # noqa: BLE001 — closed/aborted/corrupt
                # a malformed or truncated frame is a TRANSPORT failure:
                # surface it as ConnectionError so the peer's run loop
                # tears the connection down and reconnects
                _record_event("tcp_link_death", f"recv: {type(e).__name__}")
                raise ConnectionError(str(e)) from e

    class _Writer:
        def __init__(self, writer: asyncio.StreamWriter):
            self._writer = writer
            self._lock = asyncio.Lock()

        async def send(self, message: RpcMessage) -> None:
            data = dumps(message)
            async with self._lock:
                try:
                    self._writer.write(struct.pack("<I", len(data)) + data)
                    await self._writer.drain()
                except Exception as e:  # noqa: BLE001 — link died mid-send
                    _record_event("tcp_link_death", f"send: {type(e).__name__}")
                    raise ConnectionError(str(e)) from e

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = _TcpAdapter._Reader(reader)
        self.writer = _TcpAdapter._Writer(writer)
        self._stream_writer = writer
        self.close_races = 0

    def close(self, error: Optional[BaseException] = None) -> None:
        try:
            self._stream_writer.close()
        except Exception:  # noqa: BLE001 — already closed / loop gone; the
            # peer state machine has recorded the connection outcome
            self.close_races += 1


class RpcTcpServer:
    """Hosts an RpcHub over plain TCP (the stdlib counterpart of
    :class:`~.websocket.RpcWebSocketServer`)."""

    def __init__(
        self,
        hub: RpcHub,
        host: str = "127.0.0.1",
        port: int = 0,
        ref_prefix: str = "tcp:",
    ):
        self.hub = hub
        self.host = host
        self.port = port
        self.ref_prefix = ref_prefix
        self._server: Optional[asyncio.base_events.Server] = None
        #: dials that died before a valid hello (probes, port scans) and
        #: handler teardown races — operator stats, never silent exits
        self.hello_failures = 0
        self.handler_races = 0

    async def start(self) -> "RpcTcpServer":
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.debug("rpc tcp server on %s:%d", self.host, self.port)
        return self

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            hello = await asyncio.wait_for(
                reader.readline(), timeout=10.0
            )
        except Exception:  # noqa: BLE001 — probe/dead dial before hello: a
            # normal exit, not an RPC failure (the PR 12 health-probe
            # taxonomy lesson), but still visible in the server stats
            self.hello_failures += 1
            writer.close()
            return
        client_id = hello.decode("utf-8", "replace").strip()
        if not client_id or len(client_id) > _MAX_HELLO:
            self.hello_failures += 1
            writer.close()
            return
        peer = self.hub.server_peer(f"{self.ref_prefix}{client_id}")
        adapter = _TcpAdapter(reader, writer)
        peer.connect(adapter)
        # hold the handler open until the socket dies (start_server cancels
        # handlers at close; the peer's run loop owns frame processing)
        try:
            await writer.wait_closed()
        except Exception:  # noqa: BLE001 — peer torn down first; the
            # connection state machine already recorded the outcome
            self.handler_races += 1

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None


def tcp_client_connector(host: str, port: int, client_id: Optional[str] = None):
    """Client connector factory:
    ``hub.client_connector = tcp_client_connector(host, port)``.

    The generated clientId is stable per connector, so reconnects resume
    the same server peer (reconnect dedup). Pass an explicit ``client_id``
    (e.g. the member name) to pin the server-side peer ref — the mesh
    workers do, so the fan-out DCN classification sees the member.

    Dial failures retry on a bounded jittered backoff ladder
    (``_DIAL_ATTEMPTS`` tries, each counted as ``tcp_dial_retry`` in the
    resilience ledger) — a refused connection during a mesh re-form window
    rides out the race instead of failing the peer, but the ladder is
    BOUNDED: past it, the failure surfaces and the circuit breaker owns
    the long-horizon gating. Nothing is swallowed silently."""
    cid = client_id or f"c-{secrets.token_hex(8)}"

    async def connect(peer: RpcClientPeer) -> _TcpAdapter:
        last: Optional[BaseException] = None
        for attempt in range(_DIAL_ATTEMPTS):
            if attempt:
                delay = min(
                    _DIAL_BACKOFF_BASE_S * (2 ** (attempt - 1)),
                    _DIAL_BACKOFF_CAP_S,
                ) * (0.5 + random.random())
                _record_event(
                    "tcp_dial_retry",
                    f"{host}:{port} attempt={attempt + 1} "
                    f"after {type(last).__name__}",
                )
                await asyncio.sleep(delay)
            try:
                reader, writer = await asyncio.open_connection(host, port)
                writer.write(cid.encode() + b"\n")
                await writer.drain()
                return _TcpAdapter(reader, writer)
            except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                last = e
        raise ConnectionError(
            f"dial {host}:{port} failed after {_DIAL_ATTEMPTS} attempts: {last}"
        ) from last

    return connect
