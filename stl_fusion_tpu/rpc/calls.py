"""Outbound/inbound call machinery.

Re-expression of src/Stl.Rpc/Infrastructure/RpcOutboundCall.cs:7-162 and
RpcInboundCall.cs:8-243:

- an OUTBOUND call registers itself with its peer (so reconnect can re-send
  it, RpcPeer.cs:116-119), serializes its arguments, sends, and awaits a
  ``$sys`` completion (Ok / Error / Cancel); awaiter cancellation pushes a
  ``$sys.cancel`` to the server;
- an INBOUND call dedups by (peer, call_id) — a re-sent call after reconnect
  finds the registered call and just re-sends its result (``Restart``,
  RpcInboundCall.cs:160-173) — invokes the target, and reports via ``$sys``.

Call *types* (plain vs compute) come from a small registry so the Fusion
client layer can swap in call classes that carry invalidation subscriptions
(Client/Internal/RpcComputeCallType.cs) without the peer knowing.
"""
from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING, Any, Dict, Optional, Tuple, Type

from ..utils.async_utils import ChannelClosedError
from ..utils.errors import ExceptionInfo
from ..utils.serialization import dumps, loads
from .message import CALL_TYPE_PLAIN, SYSTEM_SERVICE, RpcMessage

if TYPE_CHECKING:
    from .peer import RpcPeer

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["RpcOutboundCall", "RpcInboundCall", "RpcCallTypeRegistry"]


class RpcOutboundCall:
    """One client-side call bound to a peer."""

    call_type_id = CALL_TYPE_PLAIN

    def __init__(
        self,
        peer: "RpcPeer",
        service: str,
        method: str,
        args: tuple,
        no_wait: bool = False,
        headers: tuple = (),
    ):
        self.peer = peer
        self.service = service
        self.method = method
        self.args = args
        self.no_wait = no_wait
        #: extra wire headers stamped on the call message (the cluster
        #: router's ``@shard``/``@epoch``/``@failover`` stamps ride here);
        #: fixed at call creation, so a reconnect re-send replays the SAME
        #: stamp — a re-sent call with a stale epoch is rejected with the
        #: current map, which is exactly the sync the client needs
        self.headers = headers
        self.call_id = peer.allocate_call_id()
        self.future: Optional[asyncio.Future] = None if no_wait else asyncio.get_event_loop().create_future()

    # -- wire --------------------------------------------------------------
    def to_message(self) -> RpcMessage:
        return RpcMessage(
            call_type_id=self.call_type_id,
            call_id=self.call_id,
            service=self.service,
            method=self.method,
            argument_data=dumps(list(self.args)),
            headers=self.headers,
        )

    # -- lifecycle ---------------------------------------------------------
    async def invoke(self) -> Any:
        """Register → send → await completion (with cancel propagation)."""
        if not self.no_wait:
            self.peer.outbound_calls[self.call_id] = self
        try:
            await self.peer.send(self.to_message())
        except Exception:
            # not connected yet: stay registered; reconnect re-sends us
            if self.no_wait:
                raise
        if self.no_wait:
            return None
        try:
            return await self.future
        except asyncio.CancelledError:
            self.peer.outbound_calls.pop(self.call_id, None)
            try:
                await self.peer.send_system("cancel", [self.call_id])
            except Exception:  # noqa: BLE001 — best-effort cancel
                pass
            raise

    # -- completion (from $sys) -------------------------------------------
    def set_result(self, value: Any, message: RpcMessage) -> None:
        self.peer.outbound_calls.pop(self.call_id, None)
        if self.future is not None and not self.future.done():
            self.future.set_result(value)

    def set_error(self, error: BaseException) -> None:
        self.peer.outbound_calls.pop(self.call_id, None)
        if self.future is not None and not self.future.done():
            self.future.set_exception(error)


class RpcInboundCall:
    """One server-side call; registered for reconnect dedup."""

    def __init__(self, peer: "RpcPeer", message: RpcMessage):
        self.peer = peer
        self.message = message
        self.call_id = message.call_id
        self.result_message: Optional[RpcMessage] = None
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self.peer.inbound_calls[self.call_id] = self
        self._task = asyncio.get_event_loop().create_task(self._run_gated())

    async def _run_gated(self) -> None:
        # per-peer inbound concurrency limit (system calls never come through
        # here, so they are exempt — reference RpcPeer.cs:100-110)
        semaphore = self.peer.inbound_semaphore
        if semaphore is None:
            await self._run()
            return
        try:
            await semaphore.acquire()
        except asyncio.CancelledError:
            # cancelled while QUEUED: _run never starts, so its cleanup
            # never runs — unregister here or the stale entry swallows any
            # post-reconnect re-send of this call id forever
            self.peer.inbound_calls.pop(self.call_id, None)
            raise
        try:
            await self._run()
        finally:
            semaphore.release()

    def restart(self) -> None:
        """Duplicate delivery (client re-sent after reconnect): re-send the
        result if we have one; otherwise the original task is still running
        and will send it."""
        if self.result_message is not None:
            self.peer.track_side_task(
                asyncio.get_event_loop().create_task(self._resend_result())
            )

    async def _resend_result(self) -> None:
        # a non-transport redelivery failure answers with a one-shot error
        # (completing the client's re-sent call with it) while the STORED
        # result stays the true one — a transient middleware failure is
        # surfaced as that one call's error, never memoized as the result
        await self._deliver_or_error()

    async def _run(self) -> None:
        # Phase 1 — produce the result MESSAGE. A target failure OR a
        # result-serialization failure is the call's result (an error the
        # client must see); ExceptionInfo itself is always wire-safe.
        try:
            result = await self.invoke_target()
            self._build_ok(result)
        except asyncio.CancelledError:
            self.peer.inbound_calls.pop(self.call_id, None)
            raise
        except Exception as e:  # noqa: BLE001
            self._build_error(e)
        # Phase 2 — deliver it. TRANSPORT death is NOT a call error: the
        # stored result_message survives and the post-reconnect redelivery
        # (restart) re-sends it — overwriting it with the transport
        # exception (the pre-soak behavior) served the client a RemoteError
        # for a call that actually succeeded. A NON-transport delivery
        # failure (e.g. a middleware deterministically rejecting the
        # message) falls back to a last-resort error reply so the client
        # errors instead of hanging.
        try:
            await self._deliver_or_error()
        except asyncio.CancelledError:
            self.peer.inbound_calls.pop(self.call_id, None)
            raise
        self.on_completed()

    async def invoke_target(self) -> Any:
        args = loads(self.message.argument_data)
        # the RPC boundary is a dependency-capture boundary: this task may
        # have inherited a computing node's contextvars from whatever task
        # transitively started the peer (single-process client+server), and
        # capturing server nodes into a CLIENT computed would short-circuit
        # the graph across the "wire"
        from ..core.context import ComputeContext, suspend_dependency_capture

        with suspend_dependency_capture(), ComputeContext.DEFAULT.activate():
            return await self.peer.hub.service_registry.invoke(
                self.message.service, self.message.method, args
            )

    def _build_ok(self, result: Any, headers: tuple = ()) -> None:
        """Serialize + store the OK reply (serialization errors propagate —
        they are CALL errors, the link is fine)."""
        self.result_message = RpcMessage(
            call_type_id=self.message.call_type_id,
            call_id=self.call_id,
            service=SYSTEM_SERVICE,
            method="ok",
            argument_data=dumps(result),
            headers=headers,
        )

    def _error_message(self, error: BaseException) -> RpcMessage:
        return RpcMessage(
            call_type_id=self.message.call_type_id,
            call_id=self.call_id,
            service=SYSTEM_SERVICE,
            method="error",
            argument_data=dumps(ExceptionInfo.capture(error)),
        )

    def _build_error(self, error: BaseException) -> None:
        self.result_message = self._error_message(error)

    async def _deliver(self) -> None:
        """Send the stored result; TRANSPORT failures are swallowed — the
        post-reconnect redelivery re-sends. Anything else propagates.

        Classification is by the ``_transport_death`` tag the peer stamps
        on every genuine transport failure AT ITS RAISE SITE (race-free —
        never by peeking at the peer's mutable connection slot, which a
        reconnect can refresh before this except clause runs). An
        OSError-shaped exception WITHOUT the tag is a middleware failure
        in disguise (PermissionError from an auth middleware IS an OSError
        subclass) — swallow it and nothing would ever re-send: the client
        hangs on a healthy connection. Those re-raise for the error-reply
        fallback."""
        try:
            await self.peer.send(self.result_message)
        except asyncio.CancelledError:
            raise
        except (ChannelClosedError, ConnectionError, OSError) as e:
            if not getattr(e, "_transport_death", False):
                raise

    async def _deliver_or_error(self) -> None:
        """Deliver the result; a NON-transport failure is answered with a
        ONE-SHOT error reply so the client errors instead of hanging —
        WITHOUT overwriting the stored result_message, which must stay the
        call's true result for any later redelivery."""
        try:
            await self._deliver()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            try:
                await self.peer.send(self._error_message(e))
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — nothing more we can do
                pass

    async def send_ok(self, result: Any, headers: tuple = ()) -> None:
        self._build_ok(result, headers)
        await self._deliver()

    async def send_error(self, error: BaseException) -> None:
        self._build_error(error)
        await self._deliver()

    def on_completed(self) -> None:
        """Plain calls stay registered for redelivery dedup; the peer prunes
        completed entries with a recently-seen window."""
        self.peer.note_inbound_completed(self.call_id)

    def cancel(self) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()


class RpcCallTypeRegistry:
    """(call_type_id) → (outbound class, inbound class); slot 0 = plain
    calls (≈ RpcCallTypeRegistry.cs:7-40)."""

    def __init__(self):
        self._types: Dict[int, Tuple[Type[RpcOutboundCall], Type[RpcInboundCall]]] = {
            CALL_TYPE_PLAIN: (RpcOutboundCall, RpcInboundCall)
        }

    def register(self, type_id: int, outbound: Type[RpcOutboundCall], inbound: Type[RpcInboundCall]):
        self._types[type_id] = (outbound, inbound)

    def outbound(self, type_id: int) -> Type[RpcOutboundCall]:
        return self._types[type_id][0]

    def inbound(self, type_id: int) -> Type[RpcInboundCall]:
        return self._types[type_id][1]
