"""RpcPeer — the per-connection worker.

Re-expression of src/Stl.Rpc/RpcPeer.cs:6-319: a peer owns one logical link
(surviving physical reconnects), a connection-state AsyncEvent chain, the
outbound/inbound call trackers, and the message pump. On every (re)connect
it RE-SENDS all registered outbound calls (RpcPeer.cs:116-119) — the server
side dedups via registered inbound calls — which is the whole reliability
story: calls survive connection loss without user code noticing.

``RpcClientPeer`` dials with jittered backoff (RpcClientPeerReconnectDelayer);
``RpcServerPeer`` awaits connection handoffs from a listener/transport.
"""
from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING, Any, Dict, Optional, Tuple

from ..utils.async_chain import RetryDelaySeq, WorkerBase
from ..utils.async_utils import AsyncEvent, Channel, ChannelClosedError, ChannelPair
from ..utils.collections import RecentlySeenMap
from ..utils.errors import ExceptionInfo
from ..utils.serialization import dumps, loads
from .message import (
    COMPUTE_SYSTEM_SERVICE,
    DIAG_SYSTEM_SERVICE,
    MEMBER_SYSTEM_SERVICE,
    SYSTEM_SERVICE,
    TABLE_SYSTEM_SERVICE,
    RpcMessage,
)

if TYPE_CHECKING:
    from .hub import RpcHub
    from .outbox import PeerOutbox

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["RpcPeer", "RpcClientPeer", "RpcServerPeer", "ConnectionState"]


async def _run_middlewares(mws, peer, message, terminal) -> None:
    """Run a middleware chain (≈ RpcInbound/OutboundMiddleware,
    Stl.Rpc/Infrastructure/): each middleware is ``async (peer, message,
    nxt)`` and continues with ``await nxt(message)`` — passing a different
    message rewrites it for the rest of the chain."""

    async def run_from(i: int, msg: RpcMessage) -> None:
        if i == len(mws):
            await terminal(msg)
        else:
            await mws[i](peer, msg, lambda m, _i=i: run_from(_i + 1, m))

    await run_from(0, message)


class ConnectionState:
    DISCONNECTED = "disconnected"
    CONNECTED = "connected"
    #: terminal: the peer gave up (unrecoverable connect error or attempt
    #: cap); waiters re-raise the error instead of parking forever
    TERMINATED = "terminated"

    def __init__(self, kind: str, error: Optional[BaseException] = None):
        self.kind = kind
        self.error = error

    @property
    def is_connected(self) -> bool:
        return self.kind == ConnectionState.CONNECTED

    @property
    def is_terminated(self) -> bool:
        return self.kind == ConnectionState.TERMINATED

    def __repr__(self) -> str:
        return f"ConnectionState({self.kind})"


class RpcPeer(WorkerBase):
    def __init__(self, hub: "RpcHub", ref: str):
        super().__init__(f"rpc-peer:{ref}")
        self.hub = hub
        self.ref = ref
        self.connection_state: AsyncEvent[ConnectionState] = AsyncEvent(
            ConnectionState(ConnectionState.DISCONNECTED)
        )
        # 0 = unlimited; n ≥ 1 gates non-system inbound calls through a
        # semaphore of n permits (≈ InboundConcurrencyLevel, RpcPeer.cs:20,
        # 100-110); configured per hub before its peers are created
        level = hub.inbound_concurrency_level
        self.inbound_semaphore: Optional[asyncio.Semaphore] = (
            asyncio.Semaphore(level) if level > 0 else None
        )
        self.outbound_calls: Dict[int, Any] = {}
        self.inbound_calls: Dict[int, Any] = {}
        self._completed_inbound = RecentlySeenMap(capacity=10_000, max_age=600.0)
        # call ids come from the HUB, not this peer object: a peer that is
        # torn down (breaker quarantine, retire) and later re-created for
        # the same ref must NOT restart at 1 — the server keeps completed
        # compute calls registered per client ref so $sys-c pushes survive
        # reconnects, and a reused id makes _process_inbound restart() the
        # OLD subscription, re-sending the old call's result to the new
        # call (a silent cross-wired read that never heals)
        self._call_id_counter = hub._outbound_call_ids
        self._conn: Optional[ChannelPair] = None
        self._resend_failures = 0  # consecutive connect-then-die-on-resend
        self._outbox: Optional["PeerOutbox"] = None
        # strong refs to in-flight $sys-d handler tasks: the event loop
        # holds tasks only weakly, and a collected task silently never
        # sends its explain reply
        self._diag_tasks: set = set()

    # ------------------------------------------------------------------ id/state
    def allocate_call_id(self) -> int:
        return next(self._call_id_counter)

    @property
    def is_connected(self) -> bool:
        return self._conn is not None

    def _set_state(self, kind: str, error: Optional[BaseException] = None) -> None:
        self.connection_state = self.connection_state.latest().create_next(
            ConnectionState(kind, error)
        )

    async def when_connected(self) -> None:
        ev = self.connection_state.latest()
        if not ev.value.is_connected:
            self.start()
            ev = await ev.when(lambda s: s.is_connected or s.is_terminated)
            if ev.value.is_terminated:
                raise ev.value.error or ConnectionError(
                    f"peer {self.ref} terminated without a connection"
                )

    # ------------------------------------------------------------------ transport
    async def acquire_connection(self) -> ChannelPair:
        """Client: dial (with backoff); server: await handoff."""
        raise NotImplementedError

    # ------------------------------------------------------------------ main loop
    async def on_run(self) -> None:
        while True:
            try:
                conn = await self.acquire_connection()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — unrecoverable connect error
                log.debug("peer %s: terminal connect failure: %s", self.ref, e)
                # fail everything parked on this peer: when_connected waiters
                # re-raise via the TERMINATED state; registered calls error out
                self._set_state(ConnectionState.TERMINATED, e)
                for call in list(self.outbound_calls.values()):
                    call.set_error(e)
                return
            self._conn = conn
            self._set_state(ConnectionState.CONNECTED)
            # reliability: re-send every registered outbound call. A
            # transport failure here means the fresh link is already dead —
            # falling into receive() would park the UNSENT calls until some
            # unrelated event dropped the link (VERDICT r1 weak #7), so it
            # forces a reconnect (which re-sends the whole batch again). A
            # non-transport failure (e.g. a call that can't serialize) is
            # that call's own error and must not wedge the peer.
            resend_failure: Optional[BaseException] = None
            for call in list(self.outbound_calls.values()):
                try:
                    # through send(), not _send_raw: outbound middlewares
                    # (auth tokens, session replacement) must rewrite a
                    # redelivered call exactly like the original send
                    await self.send(call.to_message())
                except asyncio.CancelledError:
                    conn.close()
                    raise
                except (ChannelClosedError, ConnectionError, OSError) as e:
                    resend_failure = e
                    break
                except Exception as e:  # noqa: BLE001 — per-call poison
                    call.set_error(e)
            if resend_failure is not None:
                self._conn = None
                conn.close(resend_failure)
                self._set_state(ConnectionState.DISCONNECTED, resend_failure)
                # connect-then-immediate-death bypasses the dial backoff
                # (the successful connect reset it) — bound the redial rate
                self._resend_failures += 1
                await asyncio.sleep(min(0.05 * (2 ** (self._resend_failures - 1)), 2.0))
                continue
            self._resend_failures = 0
            try:
                # one clock probe per (re)connect: delivery histograms can
                # then map this peer's origin_ts stamps onto the local
                # timeline (ISSUE 9 — cross-host clock-safe timestamps).
                # The PREVIOUS connection's sample is dropped first: a
                # peer-host reboot resets its perf_counter epoch, and a
                # pinned min-RTT sample from the old epoch would be wildly
                # wrong forever (offsets are per-connection truths).
                # Best-effort: a link that dies here dies in receive() too.
                from ..diagnostics.clocksync import global_clock_sync

                global_clock_sync().forget(self.ref)
                await self.probe_clock()
            except asyncio.CancelledError:
                conn.close()
                raise
            except Exception:  # noqa: BLE001 — telemetry must not wedge the pump
                pass
            try:
                while True:
                    message = await conn.reader.receive()
                    await self.process_message(message)
            except asyncio.CancelledError:
                conn.close()
                raise
            except (ChannelClosedError, ConnectionError, OSError) as e:
                self._conn = None
                self._set_state(ConnectionState.DISCONNECTED, e)
                continue  # reconnect loop

    # ------------------------------------------------------------------ send
    @staticmethod
    def _not_connected(ref: str) -> ConnectionError:
        e = ConnectionError(f"peer {ref} is not connected")
        e._transport_death = True  # see _send_raw
        return e

    @property
    def outbox(self) -> "PeerOutbox":
        """The per-peer outbound drain queue + invalidation coalescer
        (created lazily — a peer that never sends never pays for it)."""
        if self._outbox is None:
            from .outbox import PeerOutbox

            self._outbox = PeerOutbox(self)
        return self._outbox

    async def send(self, message: RpcMessage) -> None:
        """Deliver one message, in per-peer FIFO order.

        Routed through the outbox drain queue: concurrent senders no longer
        interleave on the raw channel (order is the queue's, surviving
        whatever order the loop wakes tasks in), and a sender behind a slow
        frame is parked in the queue instead of on the channel. The error
        contract is unchanged — this resolves when the message hit the
        channel and raises what the channel write raised. The no-backlog
        fast path below keeps a lone send at its pre-outbox cost (one
        awaited channel write, no queue hop)."""
        if self._conn is None:
            raise self._not_connected(self.ref)
        outbox = self._outbox
        if outbox is None or outbox.can_bypass():
            ob = outbox if outbox is not None else self.outbox
            ob._in_flight = True
            try:
                await self._send_now(message)
                ob.messages_sent += 1
            finally:
                ob._in_flight = False
                if ob._fifo or ob._pending_inval:
                    ob._kick()
            return
        await outbox.send(message)

    async def _send_now(self, message: RpcMessage) -> None:
        """The raw delivery step (middlewares + channel write) — only the
        outbox drain and its bypass fast path may call this; everything
        else goes through :meth:`send` so FIFO order holds."""
        mws = self.hub.outbound_middlewares
        if mws:
            await _run_middlewares(mws, self, message, self._send_raw)
        else:
            await self._send_raw(message)

    async def _send_raw(self, message: RpcMessage) -> None:
        conn = self._conn
        if conn is None:
            raise self._not_connected(self.ref)
        try:
            await conn.writer.send(message)
        except asyncio.CancelledError:
            raise
        except (ChannelClosedError, ConnectionError, OSError) as e:
            # a failed SEND means the link is dead even when the reader
            # still hangs (the half-open shape): tear the connection down
            # so the pump notices and reconnects — otherwise a parked
            # registered call waits for a reconnect that never comes.
            # Guarded: a STALE sender waking up after a reconnect must not
            # tear down the fresh healthy connection that replaced its own.
            # EVERY genuine transport failure is tagged on the exception at
            # its raise site: delivery paths classify by this tag (race-
            # free), never by peeking at the shared mutable _conn — an
            # OSError-shaped exception WITHOUT the tag is a middleware
            # failure in disguise.
            e._transport_death = True
            if self._conn is conn:
                await self.disconnect(e)
            raise

    async def send_system(self, method: str, args: list, call_id: int = 0, headers: tuple = ()) -> None:
        await self.send(
            RpcMessage(0, call_id, SYSTEM_SERVICE, method, dumps(args), headers)
        )

    # ------------------------------------------------------------------ dispatch
    async def process_message(self, message: RpcMessage) -> None:
        """Dispatch one inbound message through the middleware chain.

        Failures are isolated PER MESSAGE: a middleware that rejects a call
        (auth raising PermissionError — an OSError subclass the pump would
        misread as a transport death) or a buggy middleware must neither
        tear down a healthy connection nor crash the pump; the caller gets
        a ``$sys.error`` reply instead of hanging."""
        try:
            mws = self.hub.inbound_middlewares
            if mws:
                await _run_middlewares(mws, self, message, self._dispatch_message)
            else:
                await self._dispatch_message(message)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            # counted (FL002): every degraded branch below is a fallback —
            # an error-rate spike must be scrapeable, not only in the logs
            from ..diagnostics.metrics import global_metrics

            global_metrics().counter(
                "fusion_rpc_process_failures_total",
                help="inbound messages whose processing raised (per-branch recovery below)",
            ).inc()
            log.exception(
                "peer %s: processing %s.%s #%d failed",
                self.ref, message.service, message.method, message.call_id,
            )
            if message.service == SYSTEM_SERVICE:
                # a completion ($sys.ok/.error) that failed to process must
                # not leave the awaiting caller parked forever on a healthy-
                # looking link — surface the failure to the call itself
                call = self.outbound_calls.get(message.call_id)
                if call is not None:
                    call.set_error(e)
            elif message.service in (COMPUTE_SYSTEM_SERVICE, TABLE_SYSTEM_SERVICE):
                # a dropped invalidation/fence push would mean stale-forever;
                # tear the link down so the reconnect re-send/re-register (or
                # invalidate-all-and-resubscribe) cycle restores consistency
                await self.disconnect(e)
            elif message.call_id:
                try:
                    await self.send(
                        RpcMessage(
                            message.call_type_id,
                            message.call_id,
                            SYSTEM_SERVICE,
                            "error",
                            dumps(ExceptionInfo.capture(e)),
                        )
                    )
                except Exception:  # noqa: BLE001 — the pump must survive
                    pass

    async def _dispatch_message(self, message: RpcMessage) -> None:
        if message.service == SYSTEM_SERVICE:
            self._process_system(message)
        elif message.service == COMPUTE_SYSTEM_SERVICE:
            handler = self.hub.compute_system_handler
            if handler is not None:
                handler(self, message)
        elif message.service == TABLE_SYSTEM_SERVICE:
            handler = self.hub.table_system_handler
            if handler is not None:
                handler(self, message)
        elif message.service == DIAG_SYSTEM_SERVICE:
            handler = self.hub.diag_system_handler
            if handler is not None:
                result = handler(self, message)
                if asyncio.iscoroutine(result):
                    # spawned, never awaited inline: diagnostics traffic
                    # must not head-of-line-block the receive pump — a slow
                    # explain resolution would otherwise delay the $sys-c
                    # invalidation frames queued behind it on this link. A
                    # hub with no handler silently drops the frame
                    # (introspection is additive, never load-bearing).
                    task = asyncio.get_event_loop().create_task(result)
                    self._diag_tasks.add(task)
                    task.add_done_callback(self._on_diag_done)
        elif message.service == MEMBER_SYSTEM_SERVICE:
            handler = self.hub.member_system_handler
            if handler is not None:
                result = handler(self, message)
                if asyncio.iscoroutine(result):
                    # same discipline as $sys-d: membership bookkeeping may
                    # need to SEND (a map reply to a heartbeat), and that
                    # awaited send must not head-of-line-block this link's
                    # receive pump. A hub with no handler drops the frame —
                    # a cluster-unaware peer ignores the control plane.
                    task = asyncio.get_event_loop().create_task(result)
                    self._diag_tasks.add(task)
                    task.add_done_callback(self._on_diag_done)
        else:
            self._process_inbound(message)

    def track_side_task(self, task: "asyncio.Task") -> "asyncio.Task":
        """Adopt a fire-and-forget task into this peer's lifecycle (the
        fusionlint FL003 contract): a strong ref until it settles — the
        loop holds tasks weakly — and cancellation at ``stop()``. Failures
        ride the ``_on_diag_done`` swallow: side traffic (resends,
        invalidation pushes, explain replies) times out at the asker and
        must never surface as an unhandled-task error on the serving loop."""
        self._diag_tasks.add(task)
        task.add_done_callback(self._on_diag_done)
        return task

    def _on_diag_done(self, task: "asyncio.Task") -> None:
        self._diag_tasks.discard(task)
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            # swallowed deliberately: a failed explain reply (dead link,
            # serialization hiccup) times out at the asker; it must never
            # surface as an unhandled-task error on the serving loop
            log.debug("diagnostics handler failed: %s", exc)

    async def probe_clock(self) -> None:
        """Send one NTP-style clock probe (ISSUE 9 satellite: cross-host
        clock-safe delivery timestamps). The ``clock-r`` reply lands the
        ``(t_send, t_remote, t_recv)`` sample in the process-wide
        :class:`~stl_fusion_tpu.diagnostics.clocksync.ClockSync`, keyed by
        this peer's ref; delivery histograms then map the peer's
        ``origin_ts`` stamps onto the local timeline."""
        import time as _time

        await self.send_system("clock", [_time.perf_counter()])

    def _process_system(self, message: RpcMessage) -> None:
        """$sys: ok / error / cancel / not-found (RpcSystemCalls.cs:6-71)
        + clock/clock-r (the ISSUE 9 offset probe)."""
        method = message.method
        if method == "clock":
            import time as _time

            (t_send,) = loads(message.argument_data)
            reply = self.send_system("clock-r", [t_send, _time.perf_counter()])
            # fire-and-forget on the pump's loop: a probe reply must never
            # block message processing (same discipline as $sys-d)
            task = asyncio.get_event_loop().create_task(reply)
            self._diag_tasks.add(task)
            task.add_done_callback(self._on_diag_done)
            return
        if method == "clock-r":
            import time as _time

            from ..diagnostics.clocksync import global_clock_sync

            t_send, t_remote = loads(message.argument_data)
            global_clock_sync().note_sample(
                self.ref, float(t_send), float(t_remote), _time.perf_counter()
            )
            return
        if method == "ok":
            call = self.outbound_calls.get(message.call_id)
            if call is not None:
                call.set_result(loads(message.argument_data), message)
        elif method == "error":
            call = self.outbound_calls.get(message.call_id)
            if call is not None:
                info: ExceptionInfo = loads(message.argument_data)
                call.set_error(info.to_exception())
        elif method == "cancel":
            (call_id,) = loads(message.argument_data)
            inbound = self.inbound_calls.get(call_id)
            if inbound is not None:
                inbound.cancel()
        elif method == "not-found":
            call = self.outbound_calls.get(message.call_id)
            if call is not None:
                call.set_error(LookupError("remote endpoint not found"))

    def _process_inbound(self, message: RpcMessage) -> None:
        existing = self.inbound_calls.get(message.call_id)
        if existing is not None:
            existing.restart()  # duplicate delivery after reconnect
            return
        if message.call_id in self._completed_inbound:
            return  # already served and pruned
        inbound_cls = self.hub.call_types.inbound(message.call_type_id)
        inbound_cls(self, message).start()

    def note_inbound_completed(self, call_id: int) -> None:
        # keep the entry for redelivery dedup; prune via recently-seen window
        self._completed_inbound.try_add(call_id)

    # ------------------------------------------------------------------ disconnect
    async def disconnect(self, error: Optional[BaseException] = None) -> None:
        conn, self._conn = self._conn, None
        if conn is not None:
            conn.close(error)
            # surface the drop immediately — the pump notices asynchronously
            self._set_state(ConnectionState.DISCONNECTED, error)

    async def stop(self) -> None:
        await self.disconnect()
        if self._outbox is not None:
            self._outbox.stop()
        for task in list(self._diag_tasks):
            # in-flight explain replies die with the peer — left pending
            # they surface as "Task was destroyed but it is pending!" at
            # loop close (the asker's timeout covers the lost reply)
            task.cancel()
        await super().stop()


class RpcClientPeer(RpcPeer):
    """Dials via the hub's client connector with jittered backoff
    (≈ RpcClientPeer.cs:6-55 + RpcClientPeerReconnectDelayer)."""

    def __init__(self, hub: "RpcHub", ref: str, reconnect_delays: Optional[RetryDelaySeq] = None):
        super().__init__(hub, ref)
        self.reconnect_delays = reconnect_delays or RetryDelaySeq(min_delay=0.05, max_delay=5.0)
        self.reconnects_at: Optional[float] = None

    async def acquire_connection(self) -> ChannelPair:
        failures = 0
        while True:
            try:
                conn = await self.hub.connect_client(self)
                self.reconnects_at = None
                return conn
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                if self.hub.unrecoverable_error_detector(e):
                    # config/programming error — retrying can never succeed
                    # (≈ RpcUnrecoverableErrorDetector, RpcPeer.cs:268-274)
                    raise
                failures += 1
                if failures > self.hub.max_connect_attempts:
                    raise
                delay = self.reconnect_delays[failures]
                self.reconnects_at = asyncio.get_event_loop().time() + delay
                log.debug("peer %s reconnect #%d in %.2fs (%s)", self.ref, failures, delay, e)
                await asyncio.sleep(delay)


class RpcServerPeer(RpcPeer):
    """Receives connections from a listener (≈ RpcServerPeer.cs)."""

    def __init__(self, hub: "RpcHub", ref: str):
        super().__init__(hub, ref)
        self._handoff: "asyncio.Queue[ChannelPair]" = asyncio.Queue()

    def connect(self, conn: ChannelPair) -> None:
        """Hand a fresh transport to this peer (new physical connection)."""
        old, self._conn = self._conn, None
        if old is not None:
            old.close()
        self._handoff.put_nowait(conn)
        self.start()

    async def acquire_connection(self) -> ChannelPair:
        return await self._handoff.get()
