"""WebSocket transport — the real-network counterpart of the test channels.

Re-expression of src/Stl.Rpc/WebSockets/WebSocketChannel.cs:11-120 +
Rpc.Server/RpcWebSocketServer.cs:32-64 + Clients/RpcWebSocketClient.cs:
messages ride binary frames (wire-serialized RpcMessage); the client sends a
stable ``clientId`` query parameter so a re-dialed connection lands on the
SAME server peer — which is what makes reconnect dedup/re-send work across
physical connections (SessionBoundRpcConnection analogue).
"""
from __future__ import annotations

import asyncio
import collections
import logging
import secrets
import struct
import urllib.parse
from typing import Optional

from ..utils.serialization import dumps, loads
from .hub import RpcHub
from .message import RpcMessage
from .peer import RpcClientPeer

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["RpcWebSocketServer", "websocket_client_connector", "websocket_multi_connector"]

RPC_PATH = "/rpc/ws"


class _WsAdapter:
    """Adapts a websockets connection to the peer's reader/writer protocol.

    Framing (≈ WebSocketChannel.cs:14-37): one websocket frame carries ONE
    OR MORE length-prefixed wire-serialized RpcMessages. Small outbound
    messages that are ready together — an invalidation flood, a re-send
    burst — coalesce into ~4 KB frames instead of paying per-message frame
    overhead, with no added latency: the flusher packs only what is already
    queued when it runs. The outbound buffer is BOUNDED (128 messages);
    senders block when it is full — backpressure is the overflow policy,
    never unbounded buffering in the websocket library. Each ``send()``
    still resolves or raises with its own message's transport outcome, so
    the peer's re-send / failure-disambiguation logic is unchanged.
    """

    PACK_BYTES = 4096  # stop adding to a frame once it crosses this
    MAX_PENDING = 128  # outbound bound (≈ the reference's channel capacity)

    class _Reader:
        def __init__(self, ws):
            self._ws = ws
            self._parsed: "collections.deque[RpcMessage]" = collections.deque()

        async def receive(self) -> RpcMessage:
            while not self._parsed:
                try:
                    frame = await self._ws.recv()
                except Exception as e:  # noqa: BLE001 — closed/aborted
                    raise ConnectionError(str(e)) from e
                buf = frame if isinstance(frame, bytes) else frame.encode()
                off = 0
                # a malformed pack (truncated frame, corrupt length) is a
                # TRANSPORT failure: surface it as ConnectionError so the
                # peer's run loop tears the connection down and reconnects,
                # instead of an unhandled parse error killing the loop task
                # with the peer stuck "connected" forever
                try:
                    while off < len(buf):
                        (length,) = struct.unpack_from("<I", buf, off)
                        off += 4
                        if length > len(buf) - off:
                            raise ValueError(
                                f"frame truncated: {length}B message, "
                                f"{len(buf) - off}B left"
                            )
                        self._parsed.append(loads(bytes(buf[off : off + length])))
                        off += length
                except ConnectionError:
                    raise
                except Exception as e:  # noqa: BLE001 — corrupt frame
                    raise ConnectionError(f"malformed frame: {e}") from e
            return self._parsed.popleft()

    class _Writer:
        def __init__(self, ws):
            self._ws = ws
            self._pending: "collections.deque" = collections.deque()
            self._inflight: list = []  # current batch's futures, popped from _pending
            self._wake = asyncio.Event()
            self._space = asyncio.Event()
            self._space.set()
            self._error: Optional[BaseException] = None
            self._task = asyncio.ensure_future(self._flush_loop())

        async def send(self, message: RpcMessage) -> None:
            data = dumps(message)
            while self._error is None and len(self._pending) >= _WsAdapter.MAX_PENDING:
                self._space.clear()
                await self._space.wait()
            if self._error is not None:
                raise ConnectionError(str(self._error)) from self._error
            fut = asyncio.get_running_loop().create_future()
            self._pending.append((data, fut))
            self._wake.set()
            await fut

        async def _flush_loop(self) -> None:
            try:
                while True:
                    await self._wake.wait()
                    self._wake.clear()
                    while self._pending:
                        parts, size = [], 0
                        futs = self._inflight
                        while self._pending and (not parts or size < _WsAdapter.PACK_BYTES):
                            data, fut = self._pending.popleft()
                            parts.append(struct.pack("<I", len(data)))
                            parts.append(data)
                            futs.append(fut)
                            size += len(data)
                        self._space.set()
                        try:
                            await self._ws.send(b"".join(parts))
                        except Exception as e:  # noqa: BLE001
                            self._fail(e, futs)
                            return
                        for fut in futs:
                            if not fut.done():
                                fut.set_result(None)
                        futs.clear()
            except asyncio.CancelledError:
                # cancellation mid-send (adapter.close()): the current batch
                # was already popped from _pending — fail those futures too,
                # or every send() awaiting this batch hangs forever
                self._fail(ConnectionError("transport closed"), self._inflight)
                raise
            except Exception as e:  # noqa: BLE001 — anything else that kills
                # the loop (ws.send errors are handled inline above; this
                # covers any other failure) must fail the popped batch too,
                # or senders awaiting it hang forever on a dead task
                self._fail(e, self._inflight)
                raise

        def _fail(self, error: BaseException, futs: list) -> None:
            self._error = error
            self._space.set()
            drained = [f for _, f in self._pending]
            self._pending.clear()
            for fut in futs + drained:
                if not fut.done():
                    fut.set_exception(ConnectionError(str(error)))

    def __init__(self, ws):
        self._ws = ws
        self.reader = _WsAdapter._Reader(ws)
        self.writer = _WsAdapter._Writer(ws)
        self._close_task: Optional["asyncio.Task"] = None

    def close(self, error: Optional[BaseException] = None) -> None:
        self.writer._task.cancel()
        if self._close_task is None or self._close_task.done():
            # retained on the adapter (FL003): the loop holds tasks weakly,
            # and a collected close task leaves the socket half-open
            self._close_task = asyncio.ensure_future(self._ws.close())


class RpcWebSocketServer:
    """Hosts an RpcHub over websockets (≈ RpcWebSocketServer + route map)."""

    def __init__(self, hub: RpcHub, host: str = "127.0.0.1", port: int = 0):
        self.hub = hub
        self.host = host
        self.port = port
        self._server = None

    async def start(self) -> "RpcWebSocketServer":
        from websockets.asyncio.server import serve

        self._server = await serve(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.debug("rpc websocket server on %s:%d", self.host, self.port)
        return self

    @property
    def url(self) -> str:
        return f"ws://{self.host}:{self.port}{RPC_PATH}"

    async def _handle(self, ws) -> None:
        path = ws.request.path if ws.request is not None else RPC_PATH
        query = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
        client_id = (query.get("clientId") or [f"anon-{secrets.token_hex(4)}"])[0]
        peer = self.hub.server_peer(f"ws:{client_id}")
        peer.connect(_WsAdapter(ws))
        # hold the handler open until the socket dies (websockets closes on return)
        try:
            await ws.wait_closed()
        except Exception:  # noqa: BLE001
            pass

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None


def websocket_client_connector(url: str, client_id: Optional[str] = None):
    """Client connector factory: ``hub.client_connector = websocket_client_connector(url)``.

    The generated clientId is stable per connector, so reconnects resume the
    same server peer (reconnect dedup).
    """
    cid = client_id or f"c-{secrets.token_hex(8)}"

    async def connect(peer: RpcClientPeer):
        return await _dial(url, cid, peer)

    return connect


def websocket_multi_connector(url_by_ref, client_id: Optional[str] = None):
    """Connector for a server pool: resolve the peer ref to its host URL
    (≈ ``RpcWebSocketClient.Options.HostUrlResolver`` where the peer ref IS
    the host url, samples/MultiServerRpc/Program.cs:52-55). ``url_by_ref``
    maps peer refs to websocket URLs; together with a ``call_router`` over
    the same refs this gives per-call sharding across servers.
    """
    cid = client_id or f"c-{secrets.token_hex(8)}"

    async def connect(peer: RpcClientPeer):
        # an unknown ref is a config error, not a transient network failure —
        # fail loudly instead of entering the reconnect/backoff loop
        url = url_by_ref.get(peer.ref)
        if url is None:
            raise LookupError(
                f"no websocket URL for peer ref {peer.ref!r}; "
                f"known refs: {sorted(url_by_ref)}"
            )
        return await _dial(url, cid, peer)

    return connect


async def _dial(url: str, cid: str, peer: RpcClientPeer) -> _WsAdapter:
    from websockets.asyncio.client import connect as ws_connect

    sep = "&" if "?" in url else "?"
    ws = await ws_connect(f"{url}{sep}clientId={cid}:{peer.ref}", max_size=64 * 1024 * 1024)
    return _WsAdapter(ws)
