"""WebSocket transport — the real-network counterpart of the test channels.

Re-expression of src/Stl.Rpc/WebSockets/WebSocketChannel.cs:11-120 +
Rpc.Server/RpcWebSocketServer.cs:32-64 + Clients/RpcWebSocketClient.cs:
messages ride binary frames (wire-serialized RpcMessage); the client sends a
stable ``clientId`` query parameter so a re-dialed connection lands on the
SAME server peer — which is what makes reconnect dedup/re-send work across
physical connections (SessionBoundRpcConnection analogue).
"""
from __future__ import annotations

import asyncio
import logging
import secrets
import urllib.parse
from typing import Optional

from ..utils.serialization import dumps, loads
from .hub import RpcHub
from .message import RpcMessage
from .peer import RpcClientPeer

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["RpcWebSocketServer", "websocket_client_connector", "websocket_multi_connector"]

RPC_PATH = "/rpc/ws"


class _WsAdapter:
    """Adapts a websockets connection to the peer's reader/writer protocol."""

    class _Reader:
        def __init__(self, ws):
            self._ws = ws

        async def receive(self) -> RpcMessage:
            try:
                frame = await self._ws.recv()
            except Exception as e:  # noqa: BLE001 — closed/aborted
                raise ConnectionError(str(e)) from e
            return loads(frame if isinstance(frame, bytes) else frame.encode())

    class _Writer:
        def __init__(self, ws):
            self._ws = ws

        async def send(self, message: RpcMessage) -> None:
            try:
                await self._ws.send(dumps(message))
            except Exception as e:  # noqa: BLE001
                raise ConnectionError(str(e)) from e

    def __init__(self, ws):
        self._ws = ws
        self.reader = _WsAdapter._Reader(ws)
        self.writer = _WsAdapter._Writer(ws)

    def close(self, error: Optional[BaseException] = None) -> None:
        asyncio.ensure_future(self._ws.close())


class RpcWebSocketServer:
    """Hosts an RpcHub over websockets (≈ RpcWebSocketServer + route map)."""

    def __init__(self, hub: RpcHub, host: str = "127.0.0.1", port: int = 0):
        self.hub = hub
        self.host = host
        self.port = port
        self._server = None

    async def start(self) -> "RpcWebSocketServer":
        from websockets.asyncio.server import serve

        self._server = await serve(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.debug("rpc websocket server on %s:%d", self.host, self.port)
        return self

    @property
    def url(self) -> str:
        return f"ws://{self.host}:{self.port}{RPC_PATH}"

    async def _handle(self, ws) -> None:
        path = ws.request.path if ws.request is not None else RPC_PATH
        query = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
        client_id = (query.get("clientId") or [f"anon-{secrets.token_hex(4)}"])[0]
        peer = self.hub.server_peer(f"ws:{client_id}")
        peer.connect(_WsAdapter(ws))
        # hold the handler open until the socket dies (websockets closes on return)
        try:
            await ws.wait_closed()
        except Exception:  # noqa: BLE001
            pass

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None


def websocket_client_connector(url: str, client_id: Optional[str] = None):
    """Client connector factory: ``hub.client_connector = websocket_client_connector(url)``.

    The generated clientId is stable per connector, so reconnects resume the
    same server peer (reconnect dedup).
    """
    cid = client_id or f"c-{secrets.token_hex(8)}"

    async def connect(peer: RpcClientPeer):
        return await _dial(url, cid, peer)

    return connect


def websocket_multi_connector(url_by_ref, client_id: Optional[str] = None):
    """Connector for a server pool: resolve the peer ref to its host URL
    (≈ ``RpcWebSocketClient.Options.HostUrlResolver`` where the peer ref IS
    the host url, samples/MultiServerRpc/Program.cs:52-55). ``url_by_ref``
    maps peer refs to websocket URLs; together with a ``call_router`` over
    the same refs this gives per-call sharding across servers.
    """
    cid = client_id or f"c-{secrets.token_hex(8)}"

    async def connect(peer: RpcClientPeer):
        # an unknown ref is a config error, not a transient network failure —
        # fail loudly instead of entering the reconnect/backoff loop
        url = url_by_ref.get(peer.ref)
        if url is None:
            raise LookupError(
                f"no websocket URL for peer ref {peer.ref!r}; "
                f"known refs: {sorted(url_by_ref)}"
            )
        return await _dial(url, cid, peer)

    return connect


async def _dial(url: str, cid: str, peer: RpcClientPeer) -> _WsAdapter:
    from websockets.asyncio.client import connect as ws_connect

    sep = "&" if "?" in url else "?"
    ws = await ws_connect(f"{url}{sep}clientId={cid}:{peer.ref}", max_size=64 * 1024 * 1024)
    return _WsAdapter(ws)
