"""HTTP/REST surface for compute services + typed REST client.

The analogue of the reference's REST story: Stl.Fusion.Server's MVC
controllers/endpoints expose compute services over plain HTTP, and
Stl.RestEase generates typed clients for them (src/Stl.RestEase/,
Fusion.Server/Endpoints/ — SURVEY §2.7, §2.8). Protocol:

    GET  /fusion/{service}/{method}?args=<json-array>   — reads
    POST /fusion/{service}/{method}   (json-array body) — commands/writes

Responses are ``{"ok": value}`` or ``{"error": {"type", "message"}}``.
Unlike the RPC/websocket channel this surface carries NO invalidation
subscription — it is the integration path for plain HTTP consumers
(curl, dashboards, other stacks), exactly the niche REST fills in the
reference. Implemented on asyncio streams (stdlib only).
"""
from __future__ import annotations

import asyncio
import json
import logging
import urllib.parse
from typing import Any, Optional

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["FusionHttpServer", "RestClient", "RestError"]

PATH_PREFIX = "/fusion/"


class RestError(Exception):
    def __init__(self, type_name: str, message: str):
        super().__init__(f"{type_name}: {message}")
        self.type_name = type_name


class FusionHttpServer:
    """Serves registered services of an RpcHub (or any object registry with
    ``service_registry.invoke``) over HTTP."""

    def __init__(self, rpc_hub, host: str = "127.0.0.1", port: int = 0):
        self.rpc_hub = rpc_hub
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> "FusionHttpServer":
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            request_line = (await reader.readline()).decode("latin1").strip()
            if not request_line:
                return
            method, target, _version = request_line.split(" ", 2)
            content_length = 0
            while True:
                line = (await reader.readline()).decode("latin1").strip()
                if not line:
                    break
                name, _, value = line.partition(":")
                if name.lower() == "content-length":
                    content_length = int(value.strip())
            body = await reader.readexactly(content_length) if content_length else b""
            status, payload = await self._dispatch(method, target, body)
            try:
                data = json.dumps(payload).encode()
            except (TypeError, ValueError) as e:
                # the service returned something JSON can't carry — a real
                # error response beats a silently-dropped connection
                status = "500 Internal Server Error"
                data = json.dumps(
                    {"error": {"type": "NotSerializable", "message": str(e)}}
                ).encode()
            writer.write(
                f"HTTP/1.1 {status}\r\nContent-Type: application/json\r\n"
                f"Content-Length: {len(data)}\r\nConnection: close\r\n\r\n".encode() + data
            )
            await writer.drain()
        except Exception:  # noqa: BLE001 — one bad request never kills the server
            log.exception("http gateway request failed")
        finally:
            writer.close()

    async def _dispatch(self, http_method: str, target: str, body: bytes):
        parsed = urllib.parse.urlsplit(target)
        if not parsed.path.startswith(PATH_PREFIX):
            return "404 Not Found", {"error": {"type": "NotFound", "message": parsed.path}}
        parts = parsed.path[len(PATH_PREFIX):].split("/")
        if len(parts) != 2:
            return "404 Not Found", {"error": {"type": "NotFound", "message": parsed.path}}
        service, method = parts
        try:
            if http_method == "GET":
                query = urllib.parse.parse_qs(parsed.query)
                raw_args = query.get("args", ["[]"])[0]
            elif http_method == "POST":
                raw_args = body.decode() or "[]"
            else:
                return "405 Method Not Allowed", {
                    "error": {"type": "MethodNotAllowed", "message": http_method}
                }
            try:
                args = json.loads(raw_args)
                if not isinstance(args, list):
                    raise ValueError("args must be a JSON array")
            except ValueError as e:
                return "400 Bad Request", {"error": {"type": "BadRequest", "message": str(e)}}
            result = await self.rpc_hub.service_registry.invoke(service, method, args)
            return "200 OK", {"ok": result}
        except LookupError as e:
            return "404 Not Found", {"error": {"type": type(e).__name__, "message": str(e)}}
        except Exception as e:  # noqa: BLE001 — service errors travel as payloads
            return "500 Internal Server Error", {
                "error": {"type": type(e).__name__, "message": str(e)}
            }


class _RestMethod:
    def __init__(self, client: "RestClient", method: str):
        self._client = client
        self._method = method

    async def __call__(self, *args):
        return await self._client.call(self._method, list(args))

    async def post(self, *args):
        return await self._client.call(self._method, list(args), http_method="POST")


class RestClient:
    """Typed REST client for a served compute service (≈ Stl.RestEase
    clients): attribute access → GET call; ``.post`` for commands."""

    def __init__(self, base_url: str, service: str):
        parsed = urllib.parse.urlsplit(base_url)
        self.host = parsed.hostname
        self.port = parsed.port or 80
        self.service = service

    def __getattr__(self, method: str) -> _RestMethod:
        if method.startswith("_"):
            raise AttributeError(method)
        return _RestMethod(self, method)

    async def call(self, method: str, args: list, http_method: str = "GET") -> Any:
        path = f"{PATH_PREFIX}{self.service}/{method}"
        body = b""
        if http_method == "GET":
            path += "?args=" + urllib.parse.quote(json.dumps(args))
        else:
            body = json.dumps(args).encode()
        try:
            reader, writer = await asyncio.open_connection(self.host, self.port)
            try:
                writer.write(
                    f"{http_method} {path} HTTP/1.1\r\nHost: {self.host}\r\n"
                    f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n".encode() + body
                )
                await writer.drain()
                raw = await reader.read()
            finally:
                writer.close()
        except OSError as e:
            # refused/reset/aborted — one uniform error type for callers
            raise RestError("BadResponse", f"connection failed: {e}") from None
        headers, _, payload = raw.partition(b"\r\n\r\n")
        status_line = headers.split(b"\r\n", 1)[0].decode("latin1", "replace")
        if not payload:
            # server closed without a body (request never parsed, handler
            # crashed before write) — surface as RestError, not a JSON error
            raise RestError("BadResponse", f"empty response ({status_line or 'no status'})")
        try:
            response = json.loads(payload.decode())
        except ValueError as e:
            raise RestError("BadResponse", f"{status_line}: {e}") from None
        if "error" in response:
            raise RestError(response["error"]["type"], response["error"]["message"])
        return response["ok"]
