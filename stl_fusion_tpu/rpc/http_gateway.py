"""HTTP/REST surface for compute services + typed REST client.

The analogue of the reference's REST story: Stl.Fusion.Server's MVC
controllers/endpoints expose compute services over plain HTTP, and
Stl.RestEase generates typed clients for them (src/Stl.RestEase/,
Fusion.Server/Endpoints/ — SURVEY §2.7, §2.8). Protocol:

    GET  /fusion/{service}/{method}?args=<json-array>   — reads
    POST /fusion/{service}/{method}   (json-array body) — commands/writes

Responses are ``{"ok": value}`` or ``{"error": {"type", "message"}}``.
Unlike the RPC/websocket channel this surface carries NO invalidation
subscription — it is the integration path for plain HTTP consumers
(curl, dashboards, other stacks), exactly the niche REST fills in the
reference. Implemented on asyncio streams (stdlib only).

Arguments and results travel in the wire-type encoding
(utils/serialization: plain JSON for plain values, ``{"$t": ...}`` for
registered types), so typed values — Sessions included — round-trip.
With a :class:`HttpSessionMiddleware` attached the gateway issues/resolves
a cookie-based Session per browser and substitutes it for the
default-session placeholder in call arguments
(≈ Fusion.Server/Middlewares/SessionMiddleware.cs +
DefaultSessionReplacerRpcMiddleware.cs).
"""
from __future__ import annotations

import asyncio
import json
import logging
import urllib.parse
from typing import Any, Dict, Optional, Tuple

from ..utils.serialization import decode, encode

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["FusionHttpServer", "HttpSessionMiddleware", "RestClient", "RestError"]

PATH_PREFIX = "/fusion/"


def _normalize_ip(ip: str) -> str:
    """Canonical peer-address form for allowlist membership: a dual-stack
    listener reports the loopback sidecar as ``::ffff:127.0.0.1``, which
    must match a ``127.0.0.1`` allowlist entry."""
    import ipaddress

    try:
        addr = ipaddress.ip_address(ip)
    except ValueError:
        return ip
    mapped = getattr(addr, "ipv4_mapped", None)
    return str(mapped if mapped is not None else addr)


class HttpSessionMiddleware:
    """Cookie-based Session issue/resolve for the HTTP gateway
    (≈ SessionMiddleware.cs): a request without a valid session cookie gets
    a fresh session issued via ``Set-Cookie``; default-placeholder Session
    arguments are replaced with the cookie session before dispatch."""

    def __init__(self, cookie_name: str = "FusionSession", tenant_id: str = ""):
        from ..ext.session import Session

        self.cookie_name = cookie_name
        self.tenant_id = tenant_id
        self._session_cls = Session

    def resolve(self, cookie_header: str):
        """(session, set_cookie_value_or_None) for a request's Cookie header."""
        for part in cookie_header.split(";"):
            name, _, value = part.strip().partition("=")
            if name == self.cookie_name and value:
                try:
                    session = self._session_cls(urllib.parse.unquote(value))
                    if not session.is_default:
                        return session, None
                    # a crafted '~' cookie must not smuggle the shared
                    # placeholder identity past issuance
                except ValueError:
                    pass
                break  # malformed or placeholder id: issue a fresh one
        session = self._session_cls.new(self.tenant_id)
        cookie = (
            f"{self.cookie_name}={urllib.parse.quote(session.id, safe='')};"
            f" Path=/; HttpOnly; SameSite=Lax"
        )
        return session, cookie

    def replace_default_sessions(self, args: list, session) -> list:
        from ..ext.session import replace_default_sessions

        return replace_default_sessions(args, session, self._session_cls)


class RestError(Exception):
    def __init__(self, type_name: str, message: str):
        super().__init__(f"{type_name}: {message}")
        self.type_name = type_name


async def read_request_head(
    reader: asyncio.StreamReader,
) -> Tuple[Optional[str], Optional[str], Dict[str, str]]:
    """Parse one HTTP request line + header block into
    ``(method, target, lowercase-name headers)`` — the one parser every
    asyncio-streams server in the tree rides (this gateway and the edge
    tier's ``EdgeHttpServer``), so header handling never drifts between
    them. Returns ``(None, None, {})`` on an empty (closed) stream."""
    request_line = (await reader.readline()).decode("latin1").strip()
    if not request_line:
        return None, None, {}
    method, target, _version = request_line.split(" ", 2)
    headers: Dict[str, str] = {}
    while True:
        line = (await reader.readline()).decode("latin1").strip()
        if not line:
            break
        name, _, value = line.partition(":")
        headers[name.lower()] = value.strip()
    return method, target, headers


async def write_metrics_response(writer: asyncio.StreamWriter) -> None:
    """One Prometheus-exposition HTTP response off the process registry —
    shared by every server that mounts a ``/metrics`` route (this gateway
    and the edge tier's ``EdgeHttpServer``), so the exposition headers
    never drift between them."""
    from ..diagnostics.metrics import global_metrics

    raw = global_metrics().render_prometheus().encode()
    writer.write(
        "HTTP/1.1 200 OK\r\n"
        "Content-Type: text/plain; version=0.0.4; charset=utf-8\r\n"
        f"Content-Length: {len(raw)}\r\nConnection: close\r\n\r\n".encode()
        + raw
    )
    await writer.drain()


class FusionHttpServer:
    """Serves registered services of an RpcHub (or any object registry with
    ``service_registry.invoke``) over HTTP."""

    def __init__(
        self,
        rpc_hub,
        host: str = "127.0.0.1",
        port: int = 0,
        session_middleware: Optional[HttpSessionMiddleware] = None,
    ):
        self.rpc_hub = rpc_hub
        self.host = host
        self.port = port
        self.session_middleware = session_middleware
        #: optional ext.server_auth.ServerAuthHelper: when set (requires
        #: session_middleware), every request reconciles the transport's
        #: principal (trusted proxy headers) with the fusion session's user
        #: (≈ ServerAuthHelper.UpdateAuthState called from the host filter)
        self.auth_helper = None
        #: peer IPs allowed to supply ``x-auth-request-*`` principal headers.
        #: Without this gate any client that can reach the port directly
        #: could impersonate any user (ADVICE r2). Default = loopback — the
        #: sidecar reverse-proxy deployment shape; widen explicitly for a
        #: proxy on another host, or use :attr:`proxy_shared_secret`.
        self.trusted_proxies: frozenset = frozenset({"127.0.0.1", "::1"})
        #: when set, proxy trust is decided by this shared secret instead:
        #: the proxy must stamp it in ``x-auth-request-secret`` (constant-
        #: time compared); requests without it are treated as anonymous
        self.proxy_shared_secret: Optional[str] = None
        #: path → (content_type, body): static pages served next to the
        #: JSON API (the sample-UI host path, ≈ MapBlazorHub + index.html)
        self.static_routes: dict = {}
        #: observability routes (ISSUE 3 + 4): GET /metrics — Prometheus
        #: text exposition of the process registry; GET /trace — recent
        #: tracing spans (+ the attached monitor's report, waves and
        #: delivery histogram included, when :attr:`monitor` is set;
        #: ``?section=waves|fanout|delivery|recorder|audit`` bounds the
        #: payload to one report section); GET /explain?key= — the causal
        #: chain for a key (flight recorder + wave profiler + span join,
        #: diagnostics/explain.py). Served ONLY to
        #: peers :meth:`_is_trusted_proxy` accepts (default: loopback — the
        #: sidecar scraper shape; with :attr:`proxy_shared_secret` set the
        #: scraper must send it in ``x-auth-request-secret``): span tags
        #: carry command arguments and the report names internals, so a
        #: direct remote client gets 404, never the dump. Flip off to drop
        #: the routes entirely.
        self.serve_observability: bool = True
        #: optional diagnostics.FusionMonitor whose report() /trace embeds
        self.monitor = None
        #: optional diagnostics.MeshTelemetryAggregator (ISSUE 18): when
        #: set, ``GET /metrics?scope=mesh`` answers the MERGED fleet
        #: exposition (per-host ``host="h<N>"`` labels, SUM/MAX merge,
        #: stale marking) instead of the process-local registry, and
        #: ``GET /trace?cause=<id>`` marks missing hosts PARTIAL against
        #: the aggregator's membership
        self.mesh_telemetry = None
        #: cluster control-plane parts served by GET /shards (ISSUE 5):
        #: any mix of ClusterMember / ShardMapRouter / ClusterRebalancer
        #: (anything with ``snapshot()``), merged — same trust gate as the
        #: other observability routes (topology + per-peer traffic are
        #: operator data, not public data)
        self.cluster: tuple = ()
        self._server: Optional[asyncio.AbstractServer] = None

    def _is_trusted_proxy(self, headers: dict) -> bool:
        if self.proxy_shared_secret is not None:
            import hmac

            # bytes compare: compare_digest raises on non-ASCII str, which
            # would 500 the request instead of degrading to anonymous
            return hmac.compare_digest(
                headers.get("x-auth-request-secret", "").encode("utf-8", "replace"),
                self.proxy_shared_secret.encode("utf-8", "replace"),
            )
        return _normalize_ip(headers.get("_ip", "")) in self.trusted_proxies

    async def start(self) -> "FusionHttpServer":
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    @staticmethod
    async def _write_json(writer: asyncio.StreamWriter, status: str, payload) -> None:
        """One JSON response, non-JSON-able leaves repr'd (the observability
        routes ship diagnostic dicts, where a lossy repr beats a 500)."""
        raw = json.dumps(payload, default=repr).encode()
        writer.write(
            f"HTTP/1.1 {status}\r\nContent-Type: application/json\r\n"
            f"Content-Length: {len(raw)}\r\nConnection: close\r\n\r\n".encode()
            + raw
        )
        await writer.drain()

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            method, target, headers = await read_request_head(reader)
            if method is None:
                return
            content_length = int(headers.get("content-length", 0))
            cookie_header = headers.get("cookie", "")
            body = await reader.readexactly(content_length) if content_length else b""
            peer = writer.get_extra_info("peername")
            headers["_ip"] = peer[0] if peer else ""
            parsed_target = urllib.parse.urlsplit(target)
            path = parsed_target.path
            observability = (
                self.serve_observability
                and method == "GET"
                and path in ("/metrics", "/trace", "/explain", "/shards",
                             "/health", "/hotkeys")
                # same trust gate as principal headers: loopback (or the
                # shared scraper secret) only — a direct remote client must
                # not read spans/reports off a port it happens to reach
                and self._is_trusted_proxy(headers)
            )
            if observability and path == "/metrics":
                scope = urllib.parse.parse_qs(parsed_target.query).get(
                    "scope", [None]
                )[0]
                if scope == "mesh":
                    # fleet scrape (ISSUE 18): the merged exposition, or an
                    # honest 503 — answering scope=mesh with LOCAL data
                    # would silently misrepresent one host as the fleet
                    if self.mesh_telemetry is None:
                        await self._write_json(
                            writer,
                            "503 Service Unavailable",
                            {
                                "error": {
                                    "type": "NoMeshTelemetry",
                                    "message": (
                                        "no MeshTelemetryAggregator attached "
                                        "to this gateway"
                                    ),
                                }
                            },
                        )
                        return
                    raw = self.mesh_telemetry.render_mesh_prometheus().encode()
                    writer.write(
                        "HTTP/1.1 200 OK\r\n"
                        "Content-Type: text/plain; version=0.0.4; charset=utf-8\r\n"
                        f"Content-Length: {len(raw)}\r\nConnection: close\r\n\r\n".encode()
                        + raw
                    )
                    await writer.drain()
                    return
                await write_metrics_response(writer)
                return
            if observability and path == "/trace":
                from ..diagnostics.tracing import recent_spans

                query = urllib.parse.parse_qs(parsed_target.query)
                cause = query.get("cause", [None])[0]
                if cause:
                    # stitched cross-host wave timeline (ISSUE 18) — one
                    # clock-aligned view of one wave, keyed by its cause id
                    from ..diagnostics.mesh_telemetry import global_mesh_trace

                    expected = (
                        self.mesh_telemetry.known_hosts()
                        if self.mesh_telemetry is not None
                        else None
                    )
                    stitched = global_mesh_trace().stitch(
                        cause, expected_hosts=expected
                    )
                    if stitched is None:
                        await self._write_json(
                            writer,
                            "404 Not Found",
                            {
                                "error": {
                                    "type": "UnknownCause",
                                    "message": (
                                        f"no trace segments recorded for "
                                        f"cause {cause!r}"
                                    ),
                                }
                            },
                        )
                        return
                    await self._write_json(writer, "200 OK", {"trace": stitched})
                    return
                section = query.get("section", [None])[0]
                if section:
                    # payload bound (ISSUE 4 satellite): a scraper fetches
                    # ONE report section (waves|fanout|delivery|recorder|
                    # audit|...) instead of the whole embedded report + spans
                    if self.monitor is None:
                        # every section would 400 as "unknown" here — name
                        # the REAL problem (no monitor wired) instead
                        await self._write_json(
                            writer,
                            "503 Service Unavailable",
                            {
                                "error": {
                                    "type": "NoMonitor",
                                    "message": "no FusionMonitor attached to this gateway",
                                }
                            },
                        )
                        return
                    report = self.monitor.report()
                    if section not in report:
                        # a typo'd section served as {"<typo>": null} reads
                        # as "no data recorded" — reject loudly instead
                        await self._write_json(
                            writer,
                            "400 Bad Request",
                            {
                                "error": {
                                    "type": "BadRequest",
                                    "message": (
                                        f"unknown or empty section {section!r}; "
                                        f"available: {sorted(report)}"
                                    ),
                                }
                            },
                        )
                        return
                    payload: dict = {"report": {section: report.get(section)}}
                else:
                    payload = {
                        "spans": [s.to_dict() for s in recent_spans()[-256:]],
                    }
                    if self.monitor is not None:
                        payload["report"] = self.monitor.report()
                await self._write_json(writer, "200 OK", payload)
                return
            if observability and path == "/explain":
                from ..diagnostics.explain import explain_with_fallback

                query = urllib.parse.parse_qs(parsed_target.query)
                key = query.get("key", [None])[0]
                if not key:
                    await self._write_json(
                        writer,
                        "400 Bad Request",
                        {"error": {"type": "BadRequest", "message": "key= required"}},
                    )
                    return
                try:
                    hub = self.monitor.hub if self.monitor is not None else None
                    status_line, payload = "200 OK", explain_with_fallback(key, hub=hub)
                except Exception as e:  # noqa: BLE001 — the incident-diagnosis
                    # endpoint must answer with the failure, never with a
                    # dropped connection ($sys-d's _serve_explain contract)
                    log.exception("explain(%r) failed", key)
                    status_line = "500 Internal Server Error"
                    payload = {"error": {"type": type(e).__name__, "message": str(e)}}
                await self._write_json(writer, status_line, payload)
                return
            if observability and path == "/health":
                # machine-readable SLO verdict (ISSUE 19): mesh-scope when
                # an aggregator is attached (stale hosts surface as
                # degraded entries), local-scope otherwise. Always 200 —
                # the verdict IS the answer; transport success must not be
                # conflated with fleet health.
                from ..diagnostics.slo import global_slo_engine

                try:
                    if self.mesh_telemetry is not None:
                        payload = self.mesh_telemetry.mesh_health()
                    else:
                        payload = global_slo_engine().evaluate()
                except Exception as e:  # noqa: BLE001 — a judging fault is a
                    # degraded verdict, never a dropped connection
                    log.exception("/health evaluation failed")
                    from ..diagnostics.metrics import global_metrics

                    global_metrics().counter(
                        "fusion_health_endpoint_errors_total",
                        help="/health evaluations that raised and answered "
                             "a degraded verdict instead",
                    ).inc()
                    payload = {
                        "verdict": "degraded",
                        "scope": "local",
                        "error": {"type": type(e).__name__, "message": str(e)},
                    }
                await self._write_json(writer, "200 OK", payload)
                return
            if observability and path == "/hotkeys":
                # workload attribution (ISSUE 19): top-k heavy hitters per
                # domain, mesh-merged when an aggregator is attached
                from ..diagnostics.hotkeys import global_hotkeys

                query = urllib.parse.parse_qs(parsed_target.query)
                try:
                    n = max(1, min(int(query.get("n", ["5"])[0]), 64))
                except ValueError:
                    n = 5
                domain = query.get("domain", [None])[0]
                if self.mesh_telemetry is not None:
                    payload = self.mesh_telemetry.hotkeys_report(n)
                else:
                    payload = {
                        "scope": "local",
                        "domains": global_hotkeys().report(n),
                    }
                if domain is not None:
                    domains = payload.get("domains") or {}
                    if domain not in domains:
                        await self._write_json(
                            writer,
                            "404 Not Found",
                            {
                                "error": {
                                    "type": "UnknownDomain",
                                    "message": (
                                        f"no sketch for domain {domain!r}; "
                                        f"available: {sorted(domains)}"
                                    ),
                                }
                            },
                        )
                        return
                    payload["domains"] = {domain: domains[domain]}
                await self._write_json(writer, "200 OK", payload)
                return
            if observability and path == "/shards":
                merged: dict = {}
                for part in self.cluster:
                    try:
                        merged.update(part.snapshot())
                    except Exception as e:  # noqa: BLE001 — one bad part, not a 500
                        merged.setdefault("errors", []).append(repr(e))
                if not merged:
                    await self._write_json(
                        writer,
                        "503 Service Unavailable",
                        {
                            "error": {
                                "type": "NoCluster",
                                "message": "no cluster parts attached to this gateway",
                            }
                        },
                    )
                    return
                await self._write_json(writer, "200 OK", merged)
                return
            static = self.static_routes.get(path)
            if static is not None and method == "GET":
                ctype, content = static
                raw = content.encode() if isinstance(content, str) else content
                writer.write(
                    f"HTTP/1.1 200 OK\r\nContent-Type: {ctype}\r\n"
                    f"Content-Length: {len(raw)}\r\nConnection: close\r\n\r\n".encode()
                    + raw
                )
                await writer.drain()
                return
            status, payload, extra_headers = await self._dispatch(
                method, target, body, cookie_header, headers
            )
            try:
                data = json.dumps(payload).encode()
            except (TypeError, ValueError) as e:
                # the service returned something JSON can't carry — a real
                # error response beats a silently-dropped connection
                status = "500 Internal Server Error"
                data = json.dumps(
                    {"error": {"type": "NotSerializable", "message": str(e)}}
                ).encode()
            header_block = "".join(f"{k}: {v}\r\n" for k, v in extra_headers)
            writer.write(
                f"HTTP/1.1 {status}\r\nContent-Type: application/json\r\n"
                f"{header_block}"
                f"Content-Length: {len(data)}\r\nConnection: close\r\n\r\n".encode() + data
            )
            await writer.drain()
        except Exception:  # noqa: BLE001 — one bad request never kills the server
            log.exception("http gateway request failed")
        finally:
            writer.close()

    async def _dispatch(
        self,
        http_method: str,
        target: str,
        body: bytes,
        cookie_header: str = "",
        headers: Optional[dict] = None,
    ) -> Tuple[str, Any, list]:
        parsed = urllib.parse.urlsplit(target)
        not_found = ("404 Not Found", {"error": {"type": "NotFound", "message": parsed.path}}, [])
        if not parsed.path.startswith(PATH_PREFIX):
            return not_found
        parts = parsed.path[len(PATH_PREFIX):].split("/")
        if len(parts) != 2:
            return not_found
        service, method = parts
        extra_headers: list = []
        try:
            if http_method == "GET":
                query = urllib.parse.parse_qs(parsed.query)
                raw_args = query.get("args", ["[]"])[0]
            elif http_method == "POST":
                raw_args = body.decode() or "[]"
            else:
                return "405 Method Not Allowed", {
                    "error": {"type": "MethodNotAllowed", "message": http_method}
                }, []
            try:
                args = json.loads(raw_args)
                if not isinstance(args, list):
                    raise ValueError("args must be a JSON array")
                args = [decode(a) for a in args]  # wire-typed args round-trip
            except (ValueError, TypeError, KeyError) as e:
                # TypeError: unknown "$t" wire tag; KeyError: a known tag
                # missing its payload fields — still the CLIENT's bad
                # input, not a server fault
                return "400 Bad Request", {
                    "error": {"type": "BadRequest", "message": str(e)}
                }, []
            mw = self.session_middleware
            if mw is not None:
                session, set_cookie = mw.resolve(cookie_header)
                if set_cookie is not None:
                    extra_headers.append(("Set-Cookie", set_cookie))
                args = mw.replace_default_sessions(args, session)
                if self.auth_helper is not None:
                    # ≈ ServerAuthHelper.UpdateAuthState per request: sync
                    # the transport principal into the fusion session.
                    # Principal headers are honored ONLY from a trusted
                    # proxy peer — a direct client's copies are ignored, so
                    # impersonation requires owning the proxy, not just
                    # reaching the port. Untrusted ≠ anonymous: an untrusted
                    # peer's request must not sign an existing session OUT
                    # either (that would let any direct client revoke a
                    # user's session everywhere via the replicated op log),
                    # so reconciliation is skipped and only session setup +
                    # presence run
                    from ..ext.server_auth import principal_from_headers

                    h = headers or {}
                    trusted = self._is_trusted_proxy(h)
                    await self.auth_helper.update_auth_state(
                        session,
                        principal_from_headers(h) if trusted else None,
                        ip_address=h.get("_ip", ""),
                        user_agent=h.get("user-agent", ""),
                        principal_authoritative=trusted,
                    )
            result = await self.rpc_hub.service_registry.invoke(service, method, args)
            return "200 OK", {"ok": encode(result)}, extra_headers
        except LookupError as e:
            return "404 Not Found", {
                "error": {"type": type(e).__name__, "message": str(e)}
            }, extra_headers
        except Exception as e:  # noqa: BLE001 — service errors travel as payloads
            return "500 Internal Server Error", {
                "error": {"type": type(e).__name__, "message": str(e)}
            }, extra_headers


class _RestMethod:
    def __init__(self, client: "RestClient", method: str):
        self._client = client
        self._method = method

    async def __call__(self, *args):
        return await self._client.call(self._method, list(args))

    async def post(self, *args):
        return await self._client.call(self._method, list(args), http_method="POST")


class RestClient:
    """Typed REST client for a served compute service (≈ Stl.RestEase
    clients): attribute access → GET call; ``.post`` for commands. Args and
    results use the wire-type encoding; a cookie jar carries the gateway's
    session cookie across calls (≈ a browser talking to SessionMiddleware)."""

    def __init__(self, base_url: str, service: str, headers: Optional[Dict[str, str]] = None):
        parsed = urllib.parse.urlsplit(base_url)
        self.host = parsed.hostname
        self.port = parsed.port or 80
        self.service = service
        self.cookies: Dict[str, str] = {}
        #: extra headers on every request (e.g. trusted-proxy auth headers)
        self.headers: Dict[str, str] = dict(headers or {})

    def __getattr__(self, method: str) -> _RestMethod:
        if method.startswith("_"):
            raise AttributeError(method)
        return _RestMethod(self, method)

    async def call(self, method: str, args: list, http_method: str = "GET") -> Any:
        path = f"{PATH_PREFIX}{self.service}/{method}"
        wire_args = json.dumps([encode(a) for a in args])
        body = b""
        if http_method == "GET":
            path += "?args=" + urllib.parse.quote(wire_args)
        else:
            body = wire_args.encode()
        cookie_line = (
            "Cookie: " + "; ".join(f"{k}={v}" for k, v in self.cookies.items()) + "\r\n"
            if self.cookies
            else ""
        )
        for k, v in self.headers.items():
            # CR/LF in a header would splice extra headers (or a whole
            # pipelined request) into the buffer below — reject loudly
            if "\r" in k or "\n" in k or "\r" in v or "\n" in v:
                raise ValueError(f"illegal CR/LF in header {k!r}")
        cookie_line += "".join(f"{k}: {v}\r\n" for k, v in self.headers.items())
        try:
            reader, writer = await asyncio.open_connection(self.host, self.port)
            try:
                writer.write(
                    f"{http_method} {path} HTTP/1.1\r\nHost: {self.host}\r\n"
                    f"{cookie_line}"
                    f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n".encode() + body
                )
                await writer.drain()
                raw = await reader.read()
            finally:
                writer.close()
        except OSError as e:
            # refused/reset/aborted — one uniform error type for callers
            raise RestError("BadResponse", f"connection failed: {e}") from None
        headers, _, payload = raw.partition(b"\r\n\r\n")
        status_line = headers.split(b"\r\n", 1)[0].decode("latin1", "replace")
        for line in headers.split(b"\r\n")[1:]:
            name, _, value = line.decode("latin1", "replace").partition(":")
            if name.lower() == "set-cookie":
                cookie = value.strip().split(";", 1)[0]
                cname, _, cvalue = cookie.partition("=")
                if cname:
                    self.cookies[cname] = cvalue
        if not payload:
            # server closed without a body (request never parsed, handler
            # crashed before write) — surface as RestError, not a JSON error
            raise RestError("BadResponse", f"empty response ({status_line or 'no status'})")
        try:
            response = json.loads(payload.decode())
        except ValueError as e:
            raise RestError("BadResponse", f"{status_line}: {e}") from None
        if "error" in response:
            raise RestError(response["error"]["type"], response["error"]["message"])
        return decode(response["ok"])
