"""Bundled RPC middlewares for the composable peer pipeline.

Re-expression of src/Stl.Rpc/Infrastructure/RpcInboundMiddleware.cs /
RpcOutboundMiddleware.cs (the chains live on ``RpcHub.inbound_middlewares``
/ ``outbound_middlewares``; each middleware is ``async (peer, message,
nxt)``) plus two concrete members of the family:

- :func:`call_logging_middleware` ≈ the call-activity/logging middleware
  (RpcInboundCallActivityMiddleware.cs + ``CallLogLevel``, RpcPeer.cs:26);
- :func:`default_session_replacer_middleware` ≈
  Fusion.Server/Rpc/DefaultSessionReplacerRpcMiddleware.cs — inbound calls
  carrying the default-session placeholder get the CONNECTION's bound
  session substituted before dispatch, so clients never learn or send real
  session ids.

Adding cross-cutting behavior (auth, tracing, rate limits) is appending to
the hub lists — peers are not edited (VERDICT r1 missing #6).
"""
from __future__ import annotations

import logging
from typing import Callable, Optional

from ..ext.session import Session, SessionResolver, replace_default_sessions
from ..utils.serialization import dumps, loads
from .message import COMPUTE_SYSTEM_SERVICE, SYSTEM_SERVICE, TABLE_SYSTEM_SERVICE, RpcMessage
from .peer import RpcPeer

log = logging.getLogger("stl_fusion_tpu")

__all__ = [
    "call_logging_middleware",
    "chaos_middleware",
    "default_session_replacer_middleware",
    "bind_peer_session",
    "peer_session",
]


def chaos_middleware(policy, events=None) -> Callable:
    """Fault-injection stage (resilience/chaos.py): drop / duplicate /
    delay sampled per message from a seeded policy — the production-shaped
    chaos injection point (append to ``inbound_middlewares`` /
    ``outbound_middlewares`` like any other stage)."""
    from ..resilience.chaos import chaos_middleware as _impl

    return _impl(policy, events)


def call_logging_middleware(logger=None, level: int = logging.DEBUG) -> Callable:
    """Log every message passing the chain (attach to inbound and/or
    outbound)."""
    logger = logger or log

    async def middleware(peer: RpcPeer, message: RpcMessage, nxt):
        logger.log(
            level,
            "rpc %s: %s.%s #%d (%d bytes)",
            peer.ref,
            message.service,
            message.method,
            message.call_id,
            len(message.argument_data or b""),
        )
        await nxt(message)

    return middleware


def bind_peer_session(peer: RpcPeer, session: Session) -> None:
    """Bind a real session to a (server) peer connection
    (≈ SessionBoundRpcConnectionFactory)."""
    peer.bound_session = session  # type: ignore[attr-defined]


def peer_session(peer: RpcPeer) -> Session:
    """The peer's bound session, issued on first use."""
    session = getattr(peer, "bound_session", None)
    if session is None:
        session = Session.new()
        bind_peer_session(peer, session)
    return session


def default_session_replacer_middleware(
    resolver_for_peer: Optional[Callable[[RpcPeer], SessionResolver]] = None,
) -> Callable:
    """Inbound middleware replacing default-placeholder Session arguments
    with the connection's bound session (issued per peer on first use
    unless ``resolver_for_peer`` supplies one)."""

    async def middleware(peer: RpcPeer, message: RpcMessage, nxt):
        if message.service in (SYSTEM_SERVICE, COMPUTE_SYSTEM_SERVICE, TABLE_SYSTEM_SERVICE):
            return await nxt(message)
        # byte-level pre-check: the placeholder serializes as the literal
        # "~" — most calls carry no Session at all and must not pay a full
        # deserialize here on top of dispatch's own (false positives just
        # fall through to the real check below)
        if b'"~"' not in (message.argument_data or b""):
            return await nxt(message)
        try:
            args = loads(message.argument_data)
        except Exception:  # noqa: BLE001 — not arg-shaped; let dispatch decide
            return await nxt(message)
        if isinstance(args, list) and any(
            isinstance(a, Session) and a.is_default for a in args
        ):
            if resolver_for_peer is not None:
                real = resolver_for_peer(peer).session
            else:
                real = peer_session(peer)
            args = replace_default_sessions(args, real)
            message = RpcMessage(
                message.call_type_id,
                message.call_id,
                message.service,
                message.method,
                dumps(args),
                message.headers,
            )
        await nxt(message)

    return middleware
