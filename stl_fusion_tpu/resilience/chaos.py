"""ChaosPolicy — seeded, deterministic fault injection for the RPC fabric.

Two injection points, one policy object:

- **Twisted test channels** (``rpc/testing.py``): :func:`wrap_chaos_pair`
  wraps a ``ChannelPair`` endpoint so every ``send`` samples the policy.
  A *drop* models real packet loss on a reliable transport: the frame is
  lost AND the link is declared dead (pair closed + ``ChannelClosedError``
  raised at the sender) — exactly the unacked-frame-kills-the-TCP-session
  shape the reconnect/re-send machinery is built to absorb. Duplicates,
  delays, and reordering are delivered non-fatally (dedup + retry logic
  must absorb them on a live link).
- **Real middleware stage** (``rpc/middleware.py`` re-exports
  :func:`chaos_middleware`): drops are silent swallows, duplicates call the
  chain twice, delays sleep — the production-shaped injection for staging
  hubs (no test transport required).

Timed faults (partition windows, peer-kill schedules) live on the policy
too; :class:`ChaosScenarioRunner` replays them against a test transport on
a wall clock, so a named scenario is a complete, reproducible fault script.
All randomness flows from one ``random.Random(seed)`` — same seed, same
fault sequence.
"""
from __future__ import annotations

import asyncio
import logging
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.async_utils import ChannelClosedError
from .events import ResilienceEvents, global_events

log = logging.getLogger("stl_fusion_tpu")

__all__ = [
    "ChaosActions",
    "ChaosPolicy",
    "ChaosScenarioRunner",
    "SCENARIOS",
    "chaos_middleware",
    "wrap_chaos_pair",
]


@dataclass(frozen=True)
class ChaosActions:
    """One message's sampled fate."""

    drop: bool = False
    duplicate: bool = False
    delay_s: float = 0.0


@dataclass
class ChaosPolicy:
    """Deterministic per-message fault probabilities + timed fault script.

    ``partitions`` are ``(at_s, duration_s)`` offsets from scenario start;
    ``peer_kills`` are ``(at_s, peer_ref)``. Both are enacted by
    :class:`ChaosScenarioRunner`; the per-message probabilities apply
    wherever the policy is plugged in (channel wrapper or middleware).
    ``wave_faults`` names offsets at which the runner injects a device-wave
    fault into an attached :class:`~stl_fusion_tpu.resilience.WaveWatchdog`.
    """

    seed: int = 0
    drop: float = 0.0
    duplicate: float = 0.0
    delay: float = 0.0
    delay_range_s: Tuple[float, float] = (0.001, 0.01)
    reorder_window: int = 0  # ≥2 buffers that many frames and shuffles
    reorder_flush_s: float = 0.02  # partial buffers flush after this long
    partitions: List[Tuple[float, float]] = field(default_factory=list)
    peer_kills: List[Tuple[float, str]] = field(default_factory=list)
    wave_faults: List[float] = field(default_factory=list)

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)
        self.messages_seen = 0
        self.dropped = 0
        self.duplicated = 0
        self.delayed = 0
        self.reordered = 0

    def sample(self) -> ChaosActions:
        """One draw per message — the policy's single randomness stream."""
        rng = self._rng
        self.messages_seen += 1
        if self.drop and rng.random() < self.drop:
            self.dropped += 1
            return ChaosActions(drop=True)
        duplicate = bool(self.duplicate and rng.random() < self.duplicate)
        delay_s = 0.0
        if self.delay and rng.random() < self.delay:
            delay_s = rng.uniform(*self.delay_range_s)
            self.delayed += 1
        if duplicate:
            self.duplicated += 1
        return ChaosActions(duplicate=duplicate, delay_s=delay_s)

    def shuffle(self, items: list) -> None:
        self._rng.shuffle(items)


class _ChaosWriter:
    """Chaos-applying writer half of a wrapped endpoint. Reordering buffers
    up to ``reorder_window`` frames and releases them shuffled; a flush
    timer bounds how long a partial buffer can hold a frame (a held-forever
    invalidation would read as a lost one)."""

    def __init__(self, wrapper: "_ChaosPair", policy: ChaosPolicy, events: ResilienceEvents):
        self._wrapper = wrapper
        self._policy = policy
        self._events = events
        self._buffer: list = []
        self._flush_task: Optional[asyncio.Task] = None

    async def send(self, message) -> None:
        act = self._policy.sample()
        if act.drop:
            # frame lost ⇒ link dead (the reliable-transport contract: loss
            # surfaces as connection death, never as a silent gap)
            self._events.record("chaos_drop")
            err = ChannelClosedError("chaos: frame dropped, link torn down")
            self._wrapper.close(err)
            raise err
        if act.delay_s > 0:
            self._wrapper.spawn(self._deliver_later(message, act.delay_s))
        else:
            await self._enqueue(message)
        if act.duplicate:
            await self._enqueue(message)

    async def _deliver_later(self, message, delay_s: float) -> None:
        await asyncio.sleep(delay_s)
        await self._enqueue(message)

    async def _enqueue(self, message) -> None:
        if self._policy.reorder_window >= 2:
            self._buffer.append(message)
            if len(self._buffer) >= self._policy.reorder_window:
                await self._flush()
            elif self._flush_task is None or self._flush_task.done():
                self._flush_task = self._wrapper.spawn(self._flush_after())
        else:
            await self._deliver(message)

    async def _flush_after(self) -> None:
        await asyncio.sleep(self._policy.reorder_flush_s)
        await self._flush()

    async def _flush(self) -> None:
        batch, self._buffer = self._buffer, []
        if len(batch) > 1:
            self._policy.shuffle(batch)
            self._policy.reordered += len(batch)
        for m in batch:
            await self._deliver(m)

    async def _deliver(self, message) -> None:
        try:
            await self._wrapper._pair.writer.send(message)
        except ChannelClosedError:
            pass  # link already died; the frame is lost with it — standard recovery


class _ChaosPair:
    """ChannelPair wrapper: chaos on the write side, passthrough reads."""

    def __init__(self, pair, policy: ChaosPolicy, events: ResilienceEvents):
        self._pair = pair
        self.reader = pair.reader
        self.writer = _ChaosWriter(self, policy, events)
        self._tasks: set = set()

    def spawn(self, coro) -> asyncio.Task:
        task = asyncio.get_event_loop().create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    def close(self, error: Optional[BaseException] = None) -> None:
        for t in list(self._tasks):
            t.cancel()
        self._pair.close(error)


def wrap_chaos_pair(pair, policy: ChaosPolicy, events: Optional[ResilienceEvents] = None):
    """Wrap one endpoint of a twisted channel pair with chaos on sends."""
    return _ChaosPair(pair, policy, events if events is not None else global_events())


def chaos_middleware(policy: ChaosPolicy, events: Optional[ResilienceEvents] = None):
    """The production-shaped injection point: a middleware stage for
    ``RpcHub.inbound_middlewares`` / ``outbound_middlewares``. Unlike the
    channel wrapper, a middleware drop is a SILENT swallow (the message
    evaporates between transport and dispatch) — it exercises the layers
    above against loss without killing the link, the staging-hub shape."""
    ev = events if events is not None else global_events()

    async def middleware(peer, message, nxt):
        act = policy.sample()
        if act.drop:
            ev.record("chaos_drop", f"{message.service}.{message.method}")
            return
        if act.delay_s > 0:
            await asyncio.sleep(act.delay_s)
        await nxt(message)
        if act.duplicate:
            await nxt(message)

    return middleware


class ChaosScenarioRunner:
    """Replays a policy's timed fault script against a live test transport.

    ``await run()`` drives the whole script on the wall clock: partitions
    (block reconnects + drop the link, then unblock), peer kills (drop the
    link, auto-reconnect), and wave-fault injections into an attached
    watchdog. Message-level chaos is already live the moment the policy is
    installed on the transport — the runner only owns the timed events.
    """

    def __init__(self, transport, policy: ChaosPolicy, peer_ref: str = "default",
                 watchdog=None, events: Optional[ResilienceEvents] = None):
        self.transport = transport
        self.policy = policy
        self.peer_ref = peer_ref
        self.watchdog = watchdog
        self.events = events if events is not None else global_events()

    async def run(self) -> None:
        script = (
            [(at, "partition", dur) for at, dur in self.policy.partitions]
            + [(at, "kill", ref) for at, ref in self.policy.peer_kills]
            + [(at, "wave_fault", None) for at in self.policy.wave_faults]
        )
        script.sort(key=lambda e: e[0])
        loop = asyncio.get_event_loop()
        t0 = loop.time()
        for at, kind, arg in script:
            wait = t0 + at - loop.time()
            if wait > 0:
                await asyncio.sleep(wait)
            if kind == "partition":
                self.events.record("chaos_partition", f"{arg}s")
                self.transport.block_reconnects(True)
                await self.transport.disconnect(self.peer_ref)
                await asyncio.sleep(arg)
                self.transport.block_reconnects(False)
            elif kind == "kill":
                self.events.record("chaos_peer_kill", arg)
                await self.transport.disconnect(arg)
            elif kind == "wave_fault" and self.watchdog is not None:
                self.events.record("chaos_wave_fault")
                self.watchdog.inject_fault_next()


#: named, reusable fault scripts (RESILIENCE.md documents each); scenarios
#: are factories so every run gets a fresh rng stream from the same seed
SCENARIOS: Dict[str, Callable[..., ChaosPolicy]] = {}


def _scenario(name: str):
    def register(fn):
        SCENARIOS[name] = fn
        return fn

    return register


@_scenario("flaky_link")
def flaky_link(seed: int = 17) -> ChaosPolicy:
    """Lossy link, no scheduled events: 5% frame loss (each killing the
    link), light duplication — the pure reconnect/re-send storm shape."""
    return ChaosPolicy(seed=seed, drop=0.05, duplicate=0.02)


@_scenario("reorder_burst")
def reorder_burst(seed: int = 23) -> ChaosPolicy:
    """No loss, heavy reordering + duplication: exercises result-vs-
    invalidate races and inbound dedup without ever dropping the link."""
    return ChaosPolicy(seed=seed, duplicate=0.05, reorder_window=4)


@_scenario("member_churn")
def member_churn(seed: int = 41) -> ChaosPolicy:
    """Lossy, duplicating, reordering links with NO scheduled events — the
    message-level weather for the cluster acceptance scenario
    (tests/test_cluster.py): the kill/join sequence is orchestrated by the
    test (real member death, not a link flap), while every control-plane
    and data frame rides this policy."""
    return ChaosPolicy(seed=seed, drop=0.03, duplicate=0.02, reorder_window=4)


@_scenario("rolling_restart")
def rolling_restart(seed: int = 53) -> ChaosPolicy:
    """Message-level weather for the rolling-upgrade acceptance scenario
    (tests/test_cluster.py, ISSUE 6): each of the 3 members is killed and
    warm-rejoined from its durable snapshot IN SEQUENCE while every frame
    — heartbeats, map gossip, ``$sys-c`` pushes, rejoin traffic — rides a
    lossy, duplicating, reordering link. Like ``member_churn``, the
    kill/restart sequence itself is orchestrated by the test (real member
    death + restore-from-snapshot, not a link flap)."""
    return ChaosPolicy(seed=seed, drop=0.03, duplicate=0.02, reorder_window=4)


@_scenario("host_kill_reform")
def host_kill_reform(seed: int = 61) -> ChaosPolicy:
    """Mesh-layer weather for the host-death leg (ISSUE 16): one scheduled
    peer kill on a lossy link. The HOST kill itself (SIGKILL of a whole
    emulated-host process, evidence convergence, in-process degrade →
    re-form) is orchestrated by the harness (perf/mesh_multihost.py and
    tests/test_mesh_controller.py); this policy supplies the DCN frame
    weather riding under it, so detection converges from noisy evidence,
    not a clean silence."""
    return ChaosPolicy(
        seed=seed,
        drop=0.03,
        duplicate=0.02,
        reorder_window=4,
        peer_kills=[(0.2, "default")],
    )


@_scenario("host_flap")
def host_flap(seed: int = 67) -> ChaosPolicy:
    """Host flap (ISSUE 16): kill + fast rejoin under an open breaker.
    Two quick peer kills (the ramp that opens the breaker) and NO partition
    — the harness kills the host process right after, then relaunches it as
    a live JOINer while the survivor's breaker is still open. Certifies
    that a flapping host is absorbed via the counted degrade → re-form →
    join path with zero divergent waves, never a survivor restart."""
    return ChaosPolicy(
        seed=seed,
        drop=0.03,
        duplicate=0.02,
        reorder_window=4,
        peer_kills=[(0.1, "default"), (0.25, "default")],
    )


@_scenario("mesh_partition")
def mesh_partition(seed: int = 71) -> ChaosPolicy:
    """DCN partition between live hosts (ISSUE 16): a 1.5s full partition
    on a lossy link, no kills. The mesh controller must RIDE THIS OUT —
    a lone heartbeat lapse is single-source evidence, below the
    convergence threshold, so no eviction and no degrade; the window
    closes and waves stay oracle-exact."""
    return ChaosPolicy(
        seed=seed,
        drop=0.03,
        duplicate=0.02,
        reorder_window=4,
        partitions=[(0.2, 1.5)],
    )


@_scenario("partition_storm")
def partition_storm(seed: int = 31) -> ChaosPolicy:
    """Three quick peer kills (the flap ramp that opens a breaker), then a
    2-second full partition, on top of a lossy reordered link — the
    acceptance scenario of tests/test_resilience.py, with one wave fault
    injected mid-partition."""
    return ChaosPolicy(
        seed=seed,
        drop=0.05,
        duplicate=0.02,
        reorder_window=4,
        peer_kills=[(0.15, "default"), (0.3, "default"), (0.45, "default")],
        partitions=[(0.7, 2.0)],
        wave_faults=[0.8],
    )
