"""Resilience subsystem — chaos injection, peer circuit breakers, wave
watchdog (SURVEY "hard parts" + VERDICT "What's missing" #4: the burst path
and the RPC layer had never been exercised together under failure).

The pieces compose around one shared :class:`ResilienceEvents` registry
(degradation events + breaker transitions, exported through
``diagnostics.FusionMonitor.report()``):

- :mod:`.chaos` — seeded, deterministic fault injection (drop / duplicate /
  delay / reorder, timed partitions, peer-kill schedules) pluggable into the
  twisted test channels AND the real middleware chains, plus a scenario
  runner that replays named fault scripts;
- :mod:`.breaker` — per-peer circuit breakers (closed/open/half-open) fed by
  ``connection_state``, quarantining flapping peers so reconnect re-send
  storms can't amplify;
- :mod:`.watchdog` — deadline + fault enforcement on device wave dispatches:
  a fused burst that blows its deadline or raises degrades to the split host
  loop, and the first wave after re-engaging the fused path is verified
  against an independent host-BFS oracle.
"""
from .events import DegradationEvent, ResilienceEvents, global_events
from .chaos import (
    SCENARIOS,
    ChaosActions,
    ChaosPolicy,
    ChaosScenarioRunner,
    chaos_middleware,
    wrap_chaos_pair,
)
from .breaker import BreakerState, PeerCircuitBreaker
from .watchdog import WaveWatchdog

__all__ = [
    "BreakerState",
    "ChaosActions",
    "ChaosPolicy",
    "ChaosScenarioRunner",
    "DegradationEvent",
    "PeerCircuitBreaker",
    "ResilienceEvents",
    "SCENARIOS",
    "WaveWatchdog",
    "chaos_middleware",
    "global_events",
    "wrap_chaos_pair",
]
