"""ResilienceEvents — the shared degradation-event ledger.

Every resilience component (breaker transitions, watchdog fallbacks, oplog
quarantines) records into one of these; ``diagnostics.FusionMonitor.report()``
exports the counters so a single stats dump answers "did anything degrade,
and how often". Bounded: counters are a dict, the event tail a deque — a
flapping peer can transition forever without growing memory.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

__all__ = ["DegradationEvent", "ResilienceEvents", "global_events"]


@dataclass(frozen=True)
class DegradationEvent:
    kind: str  # e.g. "breaker_open", "wave_fallback", "oplog_corrupt"
    detail: str = ""
    at: float = field(default_factory=time.monotonic)


class ResilienceEvents:
    """Counter + bounded-tail registry for degradation events."""

    def __init__(self, capacity: int = 256):
        self.counters: Dict[str, int] = {}
        self.recent: Deque[DegradationEvent] = deque(maxlen=capacity)

    def record(self, kind: str, detail: str = "") -> DegradationEvent:
        self.counters[kind] = self.counters.get(kind, 0) + 1
        ev = DegradationEvent(kind, detail)
        self.recent.append(ev)
        return ev

    def count(self, kind: str) -> int:
        return self.counters.get(kind, 0)

    def total(self) -> int:
        return sum(self.counters.values())

    def snapshot(self) -> Dict[str, int]:
        return dict(self.counters)

    def recent_of(self, kind: str, limit: Optional[int] = None) -> List[DegradationEvent]:
        out = [e for e in self.recent if e.kind == kind]
        return out[-limit:] if limit is not None else out

    def clear(self) -> None:
        self.counters.clear()
        self.recent.clear()


#: the process-wide default ledger: components that aren't handed an explicit
#: registry record here, so FusionMonitor.report() sees them with no wiring
_GLOBAL = ResilienceEvents()
_METRICS_REGISTERED = False


def global_events() -> ResilienceEvents:
    # lazily expose the ledger's counters through the process metrics
    # registry (/metrics route, ISSUE 3): one collector, registered the
    # first time anything touches the ledger
    global _METRICS_REGISTERED
    if not _METRICS_REGISTERED:
        _METRICS_REGISTERED = True
        from ..diagnostics.metrics import global_metrics

        global_metrics().register_collector(
            _GLOBAL,
            lambda ev: {
                f"fusion_resilience_{k}_total": v for k, v in ev.counters.items()
            },
        )
    return _GLOBAL
