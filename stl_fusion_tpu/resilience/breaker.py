"""PeerCircuitBreaker — quarantine for flapping RPC peers.

Every reconnect re-sends the peer's whole registered-call batch
(rpc/peer.py:on_run), so a peer flapping at the transport's natural retry
rate multiplies wire traffic by the batch size — the re-send storm this
breaker exists to damp. Scoring is fed by the peer's ``connection_state``
AsyncEvent chain (the same stream ``ext/peer_monitor.py`` renders):

- **closed** — healthy; error-carrying DISCONNECTED transitions count as
  flaps, CONNECTED as successes. Too many flaps inside ``flap_window`` OR a
  high failure rate over the recent outcome window trips it open.
- **open** — quarantined: the hub's connect gate (installed via
  ``RpcHub.connect_gates``) parks every dial until the cooldown elapses, so
  a flapping peer stops burning connect + re-send cycles. Cooldowns escalate
  (×2 per consecutive open, capped).
- **half-open** — one probe dial is allowed through. A connection that
  stays up for ``probe_stable`` closes the breaker; one that dies first
  re-opens it with the escalated cooldown.

Transitions are counted in the shared :class:`ResilienceEvents` ledger and
surfaced per-peer through ``RpcPeerState.breaker`` (ext/peer_monitor.py).
"""
from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Callable, Deque, Optional

from ..rpc.peer import ConnectionState, RpcClientPeer
from ..utils.async_chain import WorkerBase
from ..utils.async_utils import AsyncEvent
from .events import ResilienceEvents, global_events

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["BreakerState", "PeerCircuitBreaker"]


class BreakerState:
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"


class PeerCircuitBreaker(WorkerBase):
    def __init__(
        self,
        peer: RpcClientPeer,
        flap_threshold: int = 3,
        flap_window: float = 10.0,
        failure_rate_threshold: float = 0.75,
        failure_rate_min_samples: int = 6,
        cooldown: float = 0.5,
        max_cooldown: float = 30.0,
        probe_stable: float = 0.25,
        events: Optional[ResilienceEvents] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        super().__init__(f"breaker:{peer.ref}")
        self.peer = peer
        self.flap_threshold = flap_threshold
        self.flap_window = flap_window
        self.failure_rate_threshold = failure_rate_threshold
        self.failure_rate_min_samples = failure_rate_min_samples
        self.cooldown = cooldown
        self.max_cooldown = max_cooldown
        self.probe_stable = probe_stable
        self.events = events if events is not None else global_events()
        self._clock = clock
        self.state = BreakerState.CLOSED
        #: awaitable transition chain — some transitions (open→half-open in
        #: the dial gate, half-open→closed on probe-stable timeout) happen
        #: with NO connection_state event, so observers like
        #: RpcPeerStateMonitor select on this chain too
        self.changes: AsyncEvent[str] = AsyncEvent(BreakerState.CLOSED)
        self.opens = 0  # lifetime open transitions
        self.closes = 0  # lifetime half-open → closed recoveries
        self.quarantined_dials = 0  # dials the gate parked while open
        self._consecutive_opens = 0
        self._open_until = 0.0
        self._probe_pending = False  # a released half-open probe hasn't resolved
        self._flaps: Deque[float] = deque(maxlen=64)
        self._outcomes: Deque[bool] = deque(maxlen=16)  # True = connected
        self._gate: Optional[Callable] = None

    # ------------------------------------------------------------------ wiring
    def install(self) -> "PeerCircuitBreaker":
        """Attach to the peer's hub: gate dials, watch state, advertise on
        the peer (``peer.breaker``) so peer_monitor can render the state."""

        async def gate(peer) -> None:
            if peer is self.peer:
                await self._gate_dial()

        self._gate = gate
        self.peer.hub.connect_gates.append(gate)
        self.peer.breaker = self  # type: ignore[attr-defined]
        # breaker-state gauges for /metrics (ISSUE 3): weak-registered, so a
        # disposed/collected breaker drops out of the scrape on its own
        from ..diagnostics.metrics import global_metrics

        global_metrics().register_collector(self, PeerCircuitBreaker._collect_metrics)
        self.start()
        return self

    def _collect_metrics(self) -> dict:
        return {
            "fusion_breakers": 1,
            "fusion_breakers_open": 1 if self.state == BreakerState.OPEN else 0,
            "fusion_breakers_half_open": 1 if self.state == BreakerState.HALF_OPEN else 0,
            "fusion_breaker_opens_total": self.opens,
            "fusion_breaker_closes_total": self.closes,
            "fusion_breaker_quarantined_dials_total": self.quarantined_dials,
        }

    async def dispose(self) -> None:
        if self._gate is not None:
            try:
                self.peer.hub.connect_gates.remove(self._gate)
            except ValueError:
                pass
            self._gate = None
        if getattr(self.peer, "breaker", None) is self:
            self.peer.breaker = None  # type: ignore[attr-defined]
        from ..diagnostics.metrics import global_metrics

        global_metrics().unregister_collector(self)
        await self.stop()

    # ------------------------------------------------------------------ scoring
    async def on_run(self) -> None:
        ev = self.peer.connection_state
        while True:
            s = ev.value
            if s.kind == ConnectionState.DISCONNECTED and s.error is not None:
                self._on_failure()
            elif s.is_connected:
                self._outcomes.append(True)
                if self.state in (BreakerState.HALF_OPEN, BreakerState.OPEN):
                    # HALF_OPEN: the sanctioned probe. OPEN: a dial that was
                    # already in flight when the breaker tripped (or replayed
                    # history) connected anyway — the quarantine can't undo a
                    # live link, so judge it like a probe; refusing to would
                    # strand the breaker OPEN on a healthy connection with no
                    # future dial ever consulting the gate.
                    ev = await self._judge_probe(ev)
                    continue
            ev = await ev.when_next()

    def _on_failure(self) -> None:
        now = self._clock()
        self._flaps.append(now)
        self._outcomes.append(False)
        if self.state == BreakerState.HALF_OPEN:
            self._trip("probe link died")
            return
        if self.state != BreakerState.CLOSED:
            return
        recent = [t for t in self._flaps if now - t <= self.flap_window]
        rate_samples = len(self._outcomes)
        failure_rate = (
            sum(1 for ok in self._outcomes if not ok) / rate_samples
            if rate_samples
            else 0.0
        )
        if len(recent) >= self.flap_threshold:
            self._trip(f"{len(recent)} flaps in {self.flap_window}s")
        elif (
            rate_samples >= self.failure_rate_min_samples
            and failure_rate >= self.failure_rate_threshold
        ):
            self._trip(f"failure rate {failure_rate:.2f}")

    def _set_state(self, state: str) -> None:
        self.state = state
        self.changes = self.changes.latest().create_next(state)

    def _trip(self, why: str) -> None:
        self._probe_pending = False
        self._consecutive_opens += 1
        self.opens += 1
        delay = min(
            self.cooldown * (2 ** (self._consecutive_opens - 1)), self.max_cooldown
        )
        self._open_until = self._clock() + delay
        self._set_state(BreakerState.OPEN)
        self.events.record("breaker_open", f"{self.peer.ref}: {why}")
        log.debug("breaker %s OPEN for %.2fs (%s)", self.peer.ref, delay, why)

    async def _judge_probe(self, ev):
        """Half-open + connected: stable for ``probe_stable`` ⇒ closed;
        a faster transition ⇒ the probe failed, re-open escalated."""
        try:
            nxt = await asyncio.wait_for(ev.when_next(), self.probe_stable)
        except asyncio.TimeoutError:
            self._probe_pending = False
            self._set_state(BreakerState.CLOSED)
            self.closes += 1
            self._consecutive_opens = 0
            self._flaps.clear()
            # a fresh close means a fresh score: stale failures must not
            # let one new transient disconnect re-trip via the rate rule
            self._outcomes.clear()
            self.events.record("breaker_close", self.peer.ref)
            log.debug("breaker %s CLOSED (probe stable)", self.peer.ref)
            return ev
        # the probe connection changed state before stabilizing; the
        # DISCONNECTED handler on the next loop pass re-opens via _trip
        return nxt

    # ------------------------------------------------------------------ gating
    async def _gate_dial(self) -> None:
        """Awaited by RpcHub.connect_client before every dial of this peer:
        parks dials while open, releases exactly one probe when the
        cooldown elapses (half-open)."""
        parked = False
        while True:
            if self.state == BreakerState.HALF_OPEN and self._probe_pending:
                # the peer is dialing AGAIN while a released probe never
                # resolved: the probe dial itself failed to connect (dial
                # errors emit no connection_state event — this gate re-entry
                # is the only signal). An unreachable peer must re-open
                # escalated, not dial ungated at the transport retry rate.
                self._trip("probe dial failed")
            if self.state != BreakerState.OPEN:
                if self.state == BreakerState.HALF_OPEN:
                    self._probe_pending = True
                return
            wait = self._open_until - self._clock()
            if wait <= 0:
                self._set_state(BreakerState.HALF_OPEN)
                self.events.record("breaker_half_open", self.peer.ref)
                continue  # falls through to release exactly one probe
            if not parked:  # one DIAL quarantined, however many sleep cycles
                parked = True
                self.quarantined_dials += 1
            await asyncio.sleep(wait)

    def snapshot(self) -> dict:
        return {
            "peer": self.peer.ref,
            "state": self.state,
            "opens": self.opens,
            "closes": self.closes,
            "quarantined_dials": self.quarantined_dials,
        }
