"""WaveWatchdog — deadline + fault containment for device wave dispatches.

The fused burst paths (topo-mirror sweeps, lat unions — graph/backend.py →
graph/device_graph.py) are the fast path; the SPLIT HOST LOOP (one dense
``run_waves_union(..., mirror="off")`` per seed group, driven from host
Python) is the always-correct slow path — the composable fallback arxiv
2406.18109 argues must stay live behind every fused pipeline. The watchdog
arbitrates between them:

- a fused dispatch that RAISES is contained: the burst re-runs on the host
  loop (invalidation is idempotent, so a partially-applied fused attempt is
  absorbed by the re-run) and the backend degrades;
- a fused dispatch that exceeds ``deadline_s`` degrades the backend (its
  result stands — a jax dispatch cannot be preempted, so the deadline is
  judged on completion);
- while degraded, ``recovery_bursts`` bursts run on the host loop, then the
  fused path re-engages and the FIRST fused wave is verified against an
  independent host CSR BFS oracle over the live edge set. A mismatch
  re-degrades (and counts ``wave_oracle_mismatch``); a match closes the
  incident.

``inject_fault_next()`` is the chaos hook: the next fused dispatch raises,
exactly as if the device runtime had — scenario scripts use it to prove the
burst pipeline survives a dead dispatch mid-storm.
"""
from __future__ import annotations

import logging
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .events import ResilienceEvents, global_events

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["WaveWatchdog"]


class WaveWatchdog:
    MODE_FUSED = "fused"
    MODE_HOST = "host"

    def __init__(
        self,
        deadline_s: float = 5.0,
        recovery_bursts: int = 2,
        events: Optional[ResilienceEvents] = None,
    ):
        self.deadline_s = deadline_s
        self.recovery_bursts = recovery_bursts
        self.events = events if events is not None else global_events()
        self.mode = WaveWatchdog.MODE_FUSED
        self.fallbacks = 0  # bursts served by the host loop
        self.faults = 0  # fused dispatches that raised
        self.deadline_trips = 0
        self.reengages = 0  # fused re-engagements (oracle-verified)
        self.oracle_checks = 0
        self.oracle_mismatches = 0
        self._host_bursts_left = 0
        self._verify_next = False
        self._inject: Optional[BaseException] = None

    # ------------------------------------------------------------------ chaos hook
    def inject_fault_next(self, exc: Optional[BaseException] = None) -> None:
        """Arm a one-shot fault: the next fused dispatch raises ``exc``."""
        self._inject = exc if exc is not None else RuntimeError("injected wave fault")

    def _check_injected(self) -> None:
        if self._inject is not None:
            exc, self._inject = self._inject, None
            raise exc

    # ------------------------------------------------------------------ dispatch
    def _dispatch(self, graph, seed_lists, fused_fn, host_fn):
        """The shared state machine around one burst: degraded → host path;
        fused → contain faults (re-run on host), judge the deadline, and
        oracle-verify the first wave after a re-engagement. A deadline trip
        on the verify wave re-degrades and KEEPS the pending verify for the
        next re-engagement — never recording a wave_reengaged the mode
        contradicts. Both fused_fn and host_fn return (counts-ish, newly)."""
        if self.mode == WaveWatchdog.MODE_HOST:
            res = host_fn(graph, seed_lists)
            self._after_host_burst()
            return res
        verify = self._verify_next
        pre_invalid = graph._h_invalid.copy() if verify else None
        t0 = time.perf_counter()
        try:
            self._check_injected()
            res = fused_fn(graph, seed_lists)
        except Exception as e:  # noqa: BLE001 — contain, degrade, re-run on host
            self._on_fault(e)
            res = host_fn(graph, seed_lists)
            self._after_host_burst()
            return res
        self._check_deadline(t0)
        if verify and self.mode == WaveWatchdog.MODE_FUSED:
            newly = res[1]
            if isinstance(newly, np.ndarray) and newly.dtype == np.bool_:
                newly = np.nonzero(newly)[0].astype(np.int32)
            self._oracle_verify(graph, seed_lists, pre_invalid, newly)
        return res

    def run_union(self, graph, seed_lists: Sequence[Sequence[int]]) -> Tuple[int, np.ndarray]:
        """Union burst through the watchdog: fused when healthy, split host
        loop while degraded. Same contract as DeviceGraph.run_waves_union."""
        return self._dispatch(
            graph, seed_lists,
            lambda g, s: g.run_waves_union(s), self._host_union,
        )

    def run_lanes(self, graph, seed_lists: Sequence[Sequence[int]]) -> Tuple[np.ndarray, np.ndarray]:
        """Lane burst through the watchdog. Degraded semantics: each group
        expands SEQUENTIALLY on the dense path (group i sees group < i's
        commits), so per-group counts can undercount relative to the
        snapshot-independent lane kernel — the union (what the hub applies)
        is identical, which is the consistency contract."""
        return self._dispatch(
            graph, seed_lists,
            lambda g, s: g.run_waves_lanes(s), self._host_lanes,
        )

    def run_seq(self, graph, seed_lists: Sequence[Sequence[int]]) -> Tuple[np.ndarray, np.ndarray]:
        """Sequenced union burst (cascade_rows_batch_seq) through the
        watchdog. The host fallback loops the dense union per wave — which
        IS the seq contract (wave i sees wave < i's commits), so degraded
        counts match the fused ones exactly."""
        return self._dispatch(
            graph, seed_lists,
            lambda g, s: g.run_waves_union_seq(s), self._host_lanes,
        )

    # ------------------------------------------------------------------ degradation
    def _on_fault(self, e: BaseException) -> None:
        self.faults += 1
        self._degrade("wave_fault", repr(e))

    def _check_deadline(self, t0: float) -> None:
        dt = time.perf_counter() - t0
        if dt > self.deadline_s:
            self.deadline_trips += 1
            self._degrade("wave_deadline", f"{dt:.3f}s > {self.deadline_s}s")

    def _degrade(self, kind: str, detail: str) -> None:
        self.events.record(kind, detail)
        if self.mode != WaveWatchdog.MODE_HOST:
            self.mode = WaveWatchdog.MODE_HOST
            self.events.record("wave_fallback", detail)
            log.warning("wave watchdog: degraded to host loop (%s: %s)", kind, detail)
        self._host_bursts_left = self.recovery_bursts

    def _after_host_burst(self) -> None:
        self.fallbacks += 1
        self._host_bursts_left -= 1
        if self._host_bursts_left <= 0 and self.mode == WaveWatchdog.MODE_HOST:
            self.mode = WaveWatchdog.MODE_FUSED
            self._verify_next = True  # first fused wave back is oracle-checked

    # ------------------------------------------------------------------ host path
    @staticmethod
    def _host_lanes(graph, seed_lists) -> Tuple[np.ndarray, np.ndarray]:
        """The split host loop: one dense (mirror-free) union per seed
        group, sequenced from host Python. No mirror, no fused program —
        the degraded path shares nothing with the path that just failed."""
        counts = np.zeros(len(seed_lists), dtype=np.int64)
        parts: List[np.ndarray] = []
        for i, s in enumerate(seed_lists):
            if not len(s):
                continue
            c, ids = graph.run_waves_union([s], mirror="off")
            counts[i] = c
            if len(ids):
                parts.append(ids)
        return counts, (
            np.concatenate(parts) if parts else np.empty(0, np.int32)
        )

    @classmethod
    def _host_union(cls, graph, seed_lists) -> Tuple[int, np.ndarray]:
        counts, ids = cls._host_lanes(graph, seed_lists)
        return int(counts.sum()), ids

    # ------------------------------------------------------------------ oracle
    def _oracle_verify(self, graph, seed_lists, pre_invalid: np.ndarray, newly) -> None:
        """Independent host CSR BFS over the live edge set, compared with
        the fused wave's newly-invalid set. Seeds conduct even when
        pre-invalid; non-seed invalid nodes block — the run_waves_union
        contract (ops/wave.py). Everything stays a boolean MASK end to end:
        a Python int set at the 10M-node scale would burn seconds of
        single-threaded boxing on the event loop mid-recovery."""
        self._verify_next = False
        self.oracle_checks += 1
        nn = graph.n_nodes
        expected = self._host_closure(graph, seed_lists, pre_invalid)
        if isinstance(newly, np.ndarray) and newly.dtype == np.bool_:
            got = newly[:nn]
        else:
            got = np.zeros(nn, dtype=bool)
            ids = np.asarray(newly, dtype=np.int64)
            got[ids[(ids >= 0) & (ids < nn)]] = True
        if np.array_equal(expected, got):
            n_got = int(got.sum())
            self.reengages += 1
            self.events.record("wave_reengaged", f"verified {n_got} newly")
            log.info("wave watchdog: fused path re-engaged (oracle OK, %d newly)", n_got)
            return
        self.oracle_mismatches += 1
        miss = int((expected & ~got).sum())
        extra = int((got & ~expected).sum())
        self._degrade(
            "wave_oracle_mismatch",
            f"missing {miss}, extra {extra} of {int(expected.sum())}",
        )

    @staticmethod
    def _host_closure(graph, seed_lists, pre_invalid: np.ndarray) -> np.ndarray:
        m = graph.n_edges
        live = (
            graph._h_node_epoch[graph._h_edge_dst[:m]] == graph._h_edge_dst_epoch[:m]
        )
        src = graph._h_edge_src[:m][live].astype(np.int64)
        dst = graph._h_edge_dst[:m][live].astype(np.int64)
        nn = graph.n_nodes
        keep = (src < nn) & (dst < nn)
        src, dst = src[keep], dst[keep]
        order = np.argsort(src, kind="stable")
        src_s, dst_s = src[order], dst[order]
        starts = np.zeros(nn + 1, dtype=np.int64)
        np.add.at(starts[1:], src_s, 1)
        starts = np.cumsum(starts)
        seeds = np.unique(
            np.asarray([int(i) for s in seed_lists for i in s], dtype=np.int64)
        )
        seeds = seeds[(seeds >= 0) & (seeds < nn)]
        invalid = pre_invalid[:nn].copy()
        newly_mask = np.zeros(nn, dtype=bool)
        newly_mask[seeds[~invalid[seeds]]] = True
        invalid[seeds] = True
        frontier = seeds  # all seeds conduct, pre-invalid or not
        while frontier.size:
            # vectorized level expansion: one fancy-index gather of every
            # frontier out-edge per level — a Python per-node loop here
            # would stall the event loop for minutes on 10M-node graphs
            s0, s1 = starts[frontier], starts[frontier + 1]
            deg = s1 - s0
            total = int(deg.sum())
            if total == 0:
                break
            offsets = np.repeat(np.cumsum(deg) - deg, deg)
            idx = np.repeat(s0, deg) + (np.arange(total, dtype=np.int64) - offsets)
            cand = dst_s[idx]
            cand = np.unique(cand[~invalid[cand]])
            invalid[cand] = True
            newly_mask[cand] = True
            frontier = cand
        return newly_mask

    def snapshot(self) -> dict:
        return {
            "mode": self.mode,
            "fallbacks": self.fallbacks,
            "faults": self.faults,
            "deadline_trips": self.deadline_trips,
            "reengages": self.reengages,
            "oracle_checks": self.oracle_checks,
            "oracle_mismatches": self.oracle_mismatches,
        }
