"""Mesh helpers — the device topology the sharded graph runs on.

The TPU-native replacement for the reference's server-pool scaling story
(RpcCallRouter consistent-hash routing across hosts,
samples/MultiServerRpc/Program.cs:58-76): instead of routing calls between
processes over WebSockets, the dependency graph itself is sharded over a
``jax.sharding.Mesh`` and invalidation frontiers ride ICI collectives.
"""
from __future__ import annotations

import inspect
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax ≥ 0.6 exports it top-level with the check_vma kwarg
    from jax import shard_map as _shard_map
except ImportError:  # older jax: experimental home, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

__all__ = ["graph_mesh", "shard_map_compat", "P", "Mesh", "NamedSharding"]

GRAPH_AXIS = "graph"

#: which replication-check kwarg THIS jax's shard_map takes (the flag was
#: renamed check_rep → check_vma across releases; pallas interpret-mode
#: lowering can't track either, so callers disable it by whatever name)
_CHECK_KW = (
    "check_vma"
    if "check_vma" in inspect.signature(_shard_map).parameters
    else "check_rep"
)


def shard_map_compat(mesh, in_specs, out_specs, check: bool = False):
    """``jax.shard_map`` decorator across the jax versions this repo meets
    (top-level vs experimental module, check_vma vs check_rep)."""
    def deco(f):
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **{_CHECK_KW: check}
        )

    return deco


def graph_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the graph axis (edge/node sharding dimension)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (GRAPH_AXIS,))
