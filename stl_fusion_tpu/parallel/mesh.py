"""Mesh helpers — the device topology the sharded graph runs on.

The TPU-native replacement for the reference's server-pool scaling story
(RpcCallRouter consistent-hash routing across hosts,
samples/MultiServerRpc/Program.cs:58-76): instead of routing calls between
processes over WebSockets, the dependency graph itself is sharded over a
``jax.sharding.Mesh`` and invalidation frontiers ride ICI collectives.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["graph_mesh", "P", "Mesh", "NamedSharding"]

GRAPH_AXIS = "graph"


def graph_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the graph axis (edge/node sharding dimension)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (GRAPH_AXIS,))
