"""Sharded invalidation waves — the multi-chip execution of the hot path.

This is the TPU-native replacement for the reference's cross-host
invalidation fan-out (per-peer WebSocket pub/sub + DB op-log readers,
SURVEY.md §3.5, §5.8), re-designed per the BASELINE north star: the
dependency graph's nodes AND edges are sharded over a device mesh, and each
BFS level exchanges the invalidation frontier with ONE ``all_gather`` over
ICI instead of N point-to-point messages.

Sharding layout (1-D mesh, axis ``graph``):
- nodes: block-sharded — device d owns ids [d*n_local, (d+1)*n_local);
  ``node_epoch`` / ``invalid`` live sharded, never replicated;
- edges: sharded by DESTINATION owner, so the version-match gather
  (``node_epoch[dst]``) and the invalidation scatter are device-local;
  only the frontier read (``frontier[src]``) needs remote data — hence the
  all-gather;
- per level: local fire-mask → local scatter → ``psum`` of the newly-lit
  count decides continuation (the while_loop carries the flag so no
  collective runs in ``cond``).

Out-of-range padding uses JAX's gather-clamps/scatter-drops semantics:
padded edges point at ``dst = n_local`` (dropped on scatter) with epoch -1
(never matches on gather).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import GRAPH_AXIS, graph_mesh, shard_map_compat

__all__ = ["ShardedGraphArrays", "ShardedDeviceGraph", "build_sharded_wave"]


class ShardedGraphArrays(NamedTuple):
    edge_src: jax.Array  # int32[n_dev * e_shard] — GLOBAL source ids
    edge_dst_local: jax.Array  # int32[n_dev * e_shard] — LOCAL dest ids (pad = n_local)
    edge_dst_epoch: jax.Array  # int32[n_dev * e_shard] — pad = -1
    node_epoch: jax.Array  # int32[n_global] — sharded by node block
    invalid: jax.Array  # bool[n_global] — sharded by node block


def build_sharded_wave(mesh: Mesh, n_global: int, exchange: str = "packed"):
    """Compile the sharded wave for a mesh + node capacity.

    Returns a ``(wave, wave_chain)`` pair:
    - ``wave(seed_frontier, g) -> (g, newly_invalidated_count)`` — one wave;
    - ``wave_chain(seed_mat, g, reset_between) -> (g, total, counts)`` —
      ``seed_mat.shape[0]`` waves in one compiled program (single readback).

    ``exchange`` selects the per-level frontier collective:
    - ``"packed"`` (default): the local frontier bit-packs into uint32 words
      before the all-gather — 8x fewer bytes over ICI than gathering the
      bool lane (XLA bools travel as one byte each); sources then test
      ``word >> (id & 31)`` instead of gathering bools.
    - ``"ring"``: packed words move through the hand-written Pallas ICI
      ring-RDMA kernel (ops/pallas_kernels.make_ring_all_gather) instead of
      ``lax.all_gather`` — explicit hop-by-hop overlap control.
    - ``"bool"``: the plain boolean all-gather (reference for equivalence
      tests and as a fallback).
    """
    n_dev = mesh.devices.size
    n_local = n_global // n_dev
    assert n_global % n_dev == 0, "node capacity must divide evenly over the mesh"
    if exchange not in ("packed", "bool", "ring"):
        raise ValueError(f"unknown exchange {exchange!r}")
    if exchange in ("packed", "ring"):
        assert n_local % 32 == 0, "packed/ring exchange needs n_local % 32 == 0"
    ring = None
    if exchange == "ring":
        from ..ops.pallas_kernels import make_ring_all_gather

        ring = make_ring_all_gather(GRAPH_AXIS)

    node_spec = P(GRAPH_AXIS)
    edge_spec = P(GRAPH_AXIS)

    def _pack_words(f_l):
        lanes = jnp.arange(32, dtype=jnp.uint32)[None, :]
        return jnp.sum(
            f_l.reshape(-1, 32).astype(jnp.uint32) << lanes, axis=1, dtype=jnp.uint32
        )

    def _gather_src_active(f_l, esrc_l):
        """frontier exchange + per-edge source-activity test (ONE collective)."""
        if exchange == "bool":
            f_full = lax.all_gather(f_l, GRAPH_AXIS, tiled=True)
            return f_full[esrc_l]
        if exchange == "packed":
            f_full_w = lax.all_gather(_pack_words(f_l), GRAPH_AXIS, tiled=True)
            word = f_full_w[esrc_l >> 5]
            return ((word >> (esrc_l & 31).astype(jnp.uint32)) & 1).astype(bool)
        # ring: pad this device's words to the kernel's 128-lane tile; the
        # gathered vector is then BLOCK-padded per device, so the word index
        # for global id g is owner(g)*padded + (g within owner)/32
        w = n_local // 32
        wp = (w + 127) // 128 * 128
        words = jnp.zeros(wp, jnp.uint32).at[:w].set(_pack_words(f_l))
        full = ring(words)  # (n_dev * wp,)
        dev = esrc_l // n_local
        within = esrc_l - dev * n_local
        word = full[dev * wp + (within >> 5)]
        return ((word >> (within & 31).astype(jnp.uint32)) & 1).astype(bool)

    @shard_map_compat(
        mesh=mesh,
        in_specs=(node_spec, edge_spec, edge_spec, edge_spec, node_spec, node_spec),
        out_specs=(node_spec, node_spec, P()),
    )
    def _wave(seeds_l, esrc_l, edst_l, eepoch_l, nepoch_l, inv_l):
        # seeds CONDUCT even when already invalid (r4, same rule as the
        # single-chip union — ops/wave.py::run_waves_union: an uncascaded
        # columnar mark's declared dependents live only in the graph);
        # pre-invalid seeds don't count, invalid NON-seeds still block
        fresh = seeds_l & ~inv_l
        inv_l = inv_l | seeds_l
        count0 = lax.psum(fresh.sum(dtype=jnp.int32), GRAPH_AXIS)
        go0 = lax.psum(seeds_l.any().astype(jnp.int32), GRAPH_AXIS) > 0

        def cond(carry):
            _f, _inv, _count, go = carry
            return go

        def body(carry):
            f_l, inv_l, count, _go = carry
            src_active = _gather_src_active(f_l, esrc_l)
            ver_ok = nepoch_l[edst_l] == eepoch_l  # gather clamps; -1 never matches
            fire = src_active & ver_ok & ~inv_l[edst_l]
            nxt_l = jnp.zeros_like(f_l).at[edst_l].max(fire)  # OOB pads dropped
            inv_l = inv_l | nxt_l
            newly = lax.psum(nxt_l.sum(dtype=jnp.int32), GRAPH_AXIS)
            return nxt_l, inv_l, count + newly, newly > 0

        _f, inv_l, count, _go = lax.while_loop(cond, body, (seeds_l, inv_l, count0, go0))
        return inv_l, nepoch_l, count

    @jax.jit
    def wave(seed_frontier: jax.Array, g: ShardedGraphArrays):
        invalid, node_epoch, count = _wave(
            seed_frontier, g.edge_src, g.edge_dst_local, g.edge_dst_epoch, g.node_epoch, g.invalid
        )
        return g._replace(invalid=invalid, node_epoch=node_epoch), count

    @functools.partial(jax.jit, static_argnums=2)
    def wave_chain(seed_mat: jax.Array, g: ShardedGraphArrays, reset_between: bool):
        """W waves in ONE compiled program with a single readback — the
        multi-chip analogue of the single-chip bench's lax.scan batching
        (per-wave host dispatch pays a relay/dispatch round trip each; the
        chain pays it once). ``reset_between`` clears ``invalid`` before
        each wave (the bench's churn model: the graph is re-consistent
        between waves)."""

        def body(carry, seeds):
            g, total = carry
            if reset_between:
                g = g._replace(invalid=jnp.zeros_like(g.invalid))
            g, count = wave(seeds, g)
            return (g, total + count), count

        (g, total), counts = lax.scan(body, (g, jnp.int32(0)), seed_mat)
        return g, total, counts

    return wave, wave_chain


class ShardedDeviceGraph:
    """Static sharded graph for multi-chip waves (bench + dry-run scale
    path; the incremental host mirror is DeviceGraph on one chip)."""

    def __init__(
        self,
        edges_src: np.ndarray,
        edges_dst: np.ndarray,
        n_nodes: int,
        mesh: Optional[Mesh] = None,
        edge_dst_epoch: Optional[np.ndarray] = None,
        exchange: str = "packed",
        node_epoch: Optional[np.ndarray] = None,
        invalid: Optional[np.ndarray] = None,
    ):
        self.mesh = mesh or graph_mesh()
        n_dev = self.mesh.devices.size
        # n_local rounds up to a multiple of 32 so the packed exchange's
        # uint32 words tile evenly per device (floor 32: an empty graph
        # still needs one valid row block per device to compile)
        self.n_local = max(((n_nodes + n_dev - 1) // n_dev + 31) // 32 * 32, 32)
        self.n_global = self.n_local * n_dev
        self.n_nodes = n_nodes
        self.n_dev = n_dev
        self.exchange = exchange

        src = np.asarray(edges_src, dtype=np.int32)
        dst = np.asarray(edges_dst, dtype=np.int32)
        epoch = (
            np.zeros_like(dst)
            if edge_dst_epoch is None
            else np.asarray(edge_dst_epoch, dtype=np.int32)
        )
        # partition edges by destination owner; pad shards to equal length
        owner = dst // self.n_local
        order = np.argsort(owner, kind="stable")
        src, dst, epoch, owner = src[order], dst[order], epoch[order], owner[order]
        counts = np.bincount(owner, minlength=n_dev)
        e_shard = max(int(counts.max()), 1)
        E = n_dev * e_shard
        esrc = np.zeros(E, dtype=np.int32)
        edst_local = np.full(E, self.n_local, dtype=np.int32)  # pad: OOB → dropped
        eepoch = np.full(E, -1, dtype=np.int32)  # pad: never version-matches
        start = 0
        for d in range(n_dev):
            k = counts[d]
            if k:
                sl = slice(d * e_shard, d * e_shard + k)
                esrc[sl] = src[start : start + k]
                edst_local[sl] = dst[start : start + k] - d * self.n_local
                eepoch[sl] = epoch[start : start + k]
                start += k
        self.e_shard = e_shard

        node_sh = NamedSharding(self.mesh, P(GRAPH_AXIS))
        edge_sh = NamedSharding(self.mesh, P(GRAPH_AXIS))
        # optional state import (live-graph snapshots): pad rows beyond
        # n_nodes keep epoch 0 / not-invalid — they have no edges to fire
        nep = np.zeros(self.n_global, dtype=np.int32)
        inv = np.zeros(self.n_global, dtype=bool)
        if node_epoch is not None:
            nep[:n_nodes] = np.asarray(node_epoch[:n_nodes], dtype=np.int32)
        if invalid is not None:
            inv[:n_nodes] = np.asarray(invalid[:n_nodes], dtype=bool)
        self.g = ShardedGraphArrays(
            edge_src=jax.device_put(esrc, edge_sh),
            edge_dst_local=jax.device_put(edst_local, edge_sh),
            edge_dst_epoch=jax.device_put(eepoch, edge_sh),
            node_epoch=jax.device_put(nep, node_sh),
            invalid=jax.device_put(inv, node_sh),
        )
        self._node_sharding = node_sh
        self._wave, self._wave_chain = build_sharded_wave(
            self.mesh, self.n_global, exchange=exchange
        )
        self._collect_cache: dict = {}  # (cap, seed_width) → jitted program

    # ------------------------------------------------------------------ waves
    def seeds_to_frontier(self, seed_ids: Sequence[int]) -> jax.Array:
        frontier = np.zeros(self.n_global, dtype=bool)
        frontier[np.asarray(seed_ids, dtype=np.int64)] = True
        return jax.device_put(frontier, self._node_sharding)

    def run_wave(self, seed_ids: Sequence[int]) -> int:
        self.g, count = self._wave(self.seeds_to_frontier(seed_ids), self.g)
        return int(count)

    def run_wave_frontier(self, frontier: jax.Array) -> int:
        self.g, count = self._wave(frontier, self.g)
        return int(count)

    def run_wave_collect(
        self, seed_ids: Sequence[int], cap: int = 65536
    ) -> Tuple[int, np.ndarray, bool]:
        """Union wave from ``seed_ids`` with an O(wave) host exchange
        (VERDICT r2 #2): seed IDS travel up (never an O(n) frontier mask),
        the newly-invalidated GLOBAL ids come back compacted into a
        ``cap``-sized buffer, all in one dispatch. Returns (count, newly
        ids, overflow) — on overflow (count > cap) the id buffer is
        partial and the caller falls back to a mask diff."""
        k = len(seed_ids)
        width = 1
        while width < max(k, 1):
            width <<= 1
        # pad = n_global: dropped as OOB by the scatter (-1 would WRAP to
        # the last row and invalidate a padding slot)
        ids = np.full(width, self.n_global, dtype=np.int32)
        ids[:k] = np.asarray(seed_ids, dtype=np.int32)
        key = (cap, width)
        fn = self._collect_cache.get(key)
        if fn is None:
            fn = self._build_collect(cap)
            self._collect_cache[key] = fn
        self.g, count, out_ids, overflow = fn(jnp.asarray(ids), self.g)
        count, out_ids, overflow = jax.device_get((count, out_ids, overflow))
        count = int(count)
        return count, out_ids[:count] if count <= cap else out_ids, bool(overflow)

    def _build_collect(self, cap: int):
        node_sh = self._node_sharding
        n_global = self.n_global
        n_nodes = self.n_nodes
        wave = self._wave

        @jax.jit
        def collect(seed_ids: jax.Array, g: ShardedGraphArrays):
            frontier = lax.with_sharding_constraint(
                jnp.zeros(n_global, bool).at[seed_ids].set(True, mode="drop"),
                node_sh,
            )
            inv_before = g.invalid
            g2, _count = wave(frontier, g)
            # only REAL rows count/compact — padding rows [n_nodes, n_global)
            # exist for the mesh tiling, never for the caller
            newly = (
                g2.invalid
                & ~inv_before
                & (jnp.arange(n_global, dtype=jnp.int32) < n_nodes)
            )
            count = newly.sum(dtype=jnp.int32)
            # global compaction over the sharded mask: XLA lowers the
            # cumsum/scatter to mesh collectives; host traffic stays O(cap)
            pos = jnp.cumsum(newly.astype(jnp.int32)) - 1
            scatter_pos = jnp.where(newly & (pos < cap), pos, cap)
            out = (
                jnp.full(cap, -1, dtype=jnp.int32)
                .at[scatter_pos]
                .set(jnp.arange(n_global, dtype=jnp.int32), mode="drop")
            )
            return g2, count, out, count > cap

        return collect

    def prepare_seed_mat(self, seed_mat: np.ndarray) -> jax.Array:
        """Pad a bool[W, n_nodes] seed matrix to the mesh capacity and
        upload it sharded — call once, outside any timed region."""
        W, n = seed_mat.shape
        if n < self.n_global:
            seed_mat = np.pad(seed_mat, ((0, 0), (0, self.n_global - n)))
        sharding = NamedSharding(self.mesh, P(None, GRAPH_AXIS))
        return jax.device_put(np.asarray(seed_mat, dtype=bool), sharding)

    def run_waves_chained(
        self, seed_mat, reset_between: bool = True
    ) -> Tuple[int, np.ndarray]:
        """Run ``seed_mat.shape[0]`` waves in one compiled program; returns
        (total, per-wave counts). ``seed_mat`` is bool[W, n_nodes-or-global]
        (numpy, uploaded per call) or a device array from
        ``prepare_seed_mat`` (no transfer cost)."""
        if isinstance(seed_mat, np.ndarray):
            seed_mat = self.prepare_seed_mat(seed_mat)
        self.g, total, counts = self._wave_chain(seed_mat, self.g, reset_between)
        return int(total), np.asarray(counts)

    # ------------------------------------------------------------------ readback
    def invalid_mask(self) -> np.ndarray:
        return np.asarray(self.g.invalid)[: self.n_nodes]

    def set_invalid(self, mask: np.ndarray) -> None:
        """Replace the sharded invalid state from a host mask[n_nodes-or-
        global] (the live-mirror sync path: the single-chip dense state is
        authoritative between mesh bursts)."""
        inv = np.zeros(self.n_global, dtype=bool)
        inv[: len(mask)] = np.asarray(mask[: self.n_global], dtype=bool)
        self.g = self.g._replace(
            invalid=jax.device_put(inv, self._node_sharding)
        )

    def clear_invalid(self) -> None:
        self.g = self.g._replace(
            invalid=jax.device_put(np.zeros(self.n_global, dtype=bool), self._node_sharding)
        )
