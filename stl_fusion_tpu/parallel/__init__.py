"""Multi-chip execution: mesh helpers + sharded invalidation waves."""
from .mesh import GRAPH_AXIS, graph_mesh
from .packed_wave import PackedShardedGraph, build_packed_sharded_wave
from .routed_wave import RoutedShardedGraph, build_routed_wave
from .sharded_wave import ShardedDeviceGraph, ShardedGraphArrays, build_sharded_wave

__all__ = [
    "GRAPH_AXIS",
    "graph_mesh",
    "PackedShardedGraph",
    "RoutedShardedGraph",
    "ShardedDeviceGraph",
    "ShardedGraphArrays",
    "build_packed_sharded_wave",
    "build_routed_wave",
    "build_sharded_wave",
]
