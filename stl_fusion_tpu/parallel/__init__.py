"""Multi-chip execution: mesh helpers + sharded invalidation waves."""
from .mesh import GRAPH_AXIS, graph_mesh
from .sharded_wave import ShardedDeviceGraph, ShardedGraphArrays, build_sharded_wave

__all__ = [
    "GRAPH_AXIS",
    "graph_mesh",
    "ShardedDeviceGraph",
    "ShardedGraphArrays",
    "build_sharded_wave",
]
