"""Bit-packed sharded waves — ``32*words`` independent waves per mesh pass.

The multi-chip counterpart of the single-chip pull kernel
(ops/pull_wave.py): node rows block-shard over the mesh's ``graph`` axis,
each row's ≤ k in-edges live beside it (in-ELL with virtual OR-collector
trees bounding fan-in, built by the native packer), and each BFS level is:

  1. ONE ``all_gather`` of the newly-lit frontier WORDS over ICI —
     32 waves ride each uint32 lane, so the per-wave exchange cost is
     1 bit/node/level;
  2. a local row gather + epoch-masked OR-fold (the pull pattern: a row
     pulls from its dependencies, so the scatter-OR that JAX lacks is
     never needed);
  3. ``psum`` of the newly-lit count for the loop-continuation flag.

``words`` packs W uint32 lanes per row — the same transaction-width lever
that took the single-chip topo sweep from 1B to 7.7B inv/s (PERF.md);
``run_wave_batches`` chains batches in one compiled program with a single
readback (per-batch host dispatch pays a relay round trip each).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pull_wave import pack_seed_words
from .mesh import GRAPH_AXIS, graph_mesh, shard_map_compat

__all__ = ["PackedShardedGraph", "build_packed_sharded_wave"]


@functools.lru_cache(maxsize=1)
def _patch_scatter_add():
    @jax.jit
    def f(arr, ids):
        return arr.at[ids].add(1, mode="drop")  # pads index OOB → dropped

    return f


@functools.lru_cache(maxsize=1)
def _fused_patch_apply():
    """ONE dispatch for a whole burst's patches (ISSUE 9 satellite —
    BENCH_r05's 1090.7 ms mirror_patch bill was per-PATCH dispatch
    overhead, not per-edge cost): epoch bumps scatter-add (+1 per
    occurrence, so concatenated bump payloads keep their cumulative
    effect) and spliced rows pair-scatter, all OOB pads dropped."""

    @jax.jit
    def f(nep, in_src, eep, bump_ids, rows, rows_src, rows_ep):
        nep = nep.at[bump_ids].add(1, mode="drop")
        in_src = in_src.at[rows].set(rows_src, mode="drop")
        eep = eep.at[rows].set(rows_ep, mode="drop")
        return nep, in_src, eep

    return f


def build_packed_sharded_wave(mesh: Mesh):
    """Compile the packed sharded kernel for a mesh.

    Returns ``wave(seed_bits, in_src, edge_epoch, node_epoch, is_real,
    invalid) -> (invalid, counts)`` — row-sharded arrays (row count must
    divide evenly over the mesh); seeds/invalid are int32 words
    [rows, W] (32 packed waves per lane); ``counts`` is int32[W] per-word
    (one word's count is ≤ 32·rows, int32-safe — totals are summed in
    int64 host-side). k and W come from array shapes at trace time."""
    node_spec = P(GRAPH_AXIS)
    word_spec = P(GRAPH_AXIS, None)

    @shard_map_compat(
        mesh=mesh,
        in_specs=(word_spec, word_spec, word_spec, node_spec, node_spec, word_spec),
        out_specs=(word_spec, P()),
    )
    def _wave(seeds_l, in_src_l, eepoch_l, nepoch_l, is_real_l, inv_l):
        inv_in_l = inv_l  # counts report only bits newly lit by THIS call
        live = eepoch_l == nepoch_l[:, None]  # dead/pad slots never match
        frontier_l = seeds_l & ~inv_l
        inv_l = inv_l | frontier_l
        go0 = lax.psum((frontier_l != 0).any().astype(jnp.int32), GRAPH_AXIS) > 0

        def cond(carry):
            _f, _inv, go = carry
            return go

        def body(carry):
            f_l, inv_l, _go = carry
            # the ONE collective: newly-lit words, 32 waves per lane
            f_full = lax.all_gather(f_l, GRAPH_AXIS, tiled=True)
            f = f_full[in_src_l]  # (n_local, k, W); pad rows clamp, masked by live
            contrib = jnp.where(live[:, :, None], f, 0)
            fire = contrib[:, 0]
            for j in range(1, contrib.shape[1]):
                fire = fire | contrib[:, j]
            fire = fire & ~inv_l
            inv_l = inv_l | fire
            go = lax.psum((fire != 0).any().astype(jnp.int32), GRAPH_AXIS) > 0
            return fire, inv_l, go

        _f, inv_l, _go = lax.while_loop(cond, body, (frontier_l, inv_l, go0))
        counts = lax.psum(
            lax.population_count(
                jnp.where(is_real_l[:, None], inv_l & ~inv_in_l, 0)
            ).sum(axis=0, dtype=jnp.int32),
            GRAPH_AXIS,
        )
        return inv_l, counts

    @jax.jit
    def wave(seed_bits, in_src, edge_epoch, node_epoch, is_real, invalid):
        return _wave(seed_bits, in_src, edge_epoch, node_epoch, is_real, invalid)

    return wave


def _build_gated_lane_burst(mesh, cap: int, n_global: int, n_nodes: int, words: int):
    """Jitted LIVE lane burst on the mesh (the multi-chip analogue of
    ops/topo_wave.py::topo_mirror_burst_lanes_step): ``32*words``
    independent command groups cascade over the mesh in one pass, gated by
    a RESIDENT blocked mask (the live graph's invalid state) — blocked
    rows neither fire, count, nor conduct, expressed through the kernel's
    own epoch machinery (epoch -3 never matches a live edge's 0).

    Cached PER PackedShardedGraph instance (not a module lru_cache): the
    program's lifetime then matches the graph that owns the mesh, instead
    of pinning discarded meshes process-wide.
    Returns ``burst(seed_ids, in_src, edge_epoch, node_epoch0, is_real,
    blocked) -> (blocked2, lane_counts int32[32*words], union_count,
    compacted union ids, overflow)`` with the union folded back into the
    blocked mask (device-resident between bursts)."""
    wave = build_packed_sharded_wave(mesh)
    W = words
    L = 32 * W
    node_sh = NamedSharding(mesh, P(GRAPH_AXIS))
    word_sh = NamedSharding(mesh, P(GRAPH_AXIS, None))

    @jax.jit
    def burst(seed_ids, in_src, edge_epoch, node_epoch0, is_real, blocked):
        lanes = jnp.arange(L, dtype=jnp.int32)
        word_of = lanes // 32
        bit_of = jnp.left_shift(jnp.int32(1), lanes % 32)
        flat = seed_ids * W + word_of[:, None]  # pad id = n_global → dropped
        vals = jnp.broadcast_to(bit_of[:, None], seed_ids.shape)
        seeds = (
            jnp.zeros(n_global * W, jnp.int32)
            .at[flat.ravel()]
            .add(vals.ravel(), mode="drop")
            .reshape(n_global, W)
        )
        # seeds CONDUCT even when already blocked (r4, the union rule —
        # ops/wave.py::run_waves_union): a blocked row still can't RECEIVE
        # (epoch -3), and the newly mask below excludes pre-blocked rows
        # from counts, union, and writeback
        seeds = lax.with_sharding_constraint(seeds, word_sh)
        node_epoch = lax.with_sharding_constraint(
            jnp.where(blocked, -3, node_epoch0), node_sh
        )
        inv, _word_counts = wave(
            seeds, in_src, edge_epoch, node_epoch, is_real,
            lax.with_sharding_constraint(jnp.zeros_like(seeds), word_sh),
        )
        newly = jnp.where(is_real[:, None] & ~blocked[:, None], inv, 0)
        lane_counts = jnp.stack(
            [
                ((newly[:, w] >> b) & 1).sum(dtype=jnp.int32)
                for w in range(W)
                for b in range(32)
            ]
        )
        union = (newly != 0).any(axis=1) & (
            jnp.arange(n_global, dtype=jnp.int32) < n_nodes
        )
        union_count = union.sum(dtype=jnp.int32)
        pos = jnp.cumsum(union.astype(jnp.int32)) - 1
        scatter_pos = jnp.where(union & (pos < cap), pos, cap)
        ids = (
            jnp.full(cap, -1, dtype=jnp.int32)
            .at[scatter_pos]
            .set(jnp.arange(n_global, dtype=jnp.int32), mode="drop")
        )
        blocked2 = lax.with_sharding_constraint(blocked | union, node_sh)
        return blocked2, lane_counts, union_count, ids, union_count > cap

    return burst


class PackedShardedGraph:
    """Static mesh-sharded graph running ``32*words`` packed waves per pass."""

    def __init__(
        self,
        edges_src: np.ndarray,
        edges_dst: np.ndarray,
        n_nodes: int,
        mesh: Optional[Mesh] = None,
        k: int = 8,
        words: int = 1,
        slack: int = 0,
    ):
        # build_pull_graph = build_ell on reversed edges, which routes
        # through the native packer itself — one packer path to maintain
        from ..ops.ell_wave import widen_ell
        from ..ops.pull_wave import build_pull_graph

        self.mesh = mesh or graph_mesh()
        n_dev = self.mesh.devices.size

        ell = build_pull_graph(edges_src, edges_dst, n_nodes, k=k)
        if slack:
            # guaranteed-free in-slots per row: the LIVE mesh mirror
            # patches structural churn in place (VERDICT r4 #4), and a
            # packed row would break the patch on its first new in-edge
            ell = widen_ell(ell, slack)
        in_src, n_tot = ell.ell_dst, ell.n_tot
        self.n_nodes = n_nodes
        self.n_tot = n_tot
        self.k = ell.k
        self.words = words
        self.patches = 0  # in-place structural patches absorbed
        # pad rows to the mesh grid; pads are inert (epoch -1 slots)
        self.n_local = max(-(-(n_tot + 1) // n_dev), 1)
        self.n_global = self.n_local * n_dev
        if 32 * self.n_global >= 2**31:
            # per-word counts popcount-sum 32 lanes in int32 on device
            # before the psum (jax x64 off); beyond ~67M global rows one
            # word's count could silently wrap — same guard as
            # topo_init_state (ops/topo_wave.py)
            raise ValueError(
                f"packed sharded count tracking is int32-limited to "
                f"<{2**31 // 32} global rows; got {self.n_global} — "
                f"use ShardedDeviceGraph (one wave per pass) at this scale"
            )

        k = self.k
        rows = np.full((self.n_global, k), n_tot, dtype=np.int32)
        rows[: n_tot + 1] = in_src
        edge_epoch = np.full((self.n_global, k), -1, dtype=np.int32)
        edge_epoch[: n_tot + 1][in_src != n_tot] = 0
        node_epoch = np.zeros(self.n_global, dtype=np.int32)
        node_epoch[n_tot:] = -2  # null + pad rows never match any edge epoch
        is_real = np.zeros(self.n_global, dtype=bool)
        is_real[:n_nodes] = True

        sh = NamedSharding(self.mesh, P(GRAPH_AXIS))
        sh2 = NamedSharding(self.mesh, P(GRAPH_AXIS, None))
        self.in_src = jax.device_put(rows, sh2)
        self.edge_epoch = jax.device_put(edge_epoch, sh2)
        self.node_epoch = jax.device_put(node_epoch, sh)
        self.is_real = jax.device_put(is_real, sh)
        # host patch-truth copies (REAL copies — the device_put above may
        # alias the numpy buffers zero-copy on the CPU backend, and these
        # mutate in place during patching)
        self.h_in_src = rows.copy()
        self.h_edge_epoch = edge_epoch.copy()
        self.h_node_epoch = node_epoch.copy()
        self._word_sharding = sh2
        self._zero_words = jax.device_put(
            np.zeros((self.n_global, words), dtype=np.int32), sh2
        )
        self.invalid = self._zero_words
        self._wave = build_packed_sharded_wave(self.mesh)
        self._chain = None  # compiled lazily per batch shape
        self._gated_lanes: dict = {}  # (cap, words) → jitted gated burst

    # ------------------------------------------------------------------ patching
    def patch_bumps(self, node_ids: np.ndarray) -> None:
        """Recomputed nodes (RELATIVE epoch convention: the mesh mirror
        rebases epochs to 0 at build; the owner translates): +1 kills all
        live in-edges of those rows — the mesh pull kernel has NO level
        order, so a bump is just an epoch scatter, never a re-level."""
        ids = np.unique(np.asarray(node_ids, dtype=np.int64))
        if ids.size == 0:
            return
        self.h_node_epoch[ids] += 1
        width = max(256, 1 << int(len(ids) - 1).bit_length())
        padded = np.full(width, self.n_global, dtype=np.int64)  # OOB → drop
        padded[: len(ids)] = ids
        self.node_epoch = _patch_scatter_add()(
            self.node_epoch, jnp.asarray(padded)
        )
        self.patches += 1

    def patch_adds(
        self, u64: np.ndarray, v64: np.ndarray, ep_rel: np.ndarray
    ) -> bool:
        """Splice new in-edges (u → v at RELATIVE captured epoch) into free
        row slots, vectorized like the single-chip mirror's patcher. The
        mesh kernel iterates BFS to fixpoint, so there are no level
        violations — only slot overflow (returns False: caller rebuilds).
        """
        if u64.size == 0:
            return True
        hd, he = self.h_in_src, self.h_edge_epoch
        pad = self.n_tot
        dup = ((hd[v64] == u64[:, None]) & (he[v64] == ep_rel[:, None])).any(axis=1)
        u, v, e = u64[~dup], v64[~dup], ep_rel[~dup]
        if u.size == 0:
            return True
        order = np.lexsort((e, u, v))
        u, v, e = u[order], v[order], e[order]
        first = np.ones(len(u), dtype=bool)
        first[1:] = (v[1:] != v[:-1]) | (u[1:] != u[:-1]) | (e[1:] != e[:-1])
        u, v, e = u[first], v[first], e[first]
        idx = np.arange(len(v))
        grp_start = np.ones(len(v), dtype=bool)
        grp_start[1:] = v[1:] != v[:-1]
        rank = idx - np.maximum.accumulate(np.where(grp_start, idx, 0))
        free_cum = (hd[v] == pad).cumsum(axis=1)
        need = rank + 1
        if (free_cum[:, -1] < need).any():
            return False  # in-row overflow: cheaper to rebuild
        slot = (free_cum == need[:, None]).argmax(axis=1)
        hd[v, slot] = u
        he[v, slot] = e
        rows = np.unique(v)
        width = max(256, 1 << int(len(rows) - 1).bit_length())
        q = np.full(width, self.n_global - 1, dtype=np.int64)
        q[: len(rows)] = rows  # pad rows rewrite their own current contents
        from ..ops.bitops import fused_pair_scatter

        self.in_src, self.edge_epoch = fused_pair_scatter()(
            self.in_src, self.edge_epoch, jnp.asarray(q),
            jnp.asarray(hd[q]), jnp.asarray(he[q]),
        )
        self.patches += 1
        return True

    def patch_batch(
        self, bump_ids: np.ndarray, u64: np.ndarray, v64: np.ndarray, ep_rel: np.ndarray
    ) -> bool:
        """A whole burst's structural patches in ONE fused device dispatch
        (vs one per :meth:`patch_bumps`/:meth:`patch_adds` call — the
        ISSUE 9 amortization satellite). Safe to coalesce because the
        final state is order-independent: bumps are epoch INCREMENTS
        (``bump_ids`` may repeat — each occurrence adds 1) and adds carry
        their captured epochs; dup detection matches the sequential
        path's (within-batch dups collapse exactly like a later call
        seeing the earlier call's splice). Returns False on slot overflow
        or unknown nodes — caller rebuilds, same contract as patch_adds."""
        bump_ids = np.asarray(bump_ids, dtype=np.int64)
        u64 = np.asarray(u64, dtype=np.int64)
        v64 = np.asarray(v64, dtype=np.int64)
        ep_rel = np.asarray(ep_rel, dtype=np.int64)
        n = self.n_nodes
        if bump_ids.size and int(bump_ids.max()) >= self.n_global:
            return False
        if u64.size and (int(u64.max()) >= n or int(v64.max()) >= n):
            return False
        rows = np.empty(0, np.int64)
        hd, he = self.h_in_src, self.h_edge_epoch
        if u64.size:
            pad = self.n_tot
            dup = ((hd[v64] == u64[:, None]) & (he[v64] == ep_rel[:, None])).any(axis=1)
            u, v, e = u64[~dup], v64[~dup], ep_rel[~dup]
            if u.size:
                order = np.lexsort((e, u, v))
                u, v, e = u[order], v[order], e[order]
                first = np.ones(len(u), dtype=bool)
                first[1:] = (v[1:] != v[:-1]) | (u[1:] != u[:-1]) | (e[1:] != e[:-1])
                u, v, e = u[first], v[first], e[first]
                idx = np.arange(len(v))
                grp_start = np.ones(len(v), dtype=bool)
                grp_start[1:] = v[1:] != v[:-1]
                rank = idx - np.maximum.accumulate(np.where(grp_start, idx, 0))
                free_cum = (hd[v] == pad).cumsum(axis=1)
                need = rank + 1
                if (free_cum[:, -1] < need).any():
                    return False  # in-row overflow: cheaper to rebuild
                slot = (free_cum == need[:, None]).argmax(axis=1)
                hd[v, slot] = u
                he[v, slot] = e
                rows = np.unique(v)
        if bump_ids.size:
            uniq, counts = np.unique(bump_ids, return_counts=True)
            live = uniq < self.n_global
            np.add.at(self.h_node_epoch, uniq[live], counts[live].astype(np.int32))
        if not bump_ids.size and not rows.size:
            return True

        def _pad(a, fill):
            w = max(256, 1 << int(max(len(a), 1) - 1).bit_length())
            out = np.full(w, fill, dtype=np.int64)
            out[: len(a)] = a
            return out

        pb = _pad(bump_ids, self.n_global)  # OOB pad → dropped by scatter
        pr = _pad(rows, self.n_global)
        gather_rows = np.minimum(pr, self.n_global - 1)  # values for dropped
        self.node_epoch, self.in_src, self.edge_epoch = _fused_patch_apply()(
            self.node_epoch, self.in_src, self.edge_epoch,
            jnp.asarray(pb), jnp.asarray(pr),
            jnp.asarray(hd[gather_rows]), jnp.asarray(he[gather_rows]),
        )
        self.patches += 1
        return True

    # ------------------------------------------------------------------ waves
    def seeds_to_bits(self, seed_ids_per_wave: Sequence[Sequence[int]]) -> np.ndarray:
        bits = pack_seed_words(self.n_global, seed_ids_per_wave, words=self.words)
        return bits[:, None] if self.words == 1 else bits

    def prepare_seeds(self, seed_ids_per_wave: Sequence[Sequence[int]]):
        """Pack + upload seed words once, outside any timed region."""
        return jax.device_put(self.seeds_to_bits(seed_ids_per_wave), self._word_sharding)

    def run_waves(self, seeds) -> int:
        """Run ≤``32*words`` packed waves; ``seeds`` is a list of per-wave id
        lists or a device array from ``prepare_seeds``. Returns the real
        invalidations NEWLY lit by this call (bits already set in the
        persistent cumulative mask are not re-counted — same semantics as
        ``ShardedDeviceGraph.run_wave``; int64-summed over lanes)."""
        if isinstance(seeds, (list, tuple)):
            seeds = self.prepare_seeds(seeds)
        self.invalid, counts = self._wave(
            seeds, self.in_src, self.edge_epoch, self.node_epoch, self.is_real, self.invalid
        )
        return int(np.asarray(counts, dtype=np.int64).sum())

    def prepare_seed_batches(self, seed_batches: np.ndarray):
        """Upload stacked seed batches [B, n_global, W] sharded — call once,
        outside any timed region."""
        return jax.device_put(
            seed_batches, NamedSharding(self.mesh, P(None, GRAPH_AXIS, None))
        )

    def run_wave_batches(self, seed_batches) -> Tuple[int, np.ndarray]:
        """Chain B batches (each ``32*words`` waves, invalid reset between —
        the bench churn model) in ONE compiled program with a single
        readback. ``seed_batches``: [B, n_global, W] numpy (uploaded per
        call) or a device array from ``prepare_seed_batches``. Returns
        (total, per-batch counts int64[B])."""
        if isinstance(seed_batches, np.ndarray):
            seed_batches = self.prepare_seed_batches(seed_batches)
        if self._chain is None:
            wave = self._wave

            @jax.jit
            def chain(seed_batches, in_src, edge_epoch, node_epoch, is_real, invalid):
                def body(inv, seeds):
                    inv = jnp.zeros_like(inv)
                    inv, counts = wave(seeds, in_src, edge_epoch, node_epoch, is_real, inv)
                    return inv, counts

                inv, counts = lax.scan(body, invalid, seed_batches)
                return inv, counts

            self._chain = chain
        self.invalid, counts = self._chain(
            seed_batches, self.in_src, self.edge_epoch, self.node_epoch,
            self.is_real, self.invalid,
        )
        counts = np.asarray(counts, dtype=np.int64)
        return int(counts.sum()), counts.sum(axis=1)

    def run_gated_lanes(
        self,
        seed_id_lists: Sequence[Sequence[int]],
        blocked,
        cap: int = 65536,
        max_words: int = 16,
    ):
        """INDEPENDENT per-group cascades over the mesh, gated by a
        device-resident ``blocked`` mask (bool[n_global] — the live graph's
        invalid state): the multi-chip face of
        ``DeviceGraph.run_waves_lanes``. Chunks of ≤``32*max_words`` groups
        per dispatch (later chunks see earlier chunks' union as blocked).
        Returns (per-group counts int64[B], union newly ids or None on
        overflow, updated blocked mask, overflow flag).

        Chunk dispatches are SOFTWARE-PIPELINED (ISSUE 7: the mesh burst's
        share of the nonblocking work): chunk ``c+1`` is enqueued — chained
        device-side through the carried blocked mask — before chunk ``c``'s
        results are read back, so the host-side unpack of one chunk
        overlaps the next chunk's collective execution."""
        from ..ops.pull_wave import pack_lane_matrix

        B = len(seed_id_lists)
        counts = np.zeros(B, dtype=np.int64)
        union_parts: list = []
        any_overflow = False
        chunk_size = 32 * max_words
        pending = None  # (device handles, chunk slice) awaiting readback

        def harvest(p) -> None:
            nonlocal any_overflow
            handles, c0_h, n_h = p
            lane_counts, count, ids, overflow = jax.device_get(handles)
            counts[c0_h : c0_h + n_h] = lane_counts[:n_h].astype(np.int64)
            if overflow:
                any_overflow = True
            else:
                union_parts.append(ids[: int(count)])

        for c0 in range(0, B, chunk_size):
            chunk = seed_id_lists[c0 : c0 + chunk_size]
            mat, words = pack_lane_matrix(
                chunk, pad_id=self.n_global, n_valid=self.n_nodes, base_index=c0
            )
            burst = self._gated_lanes.get((cap, words))
            if burst is None:
                burst = _build_gated_lane_burst(
                    self.mesh, cap, self.n_global, self.n_nodes, words
                )
                self._gated_lanes[(cap, words)] = burst
            blocked, lane_counts, count, ids, overflow = burst(
                jnp.asarray(mat), self.in_src, self.edge_epoch, self.node_epoch,
                self.is_real, blocked,
            )
            if pending is not None:
                harvest(pending)
            pending = ((lane_counts, count, ids, overflow), c0, len(chunk))
        if pending is not None:
            harvest(pending)
        union_ids = (
            None
            if any_overflow
            else (
                np.concatenate(union_parts)
                if union_parts
                else np.empty(0, np.int32)
            )
        )
        return counts, union_ids, blocked, any_overflow

    def put_blocked(self, mask: Optional[np.ndarray] = None):
        """The gated-lane blocked mask in ITS layout (bool[n_global],
        GRAPH_AXIS-sharded) from a host mask over [0, n_nodes) — one place
        owns the layout contract (mirror sync + initial state)."""
        padded = np.zeros(self.n_global, dtype=bool)
        if mask is not None:
            padded[: len(mask)] = np.asarray(mask[: self.n_global], dtype=bool)
        return jax.device_put(padded, NamedSharding(self.mesh, P(GRAPH_AXIS)))

    def clear_invalid(self) -> None:
        # a cached device-zero array: no per-clear H2D transfer
        self.invalid = self._zero_words

    def invalid_mask(self, wave: int = 0) -> np.ndarray:
        """bool[n_nodes] for one packed wave lane."""
        w, lane = divmod(wave, 32)
        col = np.asarray(self.invalid[: self.n_nodes, w]).astype(np.int64)
        return (col & (np.int64(1) << lane)) != 0
