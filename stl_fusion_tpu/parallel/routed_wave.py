"""Cluster-routed CSR shards with collective frontier exchange (ISSUE 9).

The unification of the cluster control plane with the mesh path: node rows
live on the device that owns their cluster shard (:class:`~..cluster.
placement.DevicePlacement` — the shard map's device half), edges shard by
DESTINATION owner device, and each BFS level exchanges the invalidation
frontier with mesh collectives instead of surfacing to the host:

- ``exchange="a2a"`` (default, the routed protocol): each device bit-packs
  its newly-lit frontier into uint32 words and sends each consumer device
  ONLY the words that consumer's edges actually reference — static
  per-(producer, consumer) word buckets delivered by one ``lax.all_to_all``
  per level. Exchange volume is O(cut words), not O(n): a frontier bit
  travels only to device shards whose edges need it (the "cluster-routed"
  step PAPER.md's collectives thesis asks for).
- ``exchange="tree"``: the full packed frontier replicates through a
  log2(n_dev)-round recursive-doubling ``ppermute`` reduction tree — the
  Tascade-style merge (PAPERS.md), each round OR-combining block pairs at
  doubling distance; the explicit-tree alternative to ``lax.all_gather``.
- ``exchange="gather"``: plain ``lax.all_gather`` of packed words — the
  reference for equivalence tests.

Per level, after the exchange: local row gather (``node_epoch[dst]`` —
device-local by construction, the reason edges shard by destination),
version-masked fire, local scatter, and a ``psum`` for the continuation
flag. The while_loop carries the flag, so no collective runs in ``cond``.

The **chain faces** (:meth:`RoutedShardedGraph.dispatch_union_chain` /
:meth:`harvest_union_chain`) run K logical waves in ONE ``lax.scan`` with
per-stage compacted newly-id readback — the frontier exchange composed
into the nonblocking loop-carried chain (graph/nonblocking.py rides them
when mesh routing is enabled), so a cross-shard frontier resolves inside
the fused dispatch instead of re-entering through per-key host RPC.

A live reshard MOVES a device shard (:meth:`apply_placement`): the moved
shard's fixed-width row block transfers on-device to its new owner's free
slot, the two affected consumer devices' edge slices + exchange buckets
re-pack host-side, and everything else stays resident. Structural churn
patches route by owner (:meth:`patch_batch` — bumps scatter absolute
epochs, adds splice into per-device slack slots) and apply in ONE fused
dispatch per batch (ISSUE 9 satellite: per-patch dispatch overhead, not
per-edge cost, dominated BENCH_r05's mirror_patch_ms).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..cluster.placement import DevicePlacement, PlacementError
from .mesh import GRAPH_AXIS, graph_mesh, shard_map_compat

__all__ = ["RoutedShardedGraph", "build_routed_wave"]

_EXCHANGES = ("a2a", "tree", "gather")


def build_routed_wave(mesh: Mesh, n_global: int, n_dev: int, exchange: str):
    """Compile the routed union wave for a mesh + geometry. Returns
    ``wave(frontier, send_idx, eslot, ebit, edst, eepoch, nepoch, invalid)
    -> (invalid', count, levels)`` — all arrays GRAPH_AXIS-sharded; seeds
    conduct even when already invalid (the r4 union rule); ``levels`` is
    the number of frontier exchanges the wave ran (the collective-rounds
    telemetry ``fusion_mesh_exchange_levels`` aggregates)."""
    if exchange not in _EXCHANGES:
        raise ValueError(f"unknown exchange {exchange!r}")
    n_local = n_global // n_dev
    assert n_local % 32 == 0
    w_local = n_local // 32
    if exchange == "tree" and (n_dev & (n_dev - 1)):
        raise ValueError("tree exchange needs a power-of-two device count")

    node_spec = P(GRAPH_AXIS)
    edge_spec = P(GRAPH_AXIS)
    send_spec = P(GRAPH_AXIS, None)

    def _pack_words(f_l):
        lanes = jnp.arange(32, dtype=jnp.uint32)[None, :]
        return jnp.sum(
            f_l.reshape(-1, 32).astype(jnp.uint32) << lanes, axis=1, dtype=jnp.uint32
        )

    def _exchange_words(f_l, send_idx_l):
        """One frontier exchange: local packed words → the flat word vector
        the per-edge ``eslot`` indexes into (layout differs per mode)."""
        words = _pack_words(f_l)
        if exchange == "gather":
            return lax.all_gather(words, GRAPH_AXIS, tiled=True)
        if exchange == "a2a":
            words_p = jnp.concatenate([words, jnp.zeros(1, jnp.uint32)])  # pad word
            send = words_p[send_idx_l]  # [n_dev, cap] — bucket per consumer
            recv = lax.all_to_all(
                send, GRAPH_AXIS, split_axis=0, concat_axis=0, tiled=True
            )
            return recv.reshape(-1)  # row p = words from producer p
        # tree: recursive-doubling ppermute — log2(n_dev) OR-merge rounds
        acc = words
        idx = lax.axis_index(GRAPH_AXIS)
        step = 1
        while step < n_dev:
            perm = [(i, i ^ step) for i in range(n_dev)]
            recv = lax.ppermute(acc, GRAPH_AXIS, perm)
            low = (idx & step) == 0  # my block sits in the lower half
            acc = jnp.where(
                low,
                jnp.concatenate([acc, recv]),
                jnp.concatenate([recv, acc]),
            )
            step *= 2
        return acc  # full packed frontier, device order

    @shard_map_compat(
        mesh=mesh,
        in_specs=(
            node_spec, send_spec, edge_spec, edge_spec, edge_spec, edge_spec,
            node_spec, node_spec,
        ),
        out_specs=(node_spec, P(), P()),
    )
    def _wave(seeds_l, send_idx_l, eslot_l, ebit_l, edst_l, eepoch_l, nepoch_l, inv_l):
        fresh = seeds_l & ~inv_l
        inv_l = inv_l | seeds_l
        count0 = lax.psum(fresh.sum(dtype=jnp.int32), GRAPH_AXIS)
        go0 = lax.psum(seeds_l.any().astype(jnp.int32), GRAPH_AXIS) > 0

        def cond(carry):
            return carry[4]

        def body(carry):
            f_l, inv_l, count, levels, _go = carry
            flat = _exchange_words(f_l, send_idx_l)
            word = flat[eslot_l]
            src_active = ((word >> ebit_l.astype(jnp.uint32)) & 1).astype(bool)
            ver_ok = nepoch_l[edst_l] == eepoch_l  # gather clamps; -1 never matches
            fire = src_active & ver_ok & ~inv_l[edst_l]
            nxt_l = jnp.zeros_like(f_l).at[edst_l].max(fire)  # OOB pads dropped
            inv_l = inv_l | nxt_l
            newly = lax.psum(nxt_l.sum(dtype=jnp.int32), GRAPH_AXIS)
            return nxt_l, inv_l, count + newly, levels + 1, newly > 0

        _f, inv_l, count, levels, _go = lax.while_loop(
            cond, body, (seeds_l, inv_l, count0, jnp.int32(0), go0)
        )
        return inv_l, count, levels

    return jax.jit(_wave)


def build_routed_compact(mesh: Mesh, n_global: int, n_dev: int, capd: int):
    """Per-device LOCAL newly-id compaction (ISSUE 9): each device cumsums
    its own shard rows into a ``capd``-sized buffer — no cross-device
    cumsum/scatter (the global compaction was super-linear on the mesh:
    XLA lowered it to collective permutes that dominated the wave itself
    past ~100K rows). Returns ``(counts int32[n_dev], bufs
    int32[n_dev*capd])``; device d's newly GLOBAL rows are
    ``bufs[d*capd : d*capd + counts[d]]``; ``counts[d] > capd`` = that
    device overflowed (caller mask-diffs)."""
    n_local = n_global // n_dev
    node_spec = P(GRAPH_AXIS)

    @shard_map_compat(
        mesh=mesh,
        in_specs=(node_spec, node_spec, node_spec),
        out_specs=(P(GRAPH_AXIS), P(GRAPH_AXIS)),
    )
    def _compact(inv2_l, inv_l, real_l):
        newly_l = inv2_l & ~inv_l & real_l
        count = newly_l.sum(dtype=jnp.int32)
        pos = jnp.cumsum(newly_l.astype(jnp.int32)) - 1
        base = (lax.axis_index(GRAPH_AXIS) * n_local).astype(jnp.int32)
        rows = base + jnp.arange(n_local, dtype=jnp.int32)
        scatter_pos = jnp.where(newly_l & (pos < capd), pos, capd)
        buf = jnp.full(capd, -1, jnp.int32).at[scatter_pos].set(rows, mode="drop")
        return count[None], buf

    return _compact


class RoutedShardedGraph:
    """Mesh-sharded device graph whose layout IS the cluster shard map."""

    def __init__(
        self,
        edges_src: np.ndarray,
        edges_dst: np.ndarray,
        n_nodes: int,
        placement: DevicePlacement,
        mesh: Optional[Mesh] = None,
        exchange: str = "a2a",
        edge_dst_epoch: Optional[np.ndarray] = None,
        node_epoch: Optional[np.ndarray] = None,
        invalid: Optional[np.ndarray] = None,
        bucket_headroom: float = 1.3,
        edge_headroom: float = 1.3,
    ):
        self.mesh = mesh or graph_mesh()
        if self.mesh.devices.size != placement.n_dev:
            raise PlacementError(
                f"placement spans {placement.n_dev} devices, mesh has "
                f"{self.mesh.devices.size}"
            )
        if exchange not in _EXCHANGES:
            raise ValueError(f"unknown exchange {exchange!r}")
        if exchange == "tree" and (placement.n_dev & (placement.n_dev - 1)):
            exchange = "gather"  # tree needs 2^k devices; honest fallback
        self.placement = placement
        self.exchange = exchange
        self.n_nodes = n_nodes
        self.n_dev = placement.n_dev
        self.n_local = placement.n_local
        self.n_global = placement.n_global
        self.w_local = self.n_local // 32
        #: set when a failed in-place reshard left device/host layout
        #: inconsistent — every wave entry point then refuses (rebuild)
        self.broken = False
        # -- telemetry --
        self.waves_run = 0
        self.levels_total = 0  # frontier exchanges (collective rounds)
        self.shard_moves = 0
        self.patches = 0
        self.patch_dispatches = 0

        # int32 host truth: node ids always fit (n_global is int32-bound),
        # and at 240M edges the int64 sorted copies alone were ~5 GB
        src = np.asarray(edges_src, dtype=np.int32)
        dst = np.asarray(edges_dst, dtype=np.int32)
        ep = (
            np.zeros(len(dst), dtype=np.int32)
            if edge_dst_epoch is None
            else np.asarray(edge_dst_epoch, dtype=np.int32)
        )
        # host truth: per-DST-SHARD edge lists (absolute node ids + absolute
        # captured epochs) — the unit a reshard re-partitions by owner
        ips = placement.ids_per_shard
        shard_of_dst = dst.astype(np.int64) // ips
        order = np.argsort(shard_of_dst, kind="stable")
        src, dst, ep, sh = src[order], dst[order], ep[order], shard_of_dst[order]
        self._shard_edges: Dict[int, List[np.ndarray]] = {}
        if len(sh):
            bounds = np.flatnonzero(np.diff(sh)) + 1
            starts = np.concatenate([[0], bounds])
            ends = np.concatenate([bounds, [len(sh)]])
            for a, b in zip(starts, ends):
                self._shard_edges[int(sh[a])] = [src[a:b], dst[a:b], ep[a:b]]

        # capacities sized from the initial partition + headroom
        dev_edges = np.zeros(self.n_dev, dtype=np.int64)
        for s, (es, _ed, _ee) in self._shard_edges.items():
            d = int(placement.shard_dev[s])
            if d >= 0:
                dev_edges[d] += len(es)
        self.e_cap = max(int(dev_edges.max() * edge_headroom) + 32, 64)
        self.bucket_headroom = bucket_headroom
        self._node_sh = NamedSharding(self.mesh, P(GRAPH_AXIS))
        self._edge_sh = NamedSharding(self.mesh, P(GRAPH_AXIS))
        self._send_sh = NamedSharding(self.mesh, P(GRAPH_AXIS, None))

        perm, inv_perm = placement.permutation()
        self.perm, self.inv_perm = perm, inv_perm
        self._real_rows = np.flatnonzero(inv_perm >= 0)
        self._real_nodes = inv_perm[self._real_rows]

        # node state, absolute epochs (no rebase: patches translate nothing)
        nep = np.zeros(self.n_global, dtype=np.int32)
        inv0 = np.zeros(self.n_global, dtype=bool)
        if node_epoch is not None:
            nep[perm[: len(node_epoch)][perm[: len(node_epoch)] >= 0]] = np.asarray(
                node_epoch, dtype=np.int32
            )[perm[: len(node_epoch)] >= 0]
        if invalid is not None:
            m = np.asarray(invalid, dtype=bool)
            rows = perm[: len(m)]
            ok = rows >= 0
            inv0[rows[ok]] = m[ok]
        self._h_is_real = np.zeros(self.n_global, dtype=bool)
        self._h_is_real[self._real_rows] = True

        self._build_exchange_and_edges()
        self.g_node_epoch = jax.device_put(nep, self._node_sh)
        self.g_invalid = jax.device_put(inv0, self._node_sh)
        self.g_is_real = jax.device_put(self._h_is_real, self._node_sh)
        self._wave = build_routed_wave(
            self.mesh, self.n_global, self.n_dev, self.exchange
        )
        self._collect_cache: dict = {}
        self._chain_cache: dict = {}
        self._patch_cache: dict = {}
        self._move_cache: dict = {}

    # ------------------------------------------------------------------ build
    def _consumer_pack(self, d: int):
        """Pack consumer device ``d``'s edge slice + its word buckets from
        the host per-shard edge lists. Returns (eslot, ebit, edst, eep,
        buckets) where buckets[p] = local word indices producer p sends d.
        ``eslot`` uses the exchange's layout (a2a: p*cap+j; tree/gather:
        global word id)."""
        pl = self.placement
        srcs: List[np.ndarray] = []
        dsts: List[np.ndarray] = []
        eps: List[np.ndarray] = []
        for s in range(pl.shard_map.n_shards):
            if int(pl.shard_dev[s]) != d:
                continue
            ent = self._shard_edges.get(s)
            if ent is None:
                continue
            srcs.append(ent[0])
            dsts.append(ent[1])
            eps.append(ent[2])
        if srcs:
            src = np.concatenate(srcs)
            dst = np.concatenate(dsts)
            ep = np.concatenate(eps)
        else:
            src = dst = np.empty(0, np.int64)
            ep = np.empty(0, np.int32)
        if len(src) > self.e_cap:
            raise PlacementError(
                f"device {d} edge slice {len(src)} exceeds capacity {self.e_cap}"
            )
        src_rows = self.perm[src] if len(src) else src
        dst_rows = self.perm[dst] if len(dst) else dst
        if len(src) and (src_rows.min() < 0 or dst_rows.min() < 0):
            raise PlacementError("edge endpoints land on off-mesh shards")
        words = src_rows >> 5
        buckets: Dict[int, np.ndarray] = {}
        eslot = np.zeros(self.e_cap, dtype=np.int32)
        ebit = np.zeros(self.e_cap, dtype=np.int32)
        edst = np.full(self.e_cap, self.n_local, dtype=np.int32)  # pad: dropped
        eep = np.full(self.e_cap, -1, dtype=np.int32)  # pad: never matches
        if self.exchange == "a2a":
            prod = (src_rows // self.n_local).astype(np.int64)
            slots = np.empty(len(src), dtype=np.int64)
            for p in range(self.n_dev):
                sel = prod == p
                if not sel.any():
                    buckets[p] = np.empty(0, np.int64)
                    continue
                wl = words[sel] - p * self.w_local
                uniq = np.unique(wl)
                buckets[p] = uniq
                slots[sel] = np.searchsorted(uniq, wl)
            # sorted build-time buckets: slot lookup at patch time is a
            # searchsorted, never a V×words Python dict (100M-node scale)
            self._buckets[d] = buckets
            self._patch_slots[d] = {}
            self._bucket_fill[d] = {p: len(b) for p, b in buckets.items()}
            if len(src):
                # final eslot needs bucket_cap (p*cap + j) — filled by the
                # caller once the global cap is known; stash raw (p, j)
                eslot_raw = (prod, slots)
            else:
                eslot_raw = (np.empty(0, np.int64), np.empty(0, np.int64))
        else:
            eslot_raw = None
            if len(src):
                eslot[: len(src)] = words.astype(np.int32)
        if len(src):
            ebit[: len(src)] = (src_rows & 31).astype(np.int32)
            edst[: len(src)] = (dst_rows - d * self.n_local).astype(np.int32)
            eep[: len(src)] = ep
        self._dev_edge_count[d] = len(src)
        return eslot, ebit, edst, eep, buckets, eslot_raw, len(src)

    def _build_exchange_and_edges(self) -> None:
        """(Re)build the full host-side edge partition + exchange tables and
        upload. Called at construction and on a rebuild-grade change."""
        n_dev = self.n_dev
        #: consumer dev → {producer dev → sorted build-time word bucket}
        self._buckets: Dict[int, Dict[int, np.ndarray]] = {}
        #: consumer dev → {(producer, word) → slot} for PATCH-added words
        #: only (build-time slots resolve by searchsorted in _buckets)
        self._patch_slots: Dict[int, Dict[Tuple[int, int], int]] = {}
        self._bucket_fill: Dict[int, Dict[int, int]] = {}
        self._dev_edge_count = np.zeros(n_dev, dtype=np.int64)
        packs = [self._consumer_pack(d) for d in range(n_dev)]
        if self.exchange == "a2a":
            peak = max(
                (max(f.values(), default=0) for f in (self._bucket_fill[d] for d in range(n_dev))),
                default=0,
            )
            self.bucket_cap = max(int(peak * self.bucket_headroom) + 8, 16)
            send = np.full((n_dev, n_dev, self.bucket_cap), self.w_local, np.int32)
            for d in range(n_dev):
                eslot, ebit, edst, eep, buckets, (prod, slots), n_e = packs[d]
                for p, wl in buckets.items():
                    send[p, d, : len(wl)] = wl
                if n_e:
                    eslot[:n_e] = (prod * self.bucket_cap + slots).astype(np.int32)
            self._h_send = send.reshape(n_dev * n_dev, self.bucket_cap)
        else:
            self.bucket_cap = 16  # unused; kernel signature stays uniform
            self._h_send = np.zeros((n_dev * n_dev, self.bucket_cap), np.int32)
        self._h_eslot = np.concatenate([p[0] for p in packs])
        self._h_ebit = np.concatenate([p[1] for p in packs])
        self._h_edst = np.concatenate([p[2] for p in packs])
        self._h_eep = np.concatenate([p[3] for p in packs])
        self._upload_edges()

    def _upload_edges(self) -> None:
        self.g_send = jax.device_put(self._h_send, self._send_sh)
        self.g_eslot = jax.device_put(self._h_eslot, self._edge_sh)
        self.g_ebit = jax.device_put(self._h_ebit, self._edge_sh)
        self.g_edst = jax.device_put(self._h_edst, self._edge_sh)
        self.g_eep = jax.device_put(self._h_eep, self._edge_sh)

    # ------------------------------------------------------------------ waves
    def run_wave_collect(
        self, seed_node_ids: Sequence[int], cap: int = 65536
    ) -> Tuple[int, np.ndarray, bool]:
        """Union wave from node ids with O(wave) host exchange: seed ids up,
        compacted newly NODE ids back, one dispatch. Returns (count, newly
        node ids, overflow)."""
        self._check_usable()
        k = len(seed_node_ids)
        width = 1
        while width < max(k, 1):
            width <<= 1
        rows = np.full(width, self.n_global, dtype=np.int64)  # pad: dropped
        if k:
            r = self.perm[np.asarray(seed_node_ids, dtype=np.int64)]
            if r.min() < 0:
                raise PlacementError("seed node lands on an off-mesh shard")
            rows[:k] = r
        capd = max(cap // self.n_dev, 1024)
        fn = self._collect_cache.get((capd, width))
        if fn is None:
            fn = self._build_collect(capd)
            self._collect_cache[(capd, width)] = fn
        self.g_invalid, counts, levels, bufs = fn(
            jnp.asarray(rows), self.g_send, self.g_eslot, self.g_ebit,
            self.g_edst, self.g_eep, self.g_node_epoch, self.g_invalid,
            self.g_is_real,
        )
        counts, levels, bufs = jax.device_get((counts, levels, bufs))
        self.waves_run += 1
        self.levels_total += int(levels)
        count = int(counts.sum())
        if (counts > capd).any():
            return count, np.empty(0, np.int64), True
        ids = np.concatenate(
            [bufs[d * capd : d * capd + int(counts[d])] for d in range(self.n_dev)]
        )
        return count, self.inv_perm[ids], False

    def _build_collect(self, capd: int):
        wave = self._wave
        compact = build_routed_compact(self.mesh, self.n_global, self.n_dev, capd)
        node_sh = self._node_sh
        n_global = self.n_global

        @jax.jit
        def collect(seed_rows, send, eslot, ebit, edst, eep, nepoch, inv, is_real):
            frontier = lax.with_sharding_constraint(
                jnp.zeros(n_global, bool).at[seed_rows].set(True, mode="drop"),
                node_sh,
            )
            inv2, _count, levels = wave(
                frontier, send, eslot, ebit, edst, eep, nepoch, inv
            )
            counts, bufs = compact(inv2, inv, is_real)
            return inv2, counts, levels, bufs

        return collect

    # ------------------------------------------------------------------ chain
    def stage_union_chain(
        self, stage_seed_lists: Sequence[Sequence[int]], cap: int = 65536
    ) -> dict:
        """Host-side pack of a union chain's seed tensor — the super-round
        BACK BUFFER (ISSUE 14): perm-map and pad WITHOUT dispatching, so
        the pack runs while the previous chain executes on device. The
        staged dict carries a (graph identity, placement epoch) token;
        :meth:`dispatch_union_chain` refuses a buffer staged against a
        permutation a reshard/rebuild has since retired (PlacementError —
        the caller re-stages, counted, never silently dispatches stale
        row ids)."""
        K = len(stage_seed_lists)
        if K == 0:
            raise ValueError("empty chain")
        width = 1
        kmax = max((len(s) for s in stage_seed_lists), default=1)
        while width < max(kmax, 1):
            width <<= 1
        mat = np.full((K, width), self.n_global, dtype=np.int64)
        for i, seeds in enumerate(stage_seed_lists):
            if seeds:
                r = self.perm[np.asarray(seeds, dtype=np.int64)]
                if r.min() < 0:
                    raise PlacementError("seed node lands on an off-mesh shard")
                mat[i, : len(seeds)] = r
        capd = max(cap // self.n_dev, 1024)
        return {
            "mat": mat, "stages": K, "width": width, "capd": capd,
            "token": (id(self), self.placement.epoch),
        }

    def dispatch_union_chain(
        self,
        stage_seed_lists: Optional[Sequence[Sequence[int]]] = None,
        cap: int = 65536,
        staged: Optional[dict] = None,
    ) -> dict:
        """K logical union waves in ONE lax.scan dispatch, NO readback:
        stage i cascades against the invalid state stages < i left (each
        result equals a sequential per-stage dispatch). ``staged`` (from
        :meth:`stage_union_chain`) skips the host pack — the double-
        buffered super-round path. Returns a pending ticket for
        :meth:`harvest_union_chain`; the device invalid state advances
        immediately (futures)."""
        self._check_usable()
        if staged is None:
            staged = self.stage_union_chain(stage_seed_lists, cap)
        elif staged["token"] != (id(self), self.placement.epoch):
            raise PlacementError(
                "staged seed buffer predates a reshard/rebuild — re-stage"
            )
        K, width, capd = staged["stages"], staged["width"], staged["capd"]
        mat = staged["mat"]
        fn = self._chain_cache.get((K, width, capd))
        if fn is None:
            fn = self._build_chain(capd)
            self._chain_cache[(K, width, capd)] = fn
        self.g_invalid, counts, levels, bufs = fn(
            jnp.asarray(mat), self.g_send, self.g_eslot, self.g_ebit,
            self.g_edst, self.g_eep, self.g_node_epoch, self.g_invalid,
            self.g_is_real,
        )
        return {"counts": counts, "levels": levels, "bufs": bufs,
                "stages": K, "capd": capd, "dispatches": 1}

    def _build_chain(self, capd: int):
        wave = self._wave
        compact = build_routed_compact(self.mesh, self.n_global, self.n_dev, capd)
        node_sh = self._node_sh
        n_global = self.n_global

        @jax.jit
        def chain(seed_mat, send, eslot, ebit, edst, eep, nepoch, inv0, is_real):
            def body(inv, seed_rows):
                frontier = lax.with_sharding_constraint(
                    jnp.zeros(n_global, bool).at[seed_rows].set(True, mode="drop"),
                    node_sh,
                )
                inv2, _c, levels = wave(
                    frontier, send, eslot, ebit, edst, eep, nepoch, inv
                )
                counts, bufs = compact(inv2, inv, is_real)
                return inv2, (counts, levels, bufs)

            inv, (counts, levels, bufs) = lax.scan(body, inv0, seed_mat)
            return inv, counts, levels, bufs

        return chain

    def harvest_union_chain(self, pending: dict) -> Tuple[np.ndarray, List[np.ndarray], dict]:
        """Block on a chain ticket: (per-stage counts, per-stage newly NODE
        id arrays, info). An overflowed stage returns ``None`` in its slot —
        the caller mask-diffs against its dense mirror."""
        counts_dev, levels, bufs = jax.device_get(
            (pending["counts"], pending["levels"], pending["bufs"])
        )
        capd = pending["capd"]
        self.waves_run += pending["stages"]
        self.levels_total += int(levels.sum())
        counts = counts_dev.astype(np.int64).sum(axis=1)
        stage_ids: List[Optional[np.ndarray]] = []
        overflowed = False
        for i in range(pending["stages"]):
            if (counts_dev[i] > capd).any():
                stage_ids.append(None)
                overflowed = True
            else:
                stage_ids.append(
                    self.inv_perm[
                        np.concatenate(
                            [
                                bufs[i, d * capd : d * capd + int(counts_dev[i, d])]
                                for d in range(self.n_dev)
                            ]
                        )
                    ]
                )
        info = {"levels": levels.astype(np.int64), "overflowed": overflowed}
        return counts, stage_ids, info

    # ------------------------------------------------------------------ state
    def invalid_mask(self) -> np.ndarray:
        """bool[n_nodes] in NODE space (reads the device state once)."""
        arr = np.asarray(self.g_invalid)
        out = np.zeros(self.n_nodes, dtype=bool)
        out[self._real_nodes] = arr[self._real_rows]
        return out

    def set_invalid(self, mask: np.ndarray) -> None:
        inv = np.zeros(self.n_global, dtype=bool)
        m = np.asarray(mask[: self.n_nodes], dtype=bool)
        rows = self.perm[: len(m)]
        ok = rows >= 0
        inv[rows[ok]] = m[ok]
        self.g_invalid = jax.device_put(inv, self._node_sh)

    def clear_invalid(self) -> None:
        self.g_invalid = jax.device_put(
            np.zeros(self.n_global, dtype=bool), self._node_sh
        )

    # ------------------------------------------------------------------ reshard
    def apply_placement(self, new_placement: DevicePlacement, moves) -> None:
        """MOVE the listed device shards to their new owners: each moved
        shard's fixed-width row block transfers on-device (one fused
        gather/scatter dispatch for node state), and the affected consumer
        devices' edge slices + exchange buckets re-pack — affected means
        the old/new OWNER devices plus every consumer whose edges SOURCE
        from a moved shard (their eslot/bucket routes reference the
        vacated rows; missing them loses invalidations silently — caught
        in review with a single-shard-move repro). State for unmoved
        shards never leaves its device. Raises :class:`PlacementError` on
        slot/edge-capacity overflow, after which the graph is BROKEN
        (every wave entry point refuses) — the caller rebuilds."""
        if not moves:
            self.placement = new_placement
            return
        old_rows_l: List[np.ndarray] = []
        new_rows_l: List[np.ndarray] = []
        affected_devs: set = set()
        ips = self.placement.ids_per_shard
        for s, old_dev, new_dev in moves:
            if old_dev >= 0:
                affected_devs.add(old_dev)
            if new_dev >= 0:
                affected_devs.add(new_dev)
            if old_dev < 0 or new_dev < 0:
                # shard entering/leaving the mesh changes real-row coverage:
                # that is a rebuild-grade change, not an in-place move
                raise PlacementError(f"shard {s} crossed the mesh boundary")
            base_old = old_dev * self.n_local + int(self.placement.shard_slot[s]) * self.placement.slot_rows
            base_new = new_dev * self.n_local + int(new_placement.shard_slot[s]) * new_placement.slot_rows
            n = min(ips, self.n_nodes - s * ips)
            if n <= 0:
                continue
            old_rows_l.append(np.arange(base_old, base_old + n, dtype=np.int64))
            new_rows_l.append(np.arange(base_new, base_new + n, dtype=np.int64))
        # consumers whose edge SOURCES moved: their exchange routes (a2a
        # buckets / global word slots) point at the old rows
        moved_shards = np.fromiter((m[0] for m in moves), dtype=np.int64)
        for shard, ent in self._shard_edges.items():
            d = int(new_placement.shard_dev[shard])
            if d < 0 or d in affected_devs:
                continue
            if len(ent[0]) and np.isin(ent[0] // ips, moved_shards).any():
                affected_devs.add(d)
        self.placement = new_placement
        self.perm, self.inv_perm = new_placement.permutation()
        self._real_rows = np.flatnonzero(self.inv_perm >= 0)
        self._real_nodes = self.inv_perm[self._real_rows]
        self._h_is_real = np.zeros(self.n_global, dtype=bool)
        self._h_is_real[self._real_rows] = True
        self.g_is_real = jax.device_put(self._h_is_real, self._node_sh)
        if old_rows_l:
            old_rows = np.concatenate(old_rows_l)
            new_rows = np.concatenate(new_rows_l)
            width = 1 << int(len(old_rows) - 1).bit_length()
            po = np.full(width, self.n_global, dtype=np.int64)
            pn = np.full(width, self.n_global, dtype=np.int64)
            po[: len(old_rows)] = old_rows
            pn[: len(new_rows)] = new_rows
            fn = self._move_cache.get(width)
            if fn is None:
                fn = self._build_move()
                self._move_cache[width] = fn
            self.g_node_epoch, self.g_invalid = fn(
                self.g_node_epoch, self.g_invalid, jnp.asarray(po), jnp.asarray(pn)
            )
        # re-pack edges + buckets for the touched consumer devices only
        try:
            self._repack_devices(sorted(affected_devs))
        except PlacementError:
            # the state blocks already moved and some devices may be half
            # repacked — a partial rollback would LOOK usable while being
            # wrong (review finding). Mark broken; every wave entry point
            # refuses until the caller rebuilds.
            self.broken = True
            raise
        self.shard_moves += len(moves)

    def _build_move(self):
        node_sh = self._node_sh

        @jax.jit
        def move(ep, inv, old_rows, new_rows):
            mep = ep.at[old_rows].get(mode="fill", fill_value=0)
            minv = inv.at[old_rows].get(mode="fill", fill_value=False)
            ep = ep.at[old_rows].set(0, mode="drop").at[new_rows].set(mep, mode="drop")
            inv = (
                inv.at[old_rows].set(False, mode="drop")
                .at[new_rows].set(minv, mode="drop")
            )
            return (
                lax.with_sharding_constraint(ep, node_sh),
                lax.with_sharding_constraint(inv, node_sh),
            )

        return move

    def _repack_devices(self, devs: Sequence[int]) -> None:
        """Host-side re-pack of the listed consumer devices' edge slices and
        (a2a) their bucket columns from every producer, then one upload per
        touched array slice."""
        packs = {d: self._consumer_pack(d) for d in devs}
        if self.exchange == "a2a":
            for d, (eslot, ebit, edst, eep, buckets, raw, n_e) in packs.items():
                for p, wl in buckets.items():
                    col = np.full(self.bucket_cap, self.w_local, np.int32)
                    if len(wl) > self.bucket_cap:
                        raise PlacementError(
                            f"bucket ({p}->{d}) {len(wl)} exceeds cap {self.bucket_cap}"
                        )
                    col[: len(wl)] = wl
                    self._h_send[p * self.n_dev + d] = col
                if n_e:
                    prod, slots = raw
                    eslot[:n_e] = (prod * self.bucket_cap + slots).astype(np.int32)
        for d, (eslot, ebit, edst, eep, _b, _raw, _n) in packs.items():
            sl = slice(d * self.e_cap, (d + 1) * self.e_cap)
            self._h_eslot[sl] = eslot
            self._h_ebit[sl] = ebit
            self._h_edst[sl] = edst
            self._h_eep[sl] = eep
        self._upload_edges()

    # ------------------------------------------------------------------ patches
    def patch_batch(
        self,
        bump_ids: np.ndarray,
        add_u: np.ndarray,
        add_v: np.ndarray,
        add_ep: np.ndarray,
    ) -> bool:
        """Apply a WHOLE burst's structural patches in one fused device
        dispatch (the ISSUE 9 amortization satellite): epoch bumps
        scatter-add (+k for k bumps of one row — final state is
        order-independent because bumps are increments and adds carry
        absolute captured epochs), new edges splice into per-device slack
        slots routed by their destination's OWNER. Returns False on any
        capacity overflow (caller rebuilds)."""
        self._check_usable()
        bump_rows = np.empty(0, np.int64)
        bump_counts = np.empty(0, np.int32)
        if len(bump_ids):
            ids = np.asarray(bump_ids, dtype=np.int64)
            uniq, counts = np.unique(ids, return_counts=True)
            rows = self.perm[uniq]
            if rows.min() < 0:
                return False
            bump_rows, bump_counts = rows, counts.astype(np.int32)
            # host truth for future repacks: nothing — node epochs live only
            # on device + dense mirror; shard edge lists carry captured
            # epochs, which bumps do not rewrite
        e_rows = np.empty(0, np.int64)
        e_slot = np.empty(0, np.int32)
        e_bit = np.empty(0, np.int32)
        e_dst = np.empty(0, np.int32)
        e_ep = np.empty(0, np.int32)
        s_rows = np.empty(0, np.int64)
        s_vals = np.empty(0, np.int32)
        if len(add_u):
            u = np.asarray(add_u, dtype=np.int64)
            v = np.asarray(add_v, dtype=np.int64)
            ep = np.asarray(add_ep, dtype=np.int32)
            if (u >= self.n_nodes).any() or (v >= self.n_nodes).any():
                return False  # nodes born after the build: rebuild
            ips = self.placement.ids_per_shard
            u_rows = self.perm[u]
            v_rows = self.perm[v]
            if len(u_rows) and (u_rows.min() < 0 or v_rows.min() < 0):
                return False
            shards = v // ips
            devs = (v_rows // self.n_local).astype(np.int64)
            er, es, eb, ed, ee, sr, sv = [], [], [], [], [], [], []
            for d in np.unique(devs).tolist():
                sel = devs == d
                k = int(sel.sum())
                base = int(self._dev_edge_count[d])
                if base + k > self.e_cap:
                    return False  # edge slack exhausted
                self._dev_edge_count[d] = base + k
                rows = d * self.e_cap + base + np.arange(k, dtype=np.int64)
                ur, vr = u_rows[sel], v_rows[sel]
                er.append(rows)
                eb.append((ur & 31).astype(np.int32))
                ed.append((vr - d * self.n_local).astype(np.int32))
                ee.append(ep[sel])
                if self.exchange == "a2a":
                    prod = (ur // self.n_local).astype(np.int64)
                    wl = (ur >> 5) - prod * self.w_local
                    built = self._buckets[d]
                    patch_slots = self._patch_slots[d]
                    fill = self._bucket_fill[d]
                    slots = np.empty(k, dtype=np.int64)
                    for i, (p, w) in enumerate(zip(prod.tolist(), wl.tolist())):
                        bucket = built.get(p)
                        j = None
                        if bucket is not None and len(bucket):
                            pos = int(np.searchsorted(bucket, w))
                            if pos < len(bucket) and bucket[pos] == w:
                                j = pos
                        if j is None:
                            j = patch_slots.get((p, w))
                        if j is None:
                            j = fill.get(p, 0)
                            if j >= self.bucket_cap:
                                return False  # bucket slack exhausted
                            patch_slots[(p, w)] = j
                            fill[p] = j + 1
                            sr.append(np.array([(p * self.n_dev + d) * self.bucket_cap + j]))
                            sv.append(np.array([w], dtype=np.int32))
                            self._h_send[p * self.n_dev + d, j] = w
                        slots[i] = j
                    es.append((prod * self.bucket_cap + slots).astype(np.int32))
                else:
                    es.append(((ur >> 5)).astype(np.int32))
                # host truth for future repacks
                for s in np.unique(shards[sel]).tolist():
                    ss = sel & (shards == s)
                    ent = self._shard_edges.setdefault(
                        int(s),
                        [np.empty(0, np.int64), np.empty(0, np.int64), np.empty(0, np.int32)],
                    )
                    ent[0] = np.concatenate([ent[0], u[ss]])
                    ent[1] = np.concatenate([ent[1], v[ss]])
                    ent[2] = np.concatenate([ent[2], ep[ss]])
                # mirror into host edge arrays
                self._h_eslot[rows] = es[-1]
                self._h_ebit[rows] = eb[-1]
                self._h_edst[rows] = ed[-1]
                self._h_eep[rows] = ee[-1]
            e_rows = np.concatenate(er) if er else e_rows
            e_slot = np.concatenate(es) if es else e_slot
            e_bit = np.concatenate(eb) if eb else e_bit
            e_dst = np.concatenate(ed) if ed else e_dst
            e_ep = np.concatenate(ee) if ee else e_ep
            if sr:
                s_rows = np.concatenate(sr)
                s_vals = np.concatenate(sv)
        if not len(bump_rows) and not len(e_rows):
            return True
        # ONE fused dispatch for the whole batch — pad each index family to
        # a pow2 width (OOB pads dropped) so program shapes cache
        def _pad(a, fill, dtype=np.int64):
            w = max(64, 1 << int(max(len(a), 1) - 1).bit_length())
            out = np.full(w, fill, dtype=dtype)
            out[: len(a)] = a
            return out

        pb = _pad(bump_rows, self.n_global)
        pbc = _pad(bump_counts, 0, np.int32)
        pe = _pad(e_rows, self.n_dev * self.e_cap)
        pes = _pad(e_slot, 0, np.int32)
        peb = _pad(e_bit, 0, np.int32)
        ped = _pad(e_dst, self.n_local, np.int32)
        pee = _pad(e_ep, -1, np.int32)
        ps = _pad(s_rows, self.n_dev * self.n_dev * self.bucket_cap)
        psv = _pad(s_vals, self.w_local, np.int32)
        key = (len(pb), len(pe), len(ps))
        fn = self._patch_cache.get(key)
        if fn is None:
            fn = self._build_patch()
            self._patch_cache[key] = fn
        (
            self.g_node_epoch, self.g_eslot, self.g_ebit, self.g_edst,
            self.g_eep, self.g_send,
        ) = fn(
            self.g_node_epoch, self.g_eslot, self.g_ebit, self.g_edst,
            self.g_eep, self.g_send,
            jnp.asarray(pb), jnp.asarray(pbc), jnp.asarray(pe),
            jnp.asarray(pes), jnp.asarray(peb), jnp.asarray(ped),
            jnp.asarray(pee), jnp.asarray(ps), jnp.asarray(psv),
        )
        self.patches += 1
        self.patch_dispatches += 1
        return True

    def _build_patch(self):
        node_sh, edge_sh, send_sh = self._node_sh, self._edge_sh, self._send_sh
        cap = self.bucket_cap

        @jax.jit
        def patch(nep, eslot, ebit, edst, eep, send,
                  b_rows, b_counts, e_rows, e_slot, e_bit, e_dst, e_ep,
                  s_rows, s_vals):
            nep = nep.at[b_rows].add(b_counts, mode="drop")
            eslot = eslot.at[e_rows].set(e_slot, mode="drop")
            ebit = ebit.at[e_rows].set(e_bit, mode="drop")
            edst = edst.at[e_rows].set(e_dst, mode="drop")
            eep = eep.at[e_rows].set(e_ep, mode="drop")
            flat = send.reshape(-1).at[s_rows].set(s_vals, mode="drop")
            return (
                lax.with_sharding_constraint(nep, node_sh),
                lax.with_sharding_constraint(eslot, edge_sh),
                lax.with_sharding_constraint(ebit, edge_sh),
                lax.with_sharding_constraint(edst, edge_sh),
                lax.with_sharding_constraint(eep, edge_sh),
                lax.with_sharding_constraint(flat.reshape(send.shape), send_sh),
            )

        return patch

    # ------------------------------------------------------------------ snapshots
    def export_shard_state(self) -> dict:
        """Per-device-shard node state keyed by VIRTUAL SHARD id (the unit
        that survives a reshard): checkpoint/durable.py stores this so a
        warm restart re-pins each shard under whatever placement the
        restarting process derives — layout-independent by construction."""
        ep = np.asarray(self.g_node_epoch)
        inv = np.asarray(self.g_invalid)
        pl = self.placement
        shards: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        for s in range(pl.shard_map.n_shards):
            if pl.shard_dev[s] < 0:
                continue
            lo = s * pl.ids_per_shard
            n = min(pl.ids_per_shard, self.n_nodes - lo)
            if n <= 0:
                continue
            base = pl.row_of_shard(s)
            shards[s] = (ep[base : base + n].copy(), inv[base : base + n].copy())
        return {
            "epoch": pl.epoch,
            "n_nodes": self.n_nodes,
            "n_shards": pl.shard_map.n_shards,
            "shards": shards,
        }

    def import_shard_state(self, snap: dict) -> int:
        """Re-pin snapshotted shard states under THIS graph's placement.
        Returns the number of shards restored (shards the snapshot lacks
        keep their built state)."""
        pl = self.placement
        if snap.get("n_nodes") != self.n_nodes or snap.get("n_shards") != pl.shard_map.n_shards:
            # shard keying is only meaningful under the SAME (n_nodes, V)
            # geometry — ids_per_shard derives from both, and restoring a
            # wider snapshot would write past a shard's slot into its
            # neighbour's rows (silent cross-shard corruption). Refuse.
            raise ValueError(
                f"mesh shard snapshot geometry (n_nodes={snap.get('n_nodes')}, "
                f"n_shards={snap.get('n_shards')}) does not match this graph "
                f"({self.n_nodes}, {pl.shard_map.n_shards}); cold-build instead"
            )
        ep = np.asarray(self.g_node_epoch).copy()
        inv = np.asarray(self.g_invalid).copy()
        restored = 0
        for s, (sep, sinv) in snap["shards"].items():
            s = int(s)
            if s >= pl.shard_map.n_shards or pl.shard_dev[s] < 0:
                continue
            base = pl.row_of_shard(s)
            # belt on top of the geometry check: never write past the
            # shard's real-id extent
            n = min(len(sep), max(self.n_nodes - s * pl.ids_per_shard, 0), pl.slot_rows)
            ep[base : base + n] = sep[:n]
            inv[base : base + n] = sinv[:n]
            restored += 1
        self.g_node_epoch = jax.device_put(ep, self._node_sh)
        self.g_invalid = jax.device_put(inv, self._node_sh)
        return restored

    def _check_usable(self) -> None:
        if self.broken:
            raise PlacementError(
                "routed graph broken by a failed in-place reshard; rebuild"
            )

    # ------------------------------------------------------------------ stats
    def stats(self) -> dict:
        return {
            "exchange": self.exchange,
            "n_dev": self.n_dev,
            "n_nodes": self.n_nodes,
            "n_global": self.n_global,
            "e_cap": self.e_cap,
            "bucket_cap": self.bucket_cap,
            "placement_epoch": self.placement.epoch,
            "waves_run": self.waves_run,
            "exchange_levels_total": self.levels_total,
            "shard_moves": self.shard_moves,
            "patches": self.patches,
            "patch_dispatches": self.patch_dispatches,
        }
