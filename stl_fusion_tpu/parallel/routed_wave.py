"""Cluster-routed CSR shards with collective frontier exchange (ISSUE 9 + 15).

The unification of the cluster control plane with the mesh path: node rows
live on the device that owns their cluster shard (:class:`~..cluster.
placement.DevicePlacement` — the shard map's device half), edges shard by
DESTINATION owner device, and each BFS level exchanges the invalidation
frontier with mesh collectives instead of surfacing to the host:

- ``exchange="a2a"`` (single-host default): each device bit-packs its
  newly-lit frontier into uint32 words and sends each consumer device
  ONLY the words that consumer's edges actually reference — static
  per-(producer, consumer) word buckets delivered by one ``lax.all_to_all``
  per level. Exchange volume is O(cut words), not O(n): a frontier bit
  travels only to device shards whose edges need it (the "cluster-routed"
  step PAPER.md's collectives thesis asks for).
- ``exchange="hier"`` (ISSUE 15, the multi-host protocol): each level
  resolves in TWO stages over a 2-D ``(host, ldev)`` mesh — an intra-host
  packed-word a2a over the local device group (the ICI leg: same bucket
  routing as ``a2a``, restricted to same-host pairs), then an inter-host
  exchange of the REDUCED per-host frontier words: every device gathers
  its owned words of the per-(producer-host, consumer-host) buckets, the
  host group OR-assembles them in log2(dph) ``ppermute`` rounds, and the
  assembled host payloads travel a recursive-doubling ``ppermute`` tree
  across hosts (log2(n_hosts) rounds — the Tascade reduction-tree shape,
  PAPERS.md #1). Only bucket words cross the host boundary (the DCN leg),
  and the whole two-stage exchange stays INSIDE the fused wave/chain scan
  — super-rounds ride it with zero host-relay hops. Under
  ``jax.distributed`` (cluster/multihost.py) the host axis spans REAL OS
  processes and the inter-host ppermute moves bytes between them.
- ``exchange="tree"``: the full packed frontier replicates through a
  log2(n_dev)-round recursive-doubling ``ppermute`` reduction tree,
  each round OR-combining block pairs at doubling distance; the
  explicit-tree alternative to ``lax.all_gather``.
- ``exchange="gather"``: plain ``lax.all_gather`` of packed words — the
  reference for equivalence tests.

Per level, after the exchange: local row gather (``node_epoch[dst]`` —
device-local by construction, the reason edges shard by destination),
version-masked fire, local scatter, and a ``psum`` for the continuation
flag. The while_loop carries the flag, so no collective runs in ``cond``.

The **chain faces** (:meth:`RoutedShardedGraph.dispatch_union_chain` /
:meth:`harvest_union_chain`) run K logical waves in ONE ``lax.scan`` with
per-stage compacted newly-id readback — the frontier exchange composed
into the nonblocking loop-carried chain (graph/nonblocking.py rides them
when mesh routing is enabled), so a cross-shard frontier resolves inside
the fused dispatch instead of re-entering through per-key host RPC.

A live reshard MOVES a device shard (:meth:`apply_placement`): the moved
shard's fixed-width row block transfers on-device to its new owner's free
slot, the affected consumer devices' edge slices + exchange buckets
re-pack host-side, and everything else stays resident. Structural churn
patches route by owner (:meth:`patch_batch` — bumps scatter absolute
epochs, adds splice into per-device slack slots) and apply in ONE fused
dispatch per batch.

**Dynamic bucket growth (ISSUE 15).** Edge routing is CAP-INDEPENDENT:
per-edge arrays carry ``(eprod, ebslot)`` — the producer family and the
slot WITHIN its bucket — and the kernel computes the flat exchange index
from the (trace-time) bucket capacities. An overflowed exchange bucket,
host bucket, or edge-slack slot therefore GROWS IN PLACE: the host-side
table re-allocates with the new capacity, re-uploads, and the next
dispatch recompiles against the new shape — no consumer's slot
assignments change (slots are append-only between rebuilds). Every grow
counts in ``fusion_mesh_bucket_resizes_total``; a graph that exhausts its
``max_resizes`` budget reports the overflow exactly like the old code
(``False`` / :class:`PlacementError`) and the caller takes the REBUILD
rung — the last rung of the counted ladder
(resize → resize-exhausted → rebuild), never a silent fallback.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..cluster.placement import DevicePlacement, PlacementError
from ..diagnostics.mesh_telemetry import current_dispatch_cause, global_mesh_trace
from ..diagnostics.metrics import global_metrics, next_wave_seq
from ..diagnostics.tracing import wave_shaped_cause
from .mesh import GRAPH_AXIS, graph_mesh, shard_map_compat

__all__ = ["RoutedShardedGraph", "build_routed_wave", "record_level_stall_ms"]

_EXCHANGES = ("a2a", "tree", "gather", "hier")
HOST_AXIS = "host"
LDEV_AXIS = "ldev"


def record_level_stall_ms(ms: float, cause: Optional[str] = None) -> None:
    """Record the level-barrier stall time an async A/B leg reclaimed
    (sync wall − async wall over the same wave schedule, clamped at 0) as
    the ``fusion_mesh_level_stall_ms`` MAX-gauge. Lives here — next to the
    kernel whose barrier it measures — so the perf legs share one minting
    site and the catalog row has a package anchor. ``cause`` (the leg's
    last traced wave) additionally records the sample into the
    ``fusion_mesh_stall_reclaim_ms`` histogram, whose exemplar ring keeps
    the wave id — an operator reading the reclaim number can jump to
    ``GET /trace?cause=`` in one hop (ISSUE 19)."""
    g = global_metrics().gauge(
        "fusion_mesh_level_stall_ms",
        help="level-barrier stall time reclaimed by the async frontier "
        "mode over an identical wave schedule (sync wall minus async "
        "wall, ms; MAX across recordings)",
    )
    g.set(float(ms))
    global_metrics().set_aggregation("fusion_mesh_level_stall_ms", "max")
    if cause is not None:
        global_metrics().histogram(
            "fusion_mesh_stall_reclaim_ms",
            help="per-recording async stall-reclaim samples; exemplars "
            "carry the reclaiming leg's wave cause id",
        ).record(float(ms), cause=cause)


def _flat_spec(mesh: Mesh) -> P:
    """The node/edge partition spec for a routed mesh: 1-D graph axis, or
    the flattened (host, ldev) product for the hierarchical exchange."""
    names = mesh.axis_names
    return P(names[0]) if len(names) == 1 else P(tuple(names))


def _psum_axes(mesh: Mesh):
    names = mesh.axis_names
    return names[0] if len(names) == 1 else tuple(names)


def build_routed_wave(
    mesh: Mesh, n_global: int, n_dev: int, exchange: str, async_depth: int = 0
):
    """Compile the routed union wave for a mesh + geometry. Returns
    ``wave(frontier, send_idx, hsend_idx, eprod, ebslot, ebit, edst,
    elsrc, eepoch, nepoch, invalid) -> (invalid', count, levels,
    spec_levels)`` — all arrays sharded over the mesh's flat device axis;
    seeds conduct even when already invalid (the r4 union rule);
    ``levels`` is the number of frontier exchanges the wave ran (the
    collective-rounds telemetry ``fusion_mesh_exchange_levels``
    aggregates). For ``exchange="hier"`` the mesh must be the 2-D
    ``(host, ldev)`` mesh; bucket capacities are read from the
    (trace-time) table shapes, which is what lets an in-place bucket
    resize recompile instead of re-pack.

    ``async_depth >= 1`` compiles the ASYNCHRONOUS execution mode (ISSUE
    17): between global merges each shard advances its LOCAL frontier
    speculatively for up to ``async_depth`` levels through the per-edge
    ``elsrc`` table (same-device source row, pad for remote sources —
    local CSR expansion never waits on remote words). A merge then
    exchanges the cumulative EVER-LIT accumulator through the unchanged
    OR-accumulation collectives (atomic-free by construction — packed-word
    OR is idempotent and order-independent, the Tascade reduction-tree
    property) and fires every edge against it, which both completes the
    remote frontier and picks up local rows the bounded speculation left
    unexpanded. The per-level barrier becomes a counted QUIESCENCE vote:
    one psum of "did any shard's merge fire a row" per merge epoch —
    merge firing nothing anywhere proves no ever-lit→eligible edge
    remains, i.e. the closure is complete (monotone idempotent
    OR-accumulation makes the final mask schedule-independent, so the
    async mask is bit-identical to the sync exchange and the host BFS).
    ``levels`` then counts MERGE epochs (each runs exactly one full
    exchange — the cross-host-words accounting stays honest) and
    ``spec_levels`` the deepest shard's productive speculative levels."""
    if exchange not in _EXCHANGES:
        raise ValueError(f"unknown exchange {exchange!r}")
    n_local = n_global // n_dev
    assert n_local % 32 == 0
    w_local = n_local // 32
    if exchange == "tree" and (n_dev & (n_dev - 1)):
        raise ValueError("tree exchange needs a power-of-two device count")
    if exchange == "hier":
        n_hosts, dph = mesh.devices.shape
        assert n_hosts * dph == n_dev
    else:
        n_hosts, dph = 1, n_dev

    spec = _flat_spec(mesh)
    ax = _psum_axes(mesh)
    node_spec = spec
    edge_spec = spec
    send_spec = P(*(spec + (None,)))

    def _pack_words(f_l):
        lanes = jnp.arange(32, dtype=jnp.uint32)[None, :]
        return jnp.sum(
            f_l.reshape(-1, 32).astype(jnp.uint32) << lanes, axis=1, dtype=jnp.uint32
        )

    def _exchange_words(f_l, send_idx_l, hsend_idx_l):
        """One frontier exchange: local packed words → (intra_flat,
        cross_flat) word vectors the per-edge (eprod, ebslot) routing
        indexes into (layout differs per mode; cross_flat exists only for
        hier)."""
        words = _pack_words(f_l)
        if exchange == "gather":
            return lax.all_gather(words, ax, tiled=True), None
        if exchange == "a2a":
            words_p = jnp.concatenate([words, jnp.zeros(1, jnp.uint32)])  # pad word
            send = words_p[send_idx_l]  # [n_dev, icap] — bucket per consumer
            recv = lax.all_to_all(
                send, ax, split_axis=0, concat_axis=0, tiled=True
            )
            return recv.reshape(-1), None  # row p = words from producer p
        if exchange == "tree":
            # recursive-doubling ppermute — log2(n_dev) OR-merge rounds
            acc = words
            idx = lax.axis_index(ax)
            step = 1
            while step < n_dev:
                perm = [(i, i ^ step) for i in range(n_dev)]
                recv = lax.ppermute(acc, ax, perm)
                low = (idx & step) == 0  # my block sits in the lower half
                acc = jnp.where(
                    low,
                    jnp.concatenate([acc, recv]),
                    jnp.concatenate([recv, acc]),
                )
                step *= 2
            return acc, None  # full packed frontier, device order
        # hier — ISSUE 15: two stages, intra-host then inter-host
        words_p = jnp.concatenate([words, jnp.zeros(1, jnp.uint32)])
        # stage 1: intra-host packed-word a2a over the local device group
        # (same bucket protocol as a2a, subgroup = this host's devices;
        # nothing crosses the host boundary here)
        send = words_p[send_idx_l]  # [dph, icap]
        intra = lax.all_to_all(
            send, LDEV_AXIS, split_axis=0, concat_axis=0, tiled=True
        ).reshape(-1)  # row p_l = words from local producer p_l
        # stage 2a: host-bucket contribution gather + intra-host OR
        # assembly — each device owns a disjoint word range, so OR over
        # the host group assembles the host's complete outgoing buckets
        contrib = words_p[hsend_idx_l]  # [n_hosts(G), hcap]
        step = 1
        while step < dph:
            perm = [(i, i ^ step) for i in range(dph)]
            contrib = contrib | lax.ppermute(contrib, LDEV_AXIS, perm)
            step *= 2
        # stage 2b: recursive-doubling ppermute TREE across hosts (the
        # Tascade reduction-tree shape) shipping the reduced per-host
        # frontier BUCKETS — only bucket payloads cross the host boundary
        # (never full frontiers), though each tree round re-ships the
        # accumulated blocks, so wire cost ~ n_hosts x bucket capacity
        acc = contrib[None]  # [1, n_hosts(G), hcap] — my host's payload
        h = lax.axis_index(HOST_AXIS)
        hstep = 1
        while hstep < n_hosts:
            perm = [(i, i ^ hstep) for i in range(n_hosts)]
            recv = lax.ppermute(acc, HOST_AXIS, perm)
            low = (h & hstep) == 0
            acc = jnp.where(
                low,
                jnp.concatenate([acc, recv]),
                jnp.concatenate([recv, acc]),
            )
            hstep *= 2
        return intra, acc.reshape(-1)  # [n_hosts(H) * n_hosts(G) * hcap]

    def _lookup(intra_flat, cross_flat, send_idx_l, hsend_idx_l, eprod_l, ebslot_l):
        """Per-edge source word via the cap-independent (eprod, ebslot)
        routing. Capacities come from trace-time table shapes — the hook
        dynamic bucket growth hangs off."""
        if exchange in ("tree", "gather"):
            return intra_flat[ebslot_l]
        if exchange == "a2a":
            icap = send_idx_l.shape[-1]
            return intra_flat[eprod_l * icap + ebslot_l]
        # hier: intra edges read the subgroup-a2a rows; cross edges read
        # the (producer host, consumer host) bucket of the host tree
        icap = send_idx_l.shape[-1]
        hcap = hsend_idx_l.shape[-1]
        g = lax.axis_index(HOST_AXIS)
        is_cross = eprod_l >= n_dev
        idx_i = (eprod_l % dph) * icap + ebslot_l
        idx_c = ((eprod_l - n_dev) * n_hosts + g) * hcap + ebslot_l
        w_i = intra_flat[jnp.where(is_cross, 0, idx_i)]
        w_c = cross_flat[jnp.where(is_cross, idx_c, 0)]
        return jnp.where(is_cross, w_c, w_i)

    @shard_map_compat(
        mesh=mesh,
        in_specs=(
            node_spec, send_spec, send_spec, edge_spec, edge_spec, edge_spec,
            edge_spec, edge_spec, edge_spec, node_spec, node_spec,
        ),
        out_specs=(node_spec, P(), P(), P()),
    )
    def _wave(seeds_l, send_idx_l, hsend_idx_l, eprod_l, ebslot_l, ebit_l,
              edst_l, elsrc_l, eepoch_l, nepoch_l, inv_l):
        fresh = seeds_l & ~inv_l
        inv_l = inv_l | seeds_l
        count0 = lax.psum(fresh.sum(dtype=jnp.int32), ax)
        go0 = lax.psum(seeds_l.any().astype(jnp.int32), ax) > 0

        def merge_fire(frontier, inv):
            """One global exchange of ``frontier`` + a fire over EVERY
            edge against it (shared by the sync per-level step and the
            async merge epoch)."""
            intra_flat, cross_flat = _exchange_words(
                frontier, send_idx_l, hsend_idx_l
            )
            word = _lookup(
                intra_flat, cross_flat, send_idx_l, hsend_idx_l, eprod_l, ebslot_l
            )
            src_active = ((word >> ebit_l.astype(jnp.uint32)) & 1).astype(bool)
            ver_ok = nepoch_l[edst_l] == eepoch_l  # gather clamps; -1 never matches
            fire = src_active & ver_ok & ~inv[edst_l]
            return jnp.zeros_like(frontier).at[edst_l].max(fire)  # OOB pads dropped

        if async_depth and async_depth > 0:
            # ---- asynchronous mode: speculative local levels between
            # counted-quiescence merges (ISSUE 17) ----
            def spec_body(_i, st):
                f, inv, acc, newly_l, spec = st
                # local-only expansion: a remote-sourced edge's elsrc is
                # the pad row → fill False, so it simply waits for a merge
                src_active = f.at[elsrc_l].get(mode="fill", fill_value=False)
                ver_ok = nepoch_l[edst_l] == eepoch_l
                fire = src_active & ver_ok & ~inv[edst_l]
                nxt = jnp.zeros_like(f).at[edst_l].max(fire)
                return (
                    nxt, inv | nxt, acc | nxt,
                    newly_l + nxt.sum(dtype=jnp.int32),
                    spec + nxt.any().astype(jnp.int32),
                )

            def cond(carry):
                return carry[6]

            def body(carry):
                f, inv, acc, count, merges, spec, _go = carry
                f, inv, acc, newly_l, spec = lax.fori_loop(
                    0, async_depth, spec_body,
                    (f, inv, acc, jnp.int32(0), spec),
                )
                # merge epoch: exchange the EVER-LIT accumulator and fire
                # every edge against it — completes remote frontiers AND
                # local rows the bounded speculation left unexpanded
                nxt_m = merge_fire(acc, inv)
                inv = inv | nxt_m
                acc = acc | nxt_m
                newly = lax.psum(newly_l + nxt_m.sum(dtype=jnp.int32), ax)
                # quiescence vote: the merge covers ALL edges against all
                # ever-lit rows — firing nothing anywhere proves closure
                go = lax.psum(nxt_m.any().astype(jnp.int32), ax) > 0
                return nxt_m, inv, acc, count + newly, merges + 1, spec, go

            _f, inv_l, _acc, count, levels, spec, _go = lax.while_loop(
                cond, body,
                (seeds_l, inv_l, seeds_l, count0, jnp.int32(0), jnp.int32(0), go0),
            )
            return inv_l, count, levels, lax.pmax(spec, ax)

        def cond(carry):
            return carry[4]

        def body(carry):
            f_l, inv_l, count, levels, _go = carry
            nxt_l = merge_fire(f_l, inv_l)
            inv_l = inv_l | nxt_l
            newly = lax.psum(nxt_l.sum(dtype=jnp.int32), ax)
            return nxt_l, inv_l, count + newly, levels + 1, newly > 0

        _f, inv_l, count, levels, _go = lax.while_loop(
            cond, body, (seeds_l, inv_l, count0, jnp.int32(0), go0)
        )
        return inv_l, count, levels, jnp.int32(0)

    return jax.jit(_wave)


def build_routed_compact(mesh: Mesh, n_global: int, n_dev: int, capd: int):
    """Per-device LOCAL newly-id compaction (ISSUE 9): each device cumsums
    its own shard rows into a ``capd``-sized buffer — no cross-device
    cumsum/scatter (the global compaction was super-linear on the mesh:
    XLA lowered it to collective permutes that dominated the wave itself
    past ~100K rows). Returns ``(counts int32[n_dev], bufs
    int32[n_dev*capd])``; device d's newly GLOBAL rows are
    ``bufs[d*capd : d*capd + counts[d]]``; ``counts[d] > capd`` = that
    device overflowed (caller mask-diffs)."""
    n_local = n_global // n_dev
    spec = _flat_spec(mesh)
    names = mesh.axis_names
    if len(names) == 1:
        dev_index = lambda: lax.axis_index(names[0])  # noqa: E731
    else:
        dph = mesh.devices.shape[1]
        dev_index = lambda: (  # noqa: E731
            lax.axis_index(names[0]) * dph + lax.axis_index(names[1])
        )

    @shard_map_compat(
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, spec),
    )
    def _compact(inv2_l, inv_l, real_l):
        newly_l = inv2_l & ~inv_l & real_l
        count = newly_l.sum(dtype=jnp.int32)
        pos = jnp.cumsum(newly_l.astype(jnp.int32)) - 1
        base = (dev_index() * n_local).astype(jnp.int32)
        rows = base + jnp.arange(n_local, dtype=jnp.int32)
        scatter_pos = jnp.where(newly_l & (pos < capd), pos, capd)
        buf = jnp.full(capd, -1, jnp.int32).at[scatter_pos].set(rows, mode="drop")
        return count[None], buf

    return _compact


class RoutedShardedGraph:
    """Mesh-sharded device graph whose layout IS the cluster shard map."""

    def __init__(
        self,
        edges_src: np.ndarray,
        edges_dst: np.ndarray,
        n_nodes: int,
        placement: DevicePlacement,
        mesh: Optional[Mesh] = None,
        exchange: str = "a2a",
        edge_dst_epoch: Optional[np.ndarray] = None,
        node_epoch: Optional[np.ndarray] = None,
        invalid: Optional[np.ndarray] = None,
        bucket_headroom: float = 1.3,
        edge_headroom: float = 1.3,
        max_resizes: int = 8,
        resize_growth: float = 1.5,
        exchange_async: bool = False,
        async_depth: int = 4,
    ):
        base_mesh = mesh or graph_mesh()
        if base_mesh.devices.size != placement.n_dev:
            raise PlacementError(
                f"placement spans {placement.n_dev} devices, mesh has "
                f"{base_mesh.devices.size}"
            )
        if exchange not in _EXCHANGES:
            raise ValueError(f"unknown exchange {exchange!r}")
        #: tree requested but n_dev is not a power of two — resolved via
        #: gather, COUNTED (FL002: no silent mode swaps; same contract as
        #: the hier fallback below)
        self.tree_fallbacks = 0
        if exchange == "tree" and (placement.n_dev & (placement.n_dev - 1)):
            exchange = "gather"  # tree's xor rounds need 2^k devices
            self.tree_fallbacks = 1
            global_metrics().counter(
                "fusion_mesh_tree_fallback_total",
                help="tree exchanges resolved via gather on a non-power-of-2 "
                "device count (counted fallback, never a decline)",
            ).inc()
            from ..resilience.events import global_events

            global_events().record(
                "tree_fallback", f"n_dev={placement.n_dev}"
            )
        self.dph = placement.devices_per_host or placement.n_dev
        self.n_hosts = placement.n_dev // self.dph
        #: hier requested but the geometry can't ride the xor trees —
        #: resolved via gather instead of declining (ISSUE 16), COUNTED:
        #: a non-power-of-2 mesh silently losing its hierarchical exchange
        #: would misread as a perf regression with no telemetry trail
        self.hier_fallbacks = 0
        if exchange == "hier" and (
            (self.dph & (self.dph - 1)) or (self.n_hosts & (self.n_hosts - 1))
        ):
            exchange = "gather"  # hier's xor trees need 2^k hosts AND dph
            self.hier_fallbacks = 1
            global_metrics().counter(
                "fusion_mesh_hier_fallback_total",
                help="hier exchanges resolved via gather on a non-power-of-2 "
                "host/device geometry (counted fallback, never a decline)",
            ).inc()
            from ..resilience.events import global_events

            global_events().record(
                "hier_fallback", f"hosts={self.n_hosts} dph={self.dph}"
            )
        if exchange == "hier":
            devs = np.asarray(base_mesh.devices).reshape(-1)
            self.mesh = Mesh(
                devs.reshape(self.n_hosts, self.dph), (HOST_AXIS, LDEV_AXIS)
            )
        else:
            self.mesh = base_mesh
        self.placement = placement
        self.exchange = exchange
        self.n_nodes = n_nodes
        self.n_dev = placement.n_dev
        self.n_local = placement.n_local
        self.n_global = placement.n_global
        self.w_local = self.n_local // 32
        #: set when a failed in-place reshard left device/host layout
        #: inconsistent — every wave entry point then refuses (rebuild)
        self.broken = False
        #: in-place capacity growth budget: once spent, an overflow falls
        #: to the REBUILD rung of the ladder exactly like the pre-resize
        #: code (counted, never silent)
        self.max_resizes = max_resizes
        self.resize_growth = resize_growth
        self.bucket_resizes = 0
        self.resize_detail = {"bucket": 0, "hbucket": 0, "edge": 0}
        #: async frontier execution (ISSUE 17): speculative local levels
        #: between counted-quiescence merge epochs
        self.exchange_async = bool(exchange_async)
        self.async_depth = int(async_depth) if self.exchange_async else 0
        # -- telemetry --
        self.waves_run = 0
        self.levels_total = 0  # frontier exchanges (collective rounds)
        self.quiescence_checks = 0  # async merge epochs (each = one vote)
        self.spec_levels_total = 0  # deepest shard's productive spec levels
        self.shard_moves = 0
        self.cross_host_moves = 0
        self.patches = 0
        self.patch_dispatches = 0
        self.cross_host_words = 0  # cumulative words shipped across hosts
        self.cross_words_per_level = 0  # static per-exchange-level payload
        self._procs = jax.process_count()
        #: mesh trace identity (ISSUE 18): segments recorded at the host
        #: boundaries carry this host label; ``trace_cause`` lets a driver
        #: pin a mesh-wide cause (every host running the same deterministic
        #: schedule names the wave identically, so the stitch can join
        #: their segments); the super-round threads the backend's cause via
        #: the dispatch contextvar instead
        self.trace_host = f"h{jax.process_index()}"
        self.trace_cause: Optional[str] = None
        self.last_trace_cause: Optional[str] = None

        # int32 host truth: node ids always fit (n_global is int32-bound),
        # and at 240M edges the int64 sorted copies alone were ~5 GB
        src = np.asarray(edges_src, dtype=np.int32)
        dst = np.asarray(edges_dst, dtype=np.int32)
        ep = (
            np.zeros(len(dst), dtype=np.int32)
            if edge_dst_epoch is None
            else np.asarray(edge_dst_epoch, dtype=np.int32)
        )
        # host truth: per-DST-SHARD edge lists (absolute node ids + absolute
        # captured epochs) — the unit a reshard re-partitions by owner
        ips = placement.ids_per_shard
        shard_of_dst = dst.astype(np.int64) // ips
        order = np.argsort(shard_of_dst, kind="stable")
        src, dst, ep, sh = src[order], dst[order], ep[order], shard_of_dst[order]
        self._shard_edges: Dict[int, List[np.ndarray]] = {}
        if len(sh):
            bounds = np.flatnonzero(np.diff(sh)) + 1
            starts = np.concatenate([[0], bounds])
            ends = np.concatenate([bounds, [len(sh)]])
            for a, b in zip(starts, ends):
                self._shard_edges[int(sh[a])] = [src[a:b], dst[a:b], ep[a:b]]

        # capacities sized from the initial partition + headroom
        dev_edges = np.zeros(self.n_dev, dtype=np.int64)
        for s, (es, _ed, _ee) in self._shard_edges.items():
            d = int(placement.shard_dev[s])
            if d >= 0:
                dev_edges[d] += len(es)
        self.e_cap = max(int(dev_edges.max() * edge_headroom) + 32, 64)
        self.bucket_headroom = bucket_headroom
        spec = _flat_spec(self.mesh)
        self._node_sh = NamedSharding(self.mesh, spec)
        self._edge_sh = NamedSharding(self.mesh, spec)
        self._send_sh = NamedSharding(self.mesh, P(*(spec + (None,))))
        self._rep_sh = NamedSharding(self.mesh, P())
        self._replicator = None  # lazy jit identity → replicated (multihost fetch)

        perm, inv_perm = placement.permutation()
        self.perm, self.inv_perm = perm, inv_perm
        self._real_rows = np.flatnonzero(inv_perm >= 0)
        self._real_nodes = inv_perm[self._real_rows]

        # node state, absolute epochs (no rebase: patches translate nothing)
        nep = np.zeros(self.n_global, dtype=np.int32)
        inv0 = np.zeros(self.n_global, dtype=bool)
        if node_epoch is not None:
            nep[perm[: len(node_epoch)][perm[: len(node_epoch)] >= 0]] = np.asarray(
                node_epoch, dtype=np.int32
            )[perm[: len(node_epoch)] >= 0]
        if invalid is not None:
            m = np.asarray(invalid, dtype=bool)
            rows = perm[: len(m)]
            ok = rows >= 0
            inv0[rows[ok]] = m[ok]
        self._h_is_real = np.zeros(self.n_global, dtype=bool)
        self._h_is_real[self._real_rows] = True

        self._build_exchange_and_edges()
        self.g_node_epoch = self._put(nep, self._node_sh)
        self.g_invalid = self._put(inv0, self._node_sh)
        self.g_is_real = self._put(self._h_is_real, self._node_sh)
        self._wave = build_routed_wave(
            self.mesh, self.n_global, self.n_dev, self.exchange,
            async_depth=self.async_depth,
        )
        self._collect_cache: dict = {}
        self._chain_cache: dict = {}
        self._patch_cache: dict = {}
        self._move_cache: dict = {}
        if self.n_hosts > 1:
            g = global_metrics().gauge(
                "fusion_mesh_hosts",
                help="host processes joined into the global device mesh",
            )
            g.set(self.n_hosts)
            global_metrics().set_aggregation("fusion_mesh_hosts", "max")

    # ---------------------------------------------------------------- helpers
    def _host_of_dev(self, d) -> np.ndarray:
        return np.asarray(d) // self.dph

    def _put(self, a: np.ndarray, sharding):
        """Host array → global device array. Multi-process: via
        ``make_array_from_callback`` — each process materializes ONLY its
        addressable shards from the (identical, SPMD-contract) host
        truth, so an upload NEVER touches the wire. A cross-process
        ``device_put`` lowers to an SPMD program whose collectives can
        interleave with an in-flight compute module's on the shared gloo
        pairs (chunked large messages mispair → transport abort; found
        at the 5M build, nondeterministic). Single-process: plain
        device_put, unchanged."""
        if self._procs == 1:
            return jax.device_put(a, sharding)
        a = np.asarray(a)
        return jax.make_array_from_callback(a.shape, sharding, lambda idx: a[idx])

    def _host_arg(self, a: np.ndarray):
        """A host index/seed array as a jit argument: replicated global
        array under multi-process (every host passes identical data —
        the SPMD contract), plain local array otherwise."""
        if self._procs == 1:
            return jnp.asarray(a)
        return self._put(np.asarray(a), self._rep_sh)

    def _sync(self, *arrays) -> None:
        """Multi-process collective-module serialization: block until the
        dispatched module's outputs are ready before dispatching the NEXT
        module that carries collectives. Two concurrently-executing
        modules reuse XLA channel ids on the gloo CPU transport and their
        chunked messages mispair (the same abort class the _put docstring
        names) — on the real accelerator fabric this is a no-op concern,
        so single-process keeps the async dispatch overlap."""
        if self._procs > 1:
            jax.block_until_ready(arrays)

    def _fetch(self, x) -> np.ndarray:
        """A device array's FULL value on every host. Single-process:
        plain device_get. Multi-process: one jitted replication (an
        all-gather over the mesh) then read the local copy — a global
        array spans non-addressable devices and cannot be fetched
        directly."""
        if self._procs == 1:
            return np.asarray(jax.device_get(x))
        if self._replicator is None:
            self._replicator = jax.jit(lambda a: a, out_shardings=self._rep_sh)
        rep = self._replicator(x)
        out = np.asarray(rep.addressable_shards[0].data)
        self._sync(rep)
        return out

    # ------------------------------------------------------------------ build
    def _consumer_pack(self, d: int) -> dict:
        """Pack consumer device ``d``'s edge slice (UNPADDED) + its word
        buckets from the host per-shard edge lists. Intra buckets cover
        every producer for ``a2a`` and same-host producers for ``hier``;
        hier's cross-host edges come back as (producer host, global word)
        pairs — their ``ebslot`` is assigned against the shared host
        buckets by the caller (build: vectorized union; repack/patch:
        append-only against the live tables)."""
        pl = self.placement
        srcs: List[np.ndarray] = []
        dsts: List[np.ndarray] = []
        eps: List[np.ndarray] = []
        for s in range(pl.shard_map.n_shards):
            if int(pl.shard_dev[s]) != d:
                continue
            ent = self._shard_edges.get(s)
            if ent is None:
                continue
            srcs.append(ent[0])
            dsts.append(ent[1])
            eps.append(ent[2])
        if srcs:
            src = np.concatenate(srcs)
            dst = np.concatenate(dsts)
            ep = np.concatenate(eps)
        else:
            src = dst = np.empty(0, np.int64)
            ep = np.empty(0, np.int32)
        src_rows = self.perm[src] if len(src) else src
        dst_rows = self.perm[dst] if len(dst) else dst
        if len(src) and (src_rows.min() < 0 or dst_rows.min() < 0):
            raise PlacementError("edge endpoints land on off-mesh shards")
        n_e = len(src)
        words = src_rows >> 5
        eprod = np.zeros(n_e, dtype=np.int32)
        ebslot = np.zeros(n_e, dtype=np.int32)
        ebit = (src_rows & 31).astype(np.int32) if n_e else np.empty(0, np.int32)
        edst = (
            (dst_rows - d * self.n_local).astype(np.int32)
            if n_e
            else np.empty(0, np.int32)
        )
        # async speculation operates on LOCAL sources only: same-device
        # producers get their local row, remote ones the pad row (they
        # wait for a merge epoch)
        elsrc = (
            np.where(
                src_rows // self.n_local == d,
                src_rows - d * self.n_local,
                self.n_local,
            ).astype(np.int32)
            if n_e
            else np.empty(0, np.int32)
        )
        buckets: Dict[int, np.ndarray] = {}
        cross = None
        if self.exchange in ("tree", "gather"):
            if n_e:
                ebslot[:] = words.astype(np.int32)
        else:
            prod = (src_rows // self.n_local).astype(np.int64)
            my_host = d // self.dph
            if self.exchange == "hier":
                intra_sel = self._host_of_dev(prod) == my_host if n_e else np.empty(0, bool)
            else:
                intra_sel = np.ones(n_e, dtype=bool)
            for p in range(self.n_dev):
                if self.exchange == "hier" and p // self.dph != my_host:
                    continue
                sel = intra_sel & (prod == p)
                if not sel.any():
                    buckets[p] = np.empty(0, np.int64)
                    continue
                wl = words[sel] - p * self.w_local
                uniq = np.unique(wl)
                buckets[p] = uniq
                eprod[sel] = p
                ebslot[sel] = np.searchsorted(uniq, wl)
            if self.exchange == "hier":
                csel = ~intra_sel
                if csel.any():
                    ch = self._host_of_dev(prod[csel]).astype(np.int64)
                    eprod[csel] = (self.n_dev + ch).astype(np.int32)
                    cross = (ch, words[csel], np.flatnonzero(csel))
        return {
            "n_e": n_e,
            "eprod": eprod,
            "ebslot": ebslot,
            "ebit": ebit,
            "edst": edst,
            "elsrc": elsrc,
            "eep": ep,
            "buckets": buckets,
            "cross": cross,
        }

    def _register_pack_buckets(self, d: int, pack: dict) -> None:
        """Adopt a pack's build-time intra buckets as device ``d``'s live
        bucket truth (sorted build-time buckets: slot lookup at patch time
        is a searchsorted, never a V×words Python dict at 100M-node
        scale); patch-added slots restart empty."""
        self._buckets[d] = pack["buckets"]
        self._patch_slots[d] = {}
        self._bucket_fill[d] = {p: len(b) for p, b in pack["buckets"].items()}
        self._dev_edge_count[d] = pack["n_e"]

    def _assign_cross_slots(self, d: int, pack: dict, append: bool) -> int:
        """Resolve a pack's cross-host edges to host-bucket slots. With
        ``append=True`` (repack after a reshard) new words APPEND to the
        live buckets — existing consumers' slots never shift, which is
        what makes a re-pack touch only the affected consumer's slices.
        Returns the peak fill the assignment needed (the caller grows
        ``hbucket_cap`` when it exceeds it)."""
        peak = 0
        if pack["cross"] is None:
            return peak
        g = d // self.dph
        ch, cw, pos = pack["cross"]
        for h in np.unique(ch).tolist():
            key = (int(h), g)
            sel = ch == h
            wsel = cw[sel]
            hb = self._hbuckets.setdefault(key, np.empty(0, np.int64))
            pslots = self._hpatch_slots.setdefault(key, {})
            fill = self._hbucket_fill.get(key, len(hb))
            base = np.searchsorted(hb, wsel)
            base_cl = np.minimum(base, max(len(hb) - 1, 0))
            hit = (len(hb) > 0) & (hb[base_cl] == wsel) if len(hb) else np.zeros(len(wsel), bool)
            slots = np.where(hit, base_cl, -1).astype(np.int64)
            miss = np.flatnonzero(~hit)
            if len(miss):
                if not append:
                    raise PlacementError(
                        f"cross-host word missing from host bucket {key}"
                    )
                for i in miss.tolist():
                    w = int(wsel[i])
                    j = pslots.get(w)
                    if j is None:
                        j = fill
                        pslots[w] = j
                        fill += 1
                        p = w // self.w_local
                        self._hsend_writes.append((p, g, j, w - p * self.w_local))
                    slots[i] = j
            self._hbucket_fill[key] = fill
            peak = max(peak, fill)
            pack["ebslot"][pos[sel]] = slots.astype(np.int32)
        return peak

    def _build_exchange_and_edges(self) -> None:
        """(Re)build the full host-side edge partition + exchange tables and
        upload. Called at construction and on a rebuild-grade change."""
        n_dev = self.n_dev
        #: consumer dev → {producer dev → sorted build-time word bucket}
        self._buckets: Dict[int, Dict[int, np.ndarray]] = {}
        #: consumer dev → {(producer, word) → slot} for PATCH-added words
        #: only (build-time slots resolve by searchsorted in _buckets)
        self._patch_slots: Dict[int, Dict[Tuple[int, int], int]] = {}
        self._bucket_fill: Dict[int, Dict[int, int]] = {}
        #: hier cross-host buckets: (producer host, consumer host) →
        #: sorted build-time GLOBAL word ids (+ append-only patch slots)
        self._hbuckets: Dict[Tuple[int, int], np.ndarray] = {}
        self._hpatch_slots: Dict[Tuple[int, int], Dict[int, int]] = {}
        self._hbucket_fill: Dict[Tuple[int, int], int] = {}
        self._hsend_writes: List[Tuple[int, int, int, int]] = []
        self._dev_edge_count = np.zeros(n_dev, dtype=np.int64)
        packs = [self._consumer_pack(d) for d in range(n_dev)]
        need_e = max((p["n_e"] for p in packs), default=0)
        if need_e > self.e_cap:
            # construction sizes e_cap itself; this only triggers on a
            # geometry edge case — size up front, it is not a "resize"
            self.e_cap = need_e + 32
        for d, pack in enumerate(packs):
            self._register_pack_buckets(d, pack)
        peak = max(
            (max(f.values(), default=0) for f in self._bucket_fill.values()),
            default=0,
        )
        self.bucket_cap = max(int(peak * self.bucket_headroom) + 8, 16)
        if self.exchange == "hier":
            # host buckets: vectorized union over every consumer's cross
            # word lists, one sorted array per (producer host, consumer
            # host) pair
            per_pair: Dict[Tuple[int, int], List[np.ndarray]] = {}
            for d, pack in enumerate(packs):
                if pack["cross"] is None:
                    continue
                g = d // self.dph
                ch, cw, _pos = pack["cross"]
                for h in np.unique(ch).tolist():
                    per_pair.setdefault((int(h), g), []).append(cw[ch == h])
            for key, parts in per_pair.items():
                hb = np.unique(np.concatenate(parts))
                self._hbuckets[key] = hb
                self._hpatch_slots[key] = {}
                self._hbucket_fill[key] = len(hb)
            hpeak = max(self._hbucket_fill.values(), default=0)
            self.hbucket_cap = max(int(hpeak * self.bucket_headroom) + 8, 16)
            for d, pack in enumerate(packs):
                self._assign_cross_slots(d, pack, append=False)
            self._hsend_writes = []
        else:
            self.hbucket_cap = 1
        self._rebuild_send_tables(packs)
        self._write_edge_slices({d: p for d, p in enumerate(packs)})
        self._recount_cross_words()
        self._upload_edges()

    def _rebuild_send_tables(self, packs: Sequence[dict]) -> None:
        """Materialize the send-index tables from the live bucket truth."""
        n_dev = self.n_dev
        if self.exchange == "a2a":
            send = np.full(
                (n_dev, n_dev, self.bucket_cap), self.w_local, np.int32
            )
            for d in range(n_dev):
                for p, wl in self._buckets[d].items():
                    send[p, d, : len(wl)] = wl
                for (p, w), j in self._patch_slots[d].items():
                    send[p, d, j] = w
            self._h_send = send.reshape(n_dev * n_dev, self.bucket_cap)
        elif self.exchange == "hier":
            # intra: producer p's rows are its same-host consumers by
            # LOCAL index — [n_dev * dph, icap], each device holds [dph, icap]
            send = np.full(
                (n_dev, self.dph, self.bucket_cap), self.w_local, np.int32
            )
            for d in range(n_dev):
                c_l = d % self.dph
                for p, wl in self._buckets[d].items():
                    send[p, c_l, : len(wl)] = wl
                for (p, w), j in self._patch_slots[d].items():
                    send[p, c_l, j] = w
            self._h_send = send.reshape(n_dev * self.dph, self.bucket_cap)
        else:
            self.bucket_cap = 16  # unused; kernel signature stays uniform
            self._h_send = np.zeros((n_dev, 1), np.int32)
        if self.exchange == "hier":
            # cross: device p's [n_hosts, hcap] block marks the local word
            # index of every host-bucket word IT owns (pad elsewhere; the
            # host group OR-assembles the full bucket on device)
            hs = np.full(
                (n_dev, self.n_hosts, self.hbucket_cap), self.w_local, np.int32
            )
            for (h, g), hb in self._hbuckets.items():
                if len(hb):
                    p = hb // self.w_local
                    hs[p, g, np.arange(len(hb))] = (hb - p * self.w_local).astype(
                        np.int32
                    )
                for w, j in self._hpatch_slots[(h, g)].items():
                    p = w // self.w_local
                    hs[p, g, j] = w - p * self.w_local
            self._h_hsend = hs.reshape(n_dev * self.n_hosts, self.hbucket_cap)
        else:
            self._h_hsend = np.zeros((self.n_dev, 1), np.int32)

    def _write_edge_slices(self, packs: Dict[int, dict]) -> None:
        """(Re)write the listed devices' fixed-width edge slices into the
        host mirrors (allocating them first when absent)."""
        if not hasattr(self, "_h_eprod") or len(self._h_eprod) != self.n_dev * self.e_cap:
            self._h_eprod = np.zeros(self.n_dev * self.e_cap, dtype=np.int32)
            self._h_ebslot = np.zeros(self.n_dev * self.e_cap, dtype=np.int32)
            self._h_ebit = np.zeros(self.n_dev * self.e_cap, dtype=np.int32)
            self._h_edst = np.full(
                self.n_dev * self.e_cap, self.n_local, dtype=np.int32
            )  # pad: dropped
            self._h_elsrc = np.full(
                self.n_dev * self.e_cap, self.n_local, dtype=np.int32
            )  # pad: fill-False on speculative gather
            self._h_eep = np.full(self.n_dev * self.e_cap, -1, dtype=np.int32)
        for d, pack in packs.items():
            sl = slice(d * self.e_cap, (d + 1) * self.e_cap)
            n_e = pack["n_e"]
            self._h_eprod[sl] = 0
            self._h_ebslot[sl] = 0
            self._h_ebit[sl] = 0
            self._h_edst[sl] = self.n_local
            self._h_elsrc[sl] = self.n_local
            self._h_eep[sl] = -1
            if n_e:
                self._h_eprod[sl][:n_e] = pack["eprod"]
                self._h_ebslot[sl][:n_e] = pack["ebslot"]
                self._h_ebit[sl][:n_e] = pack["ebit"]
                self._h_edst[sl][:n_e] = pack["edst"]
                self._h_elsrc[sl][:n_e] = pack["elsrc"]
                self._h_eep[sl][:n_e] = pack["eep"]

    def _recount_cross_words(self) -> None:
        """Static per-exchange-level cross-host payload (words), per mode —
        the ``fusion_mesh_cross_host_words_total`` increment unit. Zero on
        a single-host mesh by construction. For ``hier`` this counts the
        DISTINCT reduced host-bucket words (fill) — the frontier
        information that must cross; the recursive-doubling tree's wire
        traffic is larger (each round ships the accumulated payload incl.
        capacity padding, ~n_hosts x the fill at full depth)."""
        if self.n_hosts <= 1:
            self.cross_words_per_level = 0
            return
        if self.exchange == "hier":
            self.cross_words_per_level = int(sum(self._hbucket_fill.values()))
        elif self.exchange == "a2a":
            total = 0
            for d, by_p in self._bucket_fill.items():
                for p, fill in by_p.items():
                    if p // self.dph != d // self.dph:
                        total += fill
            self.cross_words_per_level = total
        else:  # tree/gather replicate the full frontier to every host
            self.cross_words_per_level = (
                (self.n_hosts - 1) * self.n_hosts * self.dph * self.w_local
            )

    def _upload_edges(self) -> None:
        self.g_send = self._put(self._h_send, self._send_sh)
        self.g_hsend = self._put(self._h_hsend, self._send_sh)
        self.g_eprod = self._put(self._h_eprod, self._edge_sh)
        self.g_ebslot = self._put(self._h_ebslot, self._edge_sh)
        self.g_ebit = self._put(self._h_ebit, self._edge_sh)
        self.g_edst = self._put(self._h_edst, self._edge_sh)
        self.g_elsrc = self._put(self._h_elsrc, self._edge_sh)
        self.g_eep = self._put(self._h_eep, self._edge_sh)

    # ------------------------------------------------------------------ resize
    def _try_grow(self, kind: str, needed: int, upload: bool = True) -> bool:
        """Grow an overflowed capacity IN PLACE (ISSUE 15): re-allocate the
        host table with headroom, re-upload, and let the next dispatch
        recompile against the new shape — slot assignments are
        cap-independent so NOTHING re-packs. Counted; a spent budget
        returns False and the caller takes the rebuild rung.

        ``upload=False`` defers the device re-upload to the caller — a
        mutation that may grow several capacities (or that ends with its
        own :meth:`_upload_edges`) pays ONE full-table transfer instead of
        one per grow; the caller must upload before the next dispatch."""
        if self.bucket_resizes >= self.max_resizes:
            global_metrics().counter(
                "fusion_mesh_resize_exhausted_total",
                help="bucket/edge-slack overflows that exhausted the in-place "
                "resize budget and fell to the rebuild rung",
            ).inc()
            return False
        if kind == "bucket":
            old = self.bucket_cap
            new = max(needed + 8, int(old * self.resize_growth) + 1)
            rows = self._h_send.shape[0]
            grown = np.full((rows, new), self.w_local, np.int32)
            grown[:, :old] = self._h_send
            self._h_send = grown
            self.bucket_cap = new
        elif kind == "hbucket":
            old = self.hbucket_cap
            new = max(needed + 8, int(old * self.resize_growth) + 1)
            rows = self._h_hsend.shape[0]
            grown = np.full((rows, new), self.w_local, np.int32)
            grown[:, :old] = self._h_hsend
            self._h_hsend = grown
            self.hbucket_cap = new
        elif kind == "edge":
            old = self.e_cap
            new = max(needed + 32, int(old * self.resize_growth) + 1)
            for name, pad in (
                ("_h_eprod", 0),
                ("_h_ebslot", 0),
                ("_h_ebit", 0),
                ("_h_edst", self.n_local),
                ("_h_elsrc", self.n_local),
                ("_h_eep", -1),
            ):
                arr = getattr(self, name)
                grown = np.full(self.n_dev * new, pad, dtype=np.int32)
                grown.reshape(self.n_dev, new)[:, :old] = arr.reshape(
                    self.n_dev, old
                )
                setattr(self, name, grown)
            self.e_cap = new
        else:  # pragma: no cover — internal misuse
            raise ValueError(kind)
        self.bucket_resizes += 1
        self.resize_detail[kind] += 1
        global_metrics().counter(
            "fusion_mesh_bucket_resizes_total",
            help="exchange-bucket / host-bucket / edge-slack capacities grown "
            "in place instead of rebuilding the routed mirror (ISSUE 15)",
        ).inc()
        if upload:
            self._upload_edges()
        return True

    # ------------------------------------------------------------------ waves
    def _count_exchange(self, levels: int, spec_levels: int = 0) -> None:
        self.levels_total += levels
        if self.exchange_async and levels:
            # async mode: each merge epoch ends in exactly one counted
            # quiescence vote over the psum plane (the level fence that
            # replaced the per-level barrier)
            self.quiescence_checks += levels
            self.spec_levels_total += spec_levels
            global_metrics().counter(
                "fusion_mesh_quiescence_checks_total",
                help="async-mode counted quiescence votes (one per merge "
                "epoch — the fence that replaced the per-level exchange "
                "barrier, ISSUE 17)",
            ).inc(levels)
        if self.cross_words_per_level and levels:
            shipped = levels * self.cross_words_per_level
            self.cross_host_words += shipped
            global_metrics().counter(
                "fusion_mesh_cross_host_words_total",
                help="distinct reduced host-bucket frontier words per exchange "
                "level (the DCN leg's information content — what the bucket "
                "protocol exists to minimize). Wire cost runs higher: the "
                "recursive-doubling tree replicates the assembled payload "
                "~n_hosts x and ships capacity padding",
            ).inc(shipped)

    # -------------------------------------------------------- trace hooks
    #: derived per-level segments are capped per stage (coarsened by
    #: grouping, window preserved) so a deep wave cannot flood the store
    _TRACE_MAX_LEVELS = 64

    def _trace_cause_for_dispatch(self) -> Optional[str]:
        """The cause this dispatch's segments key under: the super-round's
        wave cause (contextvar) > a driver-pinned mesh-wide cause > a
        freshly minted wave-shaped cause. None when tracing is off."""
        if not global_mesh_trace().enabled:
            return None
        cause = current_dispatch_cause() or self.trace_cause
        if cause is None:
            cause = wave_shaped_cause(next_wave_seq())
        self.last_trace_cause = cause
        return cause

    def _pacing_shard(self, newly_node_ids) -> int:
        """The shard that carried most of this window's newly-invalid
        frontier — the per-host pacing attribution (the per-level split
        inside the jit'd kernel is not host-visible; the dominant shard
        of the harvested frontier is, and it is what a rebalance acts on)."""
        if newly_node_ids is None or len(newly_node_ids) == 0:
            return -1
        ips = self.placement.ids_per_shard
        counts = np.bincount(np.asarray(newly_node_ids, dtype=np.int64) // ips)
        return int(counts.argmax())

    def _trace_slice(self, store, cause, t0, t1, levels, spec, shard, level_base) -> int:
        """Record one stage's host-visible window as per-level segments.

        The wave kernel runs inside ONE jit dispatch — per-level host
        timestamps do not exist — so the measured window is divided across
        the counted levels (totals and ordering preserved; the derivation
        is documented in OBSERVABILITY.md, never passed off as measured).
        Async mode: the speculative share first (spec_expand), then one
        quiescence_vote per merge epoch; hier sync: each level splits into
        a2a (intra-host) + tree_round (cross-host); other sync modes: one
        exchange/tree_round segment per level. Returns the next wave-wide
        level index (chains keep level numbering cumulative)."""
        window = max(t1 - t0, 0.0)
        if levels <= 0:
            store.record(cause, "spec_expand" if spec else "exchange",
                         t0, t0 + window, host=self.trace_host, shard=shard)
            return level_base
        cursor = t0
        if self.exchange_async and spec > 0:
            cut = t0 + window * (spec / (spec + levels))
            store.record(cause, "spec_expand", cursor, cut,
                         host=self.trace_host, shard=shard)
            cursor = cut
        per = max(t1 - cursor, 0.0) / levels
        step = max(1, -(-levels // self._TRACE_MAX_LEVELS))
        for first in range(0, levels, step):
            n = min(step, levels - first)
            seg0 = cursor + first * per
            seg1 = seg0 + n * per
            lvl = level_base + first
            if self.exchange_async:
                store.record(cause, "quiescence_vote", seg0, seg1,
                             host=self.trace_host, level=lvl, shard=shard)
            elif self.exchange == "hier":
                mid = (seg0 + seg1) / 2.0
                store.record(cause, "a2a", seg0, mid,
                             host=self.trace_host, level=lvl, shard=shard)
                store.record(cause, "tree_round", mid, seg1,
                             host=self.trace_host, level=lvl, shard=shard)
            else:
                phase = "tree_round" if self.exchange == "tree" else "exchange"
                store.record(cause, phase, seg0, seg1,
                             host=self.trace_host, level=lvl, shard=shard)
        return level_base + levels

    def run_wave_collect(
        self, seed_node_ids: Sequence[int], cap: int = 65536
    ) -> Tuple[int, np.ndarray, bool]:
        """Union wave from node ids with O(wave) host exchange: seed ids up,
        compacted newly NODE ids back, one dispatch. Returns (count, newly
        node ids, overflow)."""
        self._check_usable()
        k = len(seed_node_ids)
        width = 1
        while width < max(k, 1):
            width <<= 1
        rows = np.full(width, self.n_global, dtype=np.int64)  # pad: dropped
        if k:
            r = self.perm[np.asarray(seed_node_ids, dtype=np.int64)]
            if r.min() < 0:
                raise PlacementError("seed node lands on an off-mesh shard")
            rows[:k] = r
        capd = max(cap // self.n_dev, 1024)
        fn = self._collect_cache.get((capd, width))
        if fn is None:
            fn = self._build_collect(capd)
            self._collect_cache[(capd, width)] = fn
        cause = self._trace_cause_for_dispatch()
        t0 = time.perf_counter()
        self.g_invalid, counts, levels, spec, bufs = fn(
            self._host_arg(rows), self.g_send, self.g_hsend, self.g_eprod,
            self.g_ebslot, self.g_ebit, self.g_edst, self.g_elsrc, self.g_eep,
            self.g_node_epoch, self.g_invalid, self.g_is_real,
        )
        self._sync(self.g_invalid, counts, levels, spec, bufs)
        counts = self._fetch(counts)
        levels = self._fetch(levels)
        spec = self._fetch(spec)
        bufs = self._fetch(bufs)
        self.waves_run += 1
        self._count_exchange(int(levels), int(spec))
        count = int(counts.sum())
        overflow = bool((counts > capd).any())
        node_ids: Optional[np.ndarray] = None
        if not overflow:
            ids = np.concatenate(
                [bufs[d * capd : d * capd + int(counts[d])] for d in range(self.n_dev)]
            )
            node_ids = self.inv_perm[ids]
        if cause is not None:
            self._trace_slice(
                global_mesh_trace(), cause, t0, time.perf_counter(),
                int(levels), int(spec), self._pacing_shard(node_ids), 0,
            )
        if overflow:
            return count, np.empty(0, np.int64), True
        return count, node_ids, False

    def _build_collect(self, capd: int):
        wave = self._wave
        compact = build_routed_compact(self.mesh, self.n_global, self.n_dev, capd)
        node_sh = self._node_sh
        n_global = self.n_global

        @jax.jit
        def collect(seed_rows, send, hsend, eprod, ebslot, ebit, edst, elsrc,
                    eep, nepoch, inv, is_real):
            frontier = lax.with_sharding_constraint(
                jnp.zeros(n_global, bool).at[seed_rows].set(True, mode="drop"),
                node_sh,
            )
            inv2, _count, levels, spec = wave(
                frontier, send, hsend, eprod, ebslot, ebit, edst, elsrc,
                eep, nepoch, inv,
            )
            counts, bufs = compact(inv2, inv, is_real)
            return inv2, counts, levels, spec, bufs

        return collect

    # ------------------------------------------------------------------ chain
    def stage_union_chain(
        self, stage_seed_lists: Sequence[Sequence[int]], cap: int = 65536
    ) -> dict:
        """Host-side pack of a union chain's seed tensor — the super-round
        BACK BUFFER (ISSUE 14): perm-map and pad WITHOUT dispatching, so
        the pack runs while the previous chain executes on device. The
        staged dict carries a (graph identity, placement epoch) token;
        :meth:`dispatch_union_chain` refuses a buffer staged against a
        permutation a reshard/rebuild has since retired (PlacementError —
        the caller re-stages, counted, never silently dispatches stale
        row ids)."""
        K = len(stage_seed_lists)
        if K == 0:
            raise ValueError("empty chain")
        width = 1
        kmax = max((len(s) for s in stage_seed_lists), default=1)
        while width < max(kmax, 1):
            width <<= 1
        mat = np.full((K, width), self.n_global, dtype=np.int64)
        for i, seeds in enumerate(stage_seed_lists):
            if seeds:
                r = self.perm[np.asarray(seeds, dtype=np.int64)]
                if r.min() < 0:
                    raise PlacementError("seed node lands on an off-mesh shard")
                mat[i, : len(seeds)] = r
        capd = max(cap // self.n_dev, 1024)
        return {
            "mat": mat, "stages": K, "width": width, "capd": capd,
            "token": (id(self), self.placement.epoch),
        }

    def dispatch_union_chain(
        self,
        stage_seed_lists: Optional[Sequence[Sequence[int]]] = None,
        cap: int = 65536,
        staged: Optional[dict] = None,
    ) -> dict:
        """K logical union waves in ONE lax.scan dispatch, NO readback:
        stage i cascades against the invalid state stages < i left (each
        result equals a sequential per-stage dispatch). ``staged`` (from
        :meth:`stage_union_chain`) skips the host pack — the double-
        buffered super-round path. Returns a pending ticket for
        :meth:`harvest_union_chain`; the device invalid state advances
        immediately (futures)."""
        self._check_usable()
        if staged is None:
            staged = self.stage_union_chain(stage_seed_lists, cap)
        elif staged["token"] != (id(self), self.placement.epoch):
            raise PlacementError(
                "staged seed buffer predates a reshard/rebuild — re-stage"
            )
        K, width, capd = staged["stages"], staged["width"], staged["capd"]
        mat = staged["mat"]
        fn = self._chain_cache.get((K, width, capd))
        if fn is None:
            fn = self._build_chain(capd)
            self._chain_cache[(K, width, capd)] = fn
        trace_cause = self._trace_cause_for_dispatch()
        trace_t0 = time.perf_counter()
        self.g_invalid, counts, levels, spec, bufs = fn(
            self._host_arg(mat), self.g_send, self.g_hsend, self.g_eprod,
            self.g_ebslot, self.g_ebit, self.g_edst, self.g_elsrc, self.g_eep,
            self.g_node_epoch, self.g_invalid, self.g_is_real,
        )
        # multi-process: the chain's collectives must fully drain before
        # any later module's (harvest fetch, patch) hit the gloo pairs —
        # the dispatch stays nonblocking on a single-process mesh
        self._sync(self.g_invalid, counts, levels, spec, bufs)
        return {"counts": counts, "levels": levels, "spec": spec, "bufs": bufs,
                "stages": K, "capd": capd, "dispatches": 1,
                "trace_cause": trace_cause, "trace_t0": trace_t0}

    def _build_chain(self, capd: int):
        wave = self._wave
        compact = build_routed_compact(self.mesh, self.n_global, self.n_dev, capd)
        node_sh = self._node_sh
        n_global = self.n_global

        @jax.jit
        def chain(seed_mat, send, hsend, eprod, ebslot, ebit, edst, elsrc,
                  eep, nepoch, inv0, is_real):
            def body(inv, seed_rows):
                frontier = lax.with_sharding_constraint(
                    jnp.zeros(n_global, bool).at[seed_rows].set(True, mode="drop"),
                    node_sh,
                )
                inv2, _c, levels, spec = wave(
                    frontier, send, hsend, eprod, ebslot, ebit, edst, elsrc,
                    eep, nepoch, inv,
                )
                counts, bufs = compact(inv2, inv, is_real)
                return inv2, (counts, levels, spec, bufs)

            inv, (counts, levels, spec, bufs) = lax.scan(body, inv0, seed_mat)
            return inv, counts, levels, spec, bufs

        return chain

    def harvest_union_chain(self, pending: dict) -> Tuple[np.ndarray, List[np.ndarray], dict]:
        """Block on a chain ticket: (per-stage counts, per-stage newly NODE
        id arrays, info). An overflowed stage returns ``None`` in its slot —
        the caller mask-diffs against its dense mirror; every overflow is
        COUNTED (``fusion_mesh_chain_overflows_total``), the containment
        path is never silent."""
        counts_dev = self._fetch(pending["counts"])
        levels = self._fetch(pending["levels"])
        spec = self._fetch(pending["spec"])
        bufs = self._fetch(pending["bufs"])
        capd = pending["capd"]
        self.waves_run += pending["stages"]
        self._count_exchange(int(levels.sum()), int(spec.sum()))
        counts = counts_dev.astype(np.int64).sum(axis=1)
        stage_ids: List[Optional[np.ndarray]] = []
        overflowed = False
        for i in range(pending["stages"]):
            if (counts_dev[i] > capd).any():
                stage_ids.append(None)
                overflowed = True
            else:
                stage_ids.append(
                    self.inv_perm[
                        np.concatenate(
                            [
                                bufs[i, d * capd : d * capd + int(counts_dev[i, d])]
                                for d in range(self.n_dev)
                            ]
                        )
                    ]
                )
        if overflowed:
            global_metrics().counter(
                "fusion_mesh_chain_overflows_total",
                help="fused-chain stages whose compacted newly-id buffer "
                "overflowed (recovered by one dense mask diff — counted, "
                "never silent)",
            ).inc(sum(1 for i in stage_ids if i is None))
        cause = pending.get("trace_cause")
        store = global_mesh_trace()
        if cause is not None and store.enabled:
            # the chain's dispatch→harvest window, split across stages
            # proportionally to their counted levels, then per-level within
            # each stage (_trace_slice); level numbering runs cumulatively
            # so the stitched timeline's merge epochs stay distinct
            t1 = time.perf_counter()
            t0 = float(pending.get("trace_t0", t1))
            lv = levels.astype(np.int64).ravel()
            sp = spec.astype(np.int64).ravel()
            weights = np.maximum(lv + sp, 1).astype(np.float64)
            edges = np.concatenate([[0.0], np.cumsum(weights)])
            scale = max(t1 - t0, 0.0) / edges[-1] if edges[-1] else 0.0
            level_base = 0
            for i in range(pending["stages"]):
                level_base = self._trace_slice(
                    store, cause, t0 + edges[i] * scale, t0 + edges[i + 1] * scale,
                    int(lv[i]), int(sp[i]), self._pacing_shard(stage_ids[i]),
                    level_base,
                )
        info = {"levels": levels.astype(np.int64), "overflowed": overflowed,
                "trace_cause": cause}
        return counts, stage_ids, info

    # ------------------------------------------------------------------ state
    def invalid_mask(self) -> np.ndarray:
        """bool[n_nodes] in NODE space (reads the device state once)."""
        arr = self._fetch(self.g_invalid)
        out = np.zeros(self.n_nodes, dtype=bool)
        out[self._real_nodes] = arr[self._real_rows]
        return out

    def set_invalid(self, mask: np.ndarray) -> None:
        inv = np.zeros(self.n_global, dtype=bool)
        m = np.asarray(mask[: self.n_nodes], dtype=bool)
        rows = self.perm[: len(m)]
        ok = rows >= 0
        inv[rows[ok]] = m[ok]
        self.g_invalid = self._put(inv, self._node_sh)

    def clear_invalid(self) -> None:
        self.g_invalid = self._put(
            np.zeros(self.n_global, dtype=bool), self._node_sh
        )

    # ------------------------------------------------------------------ reshard
    def apply_placement(self, new_placement: DevicePlacement, moves) -> None:
        """MOVE the listed device shards to their new owners: each moved
        shard's fixed-width row block transfers on-device (one fused
        gather/scatter dispatch for node state), and the affected consumer
        devices' edge slices + exchange buckets re-pack — affected means
        the old/new OWNER devices plus every consumer whose edges SOURCE
        from a moved shard (their slot/bucket routes reference the
        vacated rows; missing them loses invalidations silently — caught
        in review with a single-shard-move repro). State for unmoved
        shards never leaves its device. An overflow the in-place resize
        ladder cannot absorb raises :class:`PlacementError`, after which
        the graph is BROKEN (every wave entry point refuses) — the caller
        rebuilds. Cross-host row moves (the DCN transfers the host-aware
        placement ranking minimizes) are counted separately."""
        if not moves:
            self.placement = new_placement
            return
        old_rows_l: List[np.ndarray] = []
        new_rows_l: List[np.ndarray] = []
        affected_devs: set = set()
        ips = self.placement.ids_per_shard
        for s, old_dev, new_dev in moves:
            if old_dev >= 0:
                affected_devs.add(old_dev)
            if new_dev >= 0:
                affected_devs.add(new_dev)
            if old_dev < 0 or new_dev < 0:
                # shard entering/leaving the mesh changes real-row coverage:
                # that is a rebuild-grade change, not an in-place move
                raise PlacementError(f"shard {s} crossed the mesh boundary")
            base_old = old_dev * self.n_local + int(self.placement.shard_slot[s]) * self.placement.slot_rows
            base_new = new_dev * self.n_local + int(new_placement.shard_slot[s]) * new_placement.slot_rows
            n = min(ips, self.n_nodes - s * ips)
            if n <= 0:
                continue
            old_rows_l.append(np.arange(base_old, base_old + n, dtype=np.int64))
            new_rows_l.append(np.arange(base_new, base_new + n, dtype=np.int64))
        # consumers whose edge SOURCES moved: their exchange routes (a2a
        # buckets / host buckets / global word slots) point at the old rows
        moved_shards = np.fromiter((m[0] for m in moves), dtype=np.int64)
        for shard, ent in self._shard_edges.items():
            d = int(new_placement.shard_dev[shard])
            if d < 0 or d in affected_devs:
                continue
            if len(ent[0]) and np.isin(ent[0] // ips, moved_shards).any():
                affected_devs.add(d)
        cross = new_placement.cross_host_moves(moves) if self.n_hosts > 1 else 0
        self.placement = new_placement
        self.perm, self.inv_perm = new_placement.permutation()
        self._real_rows = np.flatnonzero(self.inv_perm >= 0)
        self._real_nodes = self.inv_perm[self._real_rows]
        self._h_is_real = np.zeros(self.n_global, dtype=bool)
        self._h_is_real[self._real_rows] = True
        self.g_is_real = self._put(self._h_is_real, self._node_sh)
        if old_rows_l:
            old_rows = np.concatenate(old_rows_l)
            new_rows = np.concatenate(new_rows_l)
            width = 1 << int(len(old_rows) - 1).bit_length()
            po = np.full(width, self.n_global, dtype=np.int64)
            pn = np.full(width, self.n_global, dtype=np.int64)
            po[: len(old_rows)] = old_rows
            pn[: len(new_rows)] = new_rows
            fn = self._move_cache.get(width)
            if fn is None:
                fn = self._build_move()
                self._move_cache[width] = fn
            self.g_node_epoch, self.g_invalid = fn(
                self.g_node_epoch, self.g_invalid,
                self._host_arg(po), self._host_arg(pn),
            )
            self._sync(self.g_node_epoch, self.g_invalid)
        # re-pack edges + buckets for the touched consumer devices only
        try:
            self._repack_devices(sorted(affected_devs))
        except PlacementError:
            # the state blocks already moved and some devices may be half
            # repacked — a partial rollback would LOOK usable while being
            # wrong (review finding). Mark broken; every wave entry point
            # refuses until the caller rebuilds.
            self.broken = True
            raise
        self.shard_moves += len(moves)
        if cross:
            self.cross_host_moves += cross
            global_metrics().counter(
                "fusion_mesh_cross_host_moves_total",
                help="moved device-shard row blocks that crossed a host "
                "boundary during a reshard (the DCN transfers the "
                "host-aware placement ranking minimizes)",
            ).inc(cross)

    def _build_move(self):
        node_sh = self._node_sh

        @jax.jit
        def move(ep, inv, old_rows, new_rows):
            mep = ep.at[old_rows].get(mode="fill", fill_value=0)
            minv = inv.at[old_rows].get(mode="fill", fill_value=False)
            ep = ep.at[old_rows].set(0, mode="drop").at[new_rows].set(mep, mode="drop")
            inv = (
                inv.at[old_rows].set(False, mode="drop")
                .at[new_rows].set(minv, mode="drop")
            )
            return (
                lax.with_sharding_constraint(ep, node_sh),
                lax.with_sharding_constraint(inv, node_sh),
            )

        return move

    def _repack_devices(self, devs: Sequence[int]) -> None:
        """Host-side re-pack of the listed consumer devices' edge slices
        and their bucket columns, then one upload. Overflow climbs the
        resize ladder first (edge slack and bucket/host-bucket capacities
        grow in place, counted); only a spent budget raises."""
        packs = {d: self._consumer_pack(d) for d in devs}
        need_e = max((p["n_e"] for p in packs.values()), default=0)
        if need_e > self.e_cap and not self._try_grow("edge", need_e, upload=False):
            raise PlacementError(
                f"edge slice {need_e} exceeds capacity {self.e_cap} and the "
                f"resize budget is spent"
            )
        for d, pack in packs.items():
            self._register_pack_buckets(d, pack)
        peak = max(
            (max(f.values(), default=0) for f in self._bucket_fill.values()),
            default=0,
        )
        if peak > self.bucket_cap and not self._try_grow("bucket", peak, upload=False):
            raise PlacementError(
                f"exchange bucket fill {peak} exceeds cap {self.bucket_cap} "
                f"and the resize budget is spent"
            )
        if self.exchange == "hier":
            self._hsend_writes = []
            hpeak = 0
            for d, pack in packs.items():
                hpeak = max(hpeak, self._assign_cross_slots(d, pack, append=True))
            if hpeak > self.hbucket_cap and not self._try_grow(
                "hbucket", hpeak, upload=False
            ):
                raise PlacementError(
                    f"host bucket fill {hpeak} exceeds cap {self.hbucket_cap} "
                    f"and the resize budget is spent"
                )
            for p, g, j, wloc in self._hsend_writes:
                self._h_hsend[p * self.n_hosts + g, j] = wloc
            self._hsend_writes = []
        # repacked consumers rewrite their send columns from bucket truth
        if self.exchange == "a2a":
            for d, pack in packs.items():
                send3 = self._h_send.reshape(self.n_dev, self.n_dev, self.bucket_cap)
                for p in range(self.n_dev):
                    col = np.full(self.bucket_cap, self.w_local, np.int32)
                    wl = self._buckets[d].get(p)
                    if wl is not None and len(wl):
                        col[: len(wl)] = wl
                    send3[p, d] = col
        elif self.exchange == "hier":
            send3 = self._h_send.reshape(self.n_dev, self.dph, self.bucket_cap)
            for d, pack in packs.items():
                c_l = d % self.dph
                my_host = d // self.dph
                for p in range(my_host * self.dph, (my_host + 1) * self.dph):
                    col = np.full(self.bucket_cap, self.w_local, np.int32)
                    wl = self._buckets[d].get(p)
                    if wl is not None and len(wl):
                        col[: len(wl)] = wl
                    send3[p, c_l] = col
        self._write_edge_slices(packs)
        self._recount_cross_words()
        self._upload_edges()

    # ------------------------------------------------------------------ patches
    def patch_batch(
        self,
        bump_ids: np.ndarray,
        add_u: np.ndarray,
        add_v: np.ndarray,
        add_ep: np.ndarray,
    ) -> bool:
        """Apply a WHOLE burst's structural patches in one fused device
        dispatch: epoch bumps scatter-add (+k for k bumps of one row —
        final state is order-independent because bumps are increments and
        adds carry absolute captured epochs), new edges splice into
        per-device slack slots routed by their destination's OWNER.
        Exhausted slack GROWS IN PLACE first (edge slots, exchange
        buckets, host buckets — each counted in
        ``fusion_mesh_bucket_resizes_total``); returns False only for
        rebuild-grade shapes (new nodes, off-mesh endpoints) or a spent
        resize budget — after False the caller MUST rebuild (host truth
        may be partially advanced, same contract as before)."""
        self._check_usable()
        bump_rows = np.empty(0, np.int64)
        bump_counts = np.empty(0, np.int32)
        if len(bump_ids):
            ids = np.asarray(bump_ids, dtype=np.int64)
            uniq, counts = np.unique(ids, return_counts=True)
            rows = self.perm[uniq]
            if rows.min() < 0:
                return False
            bump_rows, bump_counts = rows, counts.astype(np.int32)
            # host truth for future repacks: nothing — node epochs live only
            # on device + dense mirror; shard edge lists carry captured
            # epochs, which bumps do not rewrite
        e_rows = np.empty(0, np.int64)
        e_prod = np.empty(0, np.int32)
        e_bslot = np.empty(0, np.int32)
        e_bit = np.empty(0, np.int32)
        e_dst = np.empty(0, np.int32)
        e_lsrc = np.empty(0, np.int32)
        e_ep = np.empty(0, np.int32)
        send_writes: List[Tuple[int, int, int, int]] = []  # (p, c, j, wl) intra
        self._hsend_writes = []
        grew = False  # defer the grow re-uploads to ONE transfer pre-dispatch
        if len(add_u):
            u = np.asarray(add_u, dtype=np.int64)
            v = np.asarray(add_v, dtype=np.int64)
            ep = np.asarray(add_ep, dtype=np.int32)
            if (u >= self.n_nodes).any() or (v >= self.n_nodes).any():
                return False  # nodes born after the build: rebuild
            ips = self.placement.ids_per_shard
            u_rows = self.perm[u]
            v_rows = self.perm[v]
            if len(u_rows) and (u_rows.min() < 0 or v_rows.min() < 0):
                return False
            shards = v // ips
            devs = (v_rows // self.n_local).astype(np.int64)
            # pre-scan the edge slack so e_rows are computed against ONE
            # final e_cap (a mid-batch grow would mix two layouts)
            uds, ucounts = np.unique(devs, return_counts=True)
            need_e = int(
                max(
                    self._dev_edge_count[d] + k
                    for d, k in zip(uds.tolist(), ucounts.tolist())
                )
            )
            if need_e > self.e_cap:
                if not self._try_grow("edge", need_e, upload=False):
                    return False  # edge slack exhausted: rebuild rung
                grew = True
            er, eP, eS, eb, ed, el, ee = [], [], [], [], [], [], []
            bucket_need = 0
            hbucket_need = 0
            for d in uds.tolist():
                sel = devs == d
                k = int(sel.sum())
                base = int(self._dev_edge_count[d])
                self._dev_edge_count[d] = base + k
                rows = d * self.e_cap + base + np.arange(k, dtype=np.int64)
                ur, vr = u_rows[sel], v_rows[sel]
                er.append(rows)
                eb.append((ur & 31).astype(np.int32))
                ed.append((vr - d * self.n_local).astype(np.int32))
                el.append(
                    np.where(
                        ur // self.n_local == d, ur - d * self.n_local, self.n_local
                    ).astype(np.int32)
                )
                ee.append(ep[sel])
                if self.exchange in ("tree", "gather"):
                    eP.append(np.zeros(k, np.int32))
                    eS.append((ur >> 5).astype(np.int32))
                else:
                    prod = (ur // self.n_local).astype(np.int64)
                    wl = (ur >> 5) - prod * self.w_local
                    my_host = d // self.dph
                    prods = np.empty(k, dtype=np.int64)
                    slots = np.empty(k, dtype=np.int64)
                    built = self._buckets[d]
                    patch_slots = self._patch_slots[d]
                    fill = self._bucket_fill[d]
                    for i, (p, w) in enumerate(zip(prod.tolist(), wl.tolist())):
                        if self.exchange == "hier" and p // self.dph != my_host:
                            # cross-host edge: slot in the (H, G) host
                            # bucket, append-only (other consumers' slots
                            # never shift)
                            h = p // self.dph
                            key = (h, my_host)
                            wg = p * self.w_local + w
                            hb = self._hbuckets.get(key)
                            j = None
                            if hb is not None and len(hb):
                                pos = int(np.searchsorted(hb, wg))
                                if pos < len(hb) and hb[pos] == wg:
                                    j = pos
                            if j is None:
                                pslots = self._hpatch_slots.setdefault(key, {})
                                j = pslots.get(wg)
                                if j is None:
                                    j = self._hbucket_fill.get(
                                        key, len(hb) if hb is not None else 0
                                    )
                                    pslots[wg] = j
                                    self._hbucket_fill[key] = j + 1
                                    self._hsend_writes.append((p, my_host, j, w))
                            hbucket_need = max(hbucket_need, j + 1)
                            prods[i] = self.n_dev + h
                            slots[i] = j
                            continue
                        bucket = built.get(p)
                        j = None
                        if bucket is not None and len(bucket):
                            pos = int(np.searchsorted(bucket, w))
                            if pos < len(bucket) and bucket[pos] == w:
                                j = pos
                        if j is None:
                            j = patch_slots.get((p, w))
                        if j is None:
                            j = fill.get(p, 0)
                            patch_slots[(p, w)] = j
                            fill[p] = j + 1
                            send_writes.append((p, d, j, w))
                        bucket_need = max(bucket_need, j + 1)
                        prods[i] = p
                        slots[i] = j
                    eP.append(prods.astype(np.int32))
                    eS.append(slots.astype(np.int32))
                # host truth for future repacks
                for s in np.unique(shards[sel]).tolist():
                    ss = sel & (shards == s)
                    ent = self._shard_edges.setdefault(
                        int(s),
                        [np.empty(0, np.int64), np.empty(0, np.int64), np.empty(0, np.int32)],
                    )
                    ent[0] = np.concatenate([ent[0], u[ss]])
                    ent[1] = np.concatenate([ent[1], v[ss]])
                    ent[2] = np.concatenate([ent[2], ep[ss]])
                # mirror into host edge arrays
                self._h_eprod[rows] = eP[-1]
                self._h_ebslot[rows] = eS[-1]
                self._h_ebit[rows] = eb[-1]
                self._h_edst[rows] = ed[-1]
                self._h_elsrc[rows] = el[-1]
                self._h_eep[rows] = ee[-1]
            # bucket growth AFTER slot assignment (slots are cap-independent
            # — only the flat table rows below depend on the final caps)
            if bucket_need > self.bucket_cap:
                if not self._try_grow("bucket", bucket_need, upload=False):
                    return False
                grew = True
            if hbucket_need > self.hbucket_cap:
                if not self._try_grow("hbucket", hbucket_need, upload=False):
                    return False
                grew = True
            if grew:
                # one transfer for every grow this batch: the fused dispatch
                # below scatters into device tables of the FINAL shapes
                self._upload_edges()
            e_rows = np.concatenate(er) if er else e_rows
            e_prod = np.concatenate(eP) if eP else e_prod
            e_bslot = np.concatenate(eS) if eS else e_bslot
            e_bit = np.concatenate(eb) if eb else e_bit
            e_dst = np.concatenate(ed) if ed else e_dst
            e_lsrc = np.concatenate(el) if el else e_lsrc
            e_ep = np.concatenate(ee) if ee else e_ep
        # materialize the send-table writes with the FINAL capacities
        s_rows = np.empty(0, np.int64)
        s_vals = np.empty(0, np.int32)
        if send_writes:
            if self.exchange == "a2a":
                s_rows = np.asarray(
                    [(p * self.n_dev + c) * self.bucket_cap + j for p, c, j, _w in send_writes],
                    dtype=np.int64,
                )
            else:  # hier intra: row p*dph + local consumer index
                s_rows = np.asarray(
                    [
                        (p * self.dph + (c % self.dph)) * self.bucket_cap + j
                        for p, c, j, _w in send_writes
                    ],
                    dtype=np.int64,
                )
            s_vals = np.asarray([w for _p, _c, _j, w in send_writes], dtype=np.int32)
            flat = self._h_send.reshape(-1)
            flat[s_rows] = s_vals
        hs_rows = np.empty(0, np.int64)
        hs_vals = np.empty(0, np.int32)
        if self._hsend_writes:
            hs_rows = np.asarray(
                [
                    (p * self.n_hosts + g) * self.hbucket_cap + j
                    for p, g, j, _w in self._hsend_writes
                ],
                dtype=np.int64,
            )
            hs_vals = np.asarray(
                [w for _p, _g, _j, w in self._hsend_writes], dtype=np.int32
            )
            hflat = self._h_hsend.reshape(-1)
            hflat[hs_rows] = hs_vals
            self._hsend_writes = []
        if send_writes or len(hs_rows):
            # new bucket words may be cross-host in EITHER mode (a2a routes
            # cross-host pairs through the same per-(p, c) buckets) — keep
            # fusion_mesh_cross_host_words_total's per-level unit honest
            self._recount_cross_words()
        if not len(bump_rows) and not len(e_rows):
            return True
        # ONE fused dispatch for the whole batch — pad each index family to
        # a pow2 width (OOB pads dropped) so program shapes cache
        def _pad(a, fill, dtype=np.int64):
            w = max(64, 1 << int(max(len(a), 1) - 1).bit_length())
            out = np.full(w, fill, dtype=dtype)
            out[: len(a)] = a
            return out

        pb = _pad(bump_rows, self.n_global)
        pbc = _pad(bump_counts, 0, np.int32)
        pe = _pad(e_rows, self.n_dev * self.e_cap)
        pep = _pad(e_prod, 0, np.int32)
        pes = _pad(e_bslot, 0, np.int32)
        peb = _pad(e_bit, 0, np.int32)
        ped = _pad(e_dst, self.n_local, np.int32)
        pel = _pad(e_lsrc, self.n_local, np.int32)
        pee = _pad(e_ep, -1, np.int32)
        ps = _pad(s_rows, self._h_send.size)
        psv = _pad(s_vals, self.w_local, np.int32)
        ph = _pad(hs_rows, self._h_hsend.size)
        phv = _pad(hs_vals, self.w_local, np.int32)
        key = (len(pb), len(pe), len(ps), len(ph))
        fn = self._patch_cache.get(key)
        if fn is None:
            fn = self._build_patch()
            self._patch_cache[key] = fn
        (
            self.g_node_epoch, self.g_eprod, self.g_ebslot, self.g_ebit,
            self.g_edst, self.g_elsrc, self.g_eep, self.g_send, self.g_hsend,
        ) = fn(
            self.g_node_epoch, self.g_eprod, self.g_ebslot, self.g_ebit,
            self.g_edst, self.g_elsrc, self.g_eep, self.g_send, self.g_hsend,
            self._host_arg(pb), self._host_arg(pbc), self._host_arg(pe),
            self._host_arg(pep), self._host_arg(pes), self._host_arg(peb),
            self._host_arg(ped), self._host_arg(pel), self._host_arg(pee),
            self._host_arg(ps), self._host_arg(psv), self._host_arg(ph),
            self._host_arg(phv),
        )
        self._sync(self.g_node_epoch, self.g_send)
        self.patches += 1
        self.patch_dispatches += 1
        return True

    def _build_patch(self):
        node_sh, edge_sh, send_sh = self._node_sh, self._edge_sh, self._send_sh

        @jax.jit
        def patch(nep, eprod, ebslot, ebit, edst, elsrc, eep, send, hsend,
                  b_rows, b_counts, e_rows, e_prod, e_bslot, e_bit, e_dst,
                  e_lsrc, e_ep, s_rows, s_vals, h_rows, h_vals):
            nep = nep.at[b_rows].add(b_counts, mode="drop")
            eprod = eprod.at[e_rows].set(e_prod, mode="drop")
            ebslot = ebslot.at[e_rows].set(e_bslot, mode="drop")
            ebit = ebit.at[e_rows].set(e_bit, mode="drop")
            edst = edst.at[e_rows].set(e_dst, mode="drop")
            elsrc = elsrc.at[e_rows].set(e_lsrc, mode="drop")
            eep = eep.at[e_rows].set(e_ep, mode="drop")
            flat = send.reshape(-1).at[s_rows].set(s_vals, mode="drop")
            hflat = hsend.reshape(-1).at[h_rows].set(h_vals, mode="drop")
            return (
                lax.with_sharding_constraint(nep, node_sh),
                lax.with_sharding_constraint(eprod, edge_sh),
                lax.with_sharding_constraint(ebslot, edge_sh),
                lax.with_sharding_constraint(ebit, edge_sh),
                lax.with_sharding_constraint(edst, edge_sh),
                lax.with_sharding_constraint(elsrc, edge_sh),
                lax.with_sharding_constraint(eep, edge_sh),
                lax.with_sharding_constraint(flat.reshape(send.shape), send_sh),
                lax.with_sharding_constraint(hflat.reshape(hsend.shape), send_sh),
            )

        return patch

    # ------------------------------------------------------------------ snapshots
    def export_shard_state(self, local_only: bool = False) -> dict:
        """Per-device-shard node state keyed by VIRTUAL SHARD id (the unit
        that survives a reshard): checkpoint/durable.py stores this so a
        warm restart re-pins each shard under whatever placement the
        restarting process derives — layout-independent by construction.
        ``local_only=True`` exports only the shards whose owner device is
        on THIS host process (the per-host snapshot unit of the multihost
        chaos ladder)."""
        ep = self._fetch(self.g_node_epoch)
        inv = self._fetch(self.g_invalid)
        pl = self.placement
        my_host = None
        if local_only and self._procs > 1:
            import jax as _jax

            my_host = _jax.process_index()
        shards: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        for s in range(pl.shard_map.n_shards):
            if pl.shard_dev[s] < 0:
                continue
            if my_host is not None and int(pl.shard_dev[s]) // self.dph != my_host:
                continue
            lo = s * pl.ids_per_shard
            n = min(pl.ids_per_shard, self.n_nodes - lo)
            if n <= 0:
                continue
            base = pl.row_of_shard(s)
            shards[s] = (ep[base : base + n].copy(), inv[base : base + n].copy())
        return {
            "epoch": pl.epoch,
            "n_nodes": self.n_nodes,
            "n_shards": pl.shard_map.n_shards,
            "shards": shards,
        }

    def import_shard_state(self, snap: dict) -> int:
        """Re-pin snapshotted shard states under THIS graph's placement.
        Returns the number of shards restored (shards the snapshot lacks
        keep their built state)."""
        pl = self.placement
        if snap.get("n_nodes") != self.n_nodes or snap.get("n_shards") != pl.shard_map.n_shards:
            # shard keying is only meaningful under the SAME (n_nodes, V)
            # geometry — ids_per_shard derives from both, and restoring a
            # wider snapshot would write past a shard's slot into its
            # neighbour's rows (silent cross-shard corruption). Refuse.
            raise ValueError(
                f"mesh shard snapshot geometry (n_nodes={snap.get('n_nodes')}, "
                f"n_shards={snap.get('n_shards')}) does not match this graph "
                f"({self.n_nodes}, {pl.shard_map.n_shards}); cold-build instead"
            )
        ep = self._fetch(self.g_node_epoch).copy()
        inv = self._fetch(self.g_invalid).copy()
        restored = 0
        for s, (sep, sinv) in snap["shards"].items():
            s = int(s)
            if s >= pl.shard_map.n_shards or pl.shard_dev[s] < 0:
                continue
            base = pl.row_of_shard(s)
            # belt on top of the geometry check: never write past the
            # shard's real-id extent
            n = min(len(sep), max(self.n_nodes - s * pl.ids_per_shard, 0), pl.slot_rows)
            ep[base : base + n] = sep[:n]
            inv[base : base + n] = sinv[:n]
            restored += 1
        self.g_node_epoch = self._put(ep, self._node_sh)
        self.g_invalid = self._put(inv, self._node_sh)
        return restored

    def _check_usable(self) -> None:
        if self.broken:
            raise PlacementError(
                "routed graph broken by a failed in-place reshard; rebuild"
            )

    # ------------------------------------------------------------------ stats
    def stats(self) -> dict:
        return {
            "exchange": self.exchange,
            "n_dev": self.n_dev,
            "hosts": self.n_hosts,
            "devices_per_host": self.dph,
            "n_nodes": self.n_nodes,
            "n_global": self.n_global,
            "e_cap": self.e_cap,
            "bucket_cap": self.bucket_cap,
            "hbucket_cap": self.hbucket_cap,
            "placement_epoch": self.placement.epoch,
            "waves_run": self.waves_run,
            "exchange_levels_total": self.levels_total,
            "exchange_async": self.exchange_async,
            "async_depth": self.async_depth,
            "quiescence_checks": self.quiescence_checks,
            "spec_levels_total": self.spec_levels_total,
            "tree_fallbacks": self.tree_fallbacks,
            "shard_moves": self.shard_moves,
            "cross_host_moves": self.cross_host_moves,
            "patches": self.patches,
            "patch_dispatches": self.patch_dispatches,
            "bucket_resizes": self.bucket_resizes,
            "hier_fallbacks": self.hier_fallbacks,
            "resize_detail": dict(self.resize_detail),
            "cross_host_words": self.cross_host_words,
            "cross_words_per_level": self.cross_words_per_level,
        }
