"""Symbol — interned string identity (src/Stl/Text/Symbol.cs).

The reference's ``Symbol`` is a struct wrapping a string with a cached hash
so dictionary keys (service names, method names, peer keys) compare by
reference after interning. CPython caches ``str.__hash__``; ``Symbol`` adds
identity interning for arbitrary strings (weak table, so dynamic symbols
don't pin memory) plus value semantics matching the reference (empty
symbol, truthiness, ordering).
"""
from __future__ import annotations

import weakref

__all__ = ["Symbol"]


class Symbol(str):
    """Interned string with value semantics; ``Symbol('') == Symbol.EMPTY``.
    Construction interns: ``Symbol(x) is Symbol(x)`` for equal inputs, so
    symbol comparisons in hot maps are pointer checks. The intern table
    holds weak references — dynamic symbols (per-session keys) are
    collectable once unreferenced."""

    __slots__ = ("__weakref__",)

    EMPTY: "Symbol"
    _interned: "weakref.WeakValueDictionary[str, Symbol]" = weakref.WeakValueDictionary()

    def __new__(cls, value: object = "") -> "Symbol":
        if isinstance(value, Symbol):
            return value
        s = str(value)
        sym = cls._interned.get(s)
        if sym is None:
            sym = super().__new__(cls, s)
            cls._interned[s] = sym
        return sym

    @property
    def value(self) -> str:
        return str(self)

    @property
    def is_empty(self) -> bool:
        return len(self) == 0

    def __repr__(self) -> str:
        return f"Symbol({str.__repr__(self)})"


Symbol.EMPTY = Symbol("")
