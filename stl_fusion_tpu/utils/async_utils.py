"""Async primitives: AsyncEvent chains, keyed lock sets, channels.

TPU-native re-expression of the reference's L0 async toolkit:
- ``AsyncEvent<T>`` (src/Stl/Async/AsyncEvent.cs) — an immutable linked list of
  versions, each awaitable for the next; used for connection-state streams.
- ``AsyncLockSet<TKey>`` (src/Stl/Locking/AsyncLockSet.cs:8-31) — striped
  per-key async locks with reentry checking; the single-flight gate of the
  compute pipeline.
- ``ChannelPair`` / ``create_twisted`` (src/Stl/Channels/ChannelPair.cs) — the
  in-memory duplex transport the RPC test harness runs on.
"""
from __future__ import annotations

import asyncio
import contextvars
import logging
from typing import Any, AsyncIterator, Generic, Hashable, Optional, Tuple, TypeVar

log = logging.getLogger("stl_fusion_tpu")

T = TypeVar("T")

__all__ = [
    "AsyncEvent",
    "AsyncLockSet",
    "LockReentryError",
    "Channel",
    "ChannelClosedError",
    "ChannelPair",
    "TaskSet",
    "create_twisted_pair",
]


class TaskSet:
    """Lifecycle owner for fire-and-forget tasks (the fusionlint FL003
    contract): ``spawn()`` keeps a strong reference until the task settles
    — the event loop holds tasks weakly, so a bare ``create_task(...)``
    can be garbage-collected mid-flight — and teardown has one handle to
    cancel every in-flight side task instead of leaking them past their
    owner's close (the PR 8/10 ghost-session / leaked-pin class).

    A failed task is logged by default (the bare-``create_task`` shape at
    least produced asyncio's never-retrieved traceback; owning the task
    must not make failures QUIETER) — pass ``on_error=`` to count or
    contain instead. Spawning after ``cancel()`` raises ``RuntimeError``
    so a closed owner can't quietly restart its side work.
    """

    __slots__ = ("_tasks", "_name", "_closed", "_on_error")

    def __init__(self, name: str = "task-set", on_error=None):
        self._tasks: set = set()
        self._name = name
        self._closed = False
        self._on_error = on_error

    def __len__(self) -> int:
        return len(self._tasks)

    @property
    def closed(self) -> bool:
        return self._closed

    def spawn(self, coro, name: Optional[str] = None) -> "asyncio.Task":
        if self._closed:
            coro.close()  # don't leave a never-awaited coroutine warning
            raise RuntimeError(f"TaskSet {self._name!r} is closed")
        task = asyncio.get_event_loop().create_task(
            coro, name=name or f"{self._name}:task"
        )
        self._tasks.add(task)
        task.add_done_callback(self._reap)
        return task

    def _reap(self, task: "asyncio.Task") -> None:
        self._tasks.discard(task)
        if task.cancelled():
            return
        exc = task.exception()
        if exc is None:
            return
        if self._on_error is not None:
            try:
                self._on_error(task, exc)
            except Exception:
                pass  # a raising error hook inside a done-callback must not escape
        else:
            log.error(
                "task-set %s: task %s failed", self._name, task.get_name(),
                exc_info=exc,
            )

    def cancel(self) -> int:
        """Cancel every in-flight task and close the set. Returns how many
        were still running (teardown accounting)."""
        self._closed = True
        pending = [t for t in self._tasks if not t.done()]
        for t in pending:
            t.cancel()
        return len(pending)

    async def aclose(self) -> None:
        """``cancel()`` then await the stragglers' completion."""
        self.cancel()
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)


class AsyncEvent(Generic[T]):
    """One immutable version in an awaitable chain.

    ``latest()`` walks to the newest version; ``when_next()`` awaits the
    successor; a producer appends with ``create_next(value)``. Consumers can
    therefore never miss a transition — they replay the chain at their own
    pace, exactly like the reference's connection-state sequence
    (RpcPeer.cs:240-302).
    """

    __slots__ = ("value", "_next", "_next_ready")

    def __init__(self, value: T):
        self.value = value
        self._next: Optional["AsyncEvent[T]"] = None
        self._next_ready: asyncio.Event = asyncio.Event()

    @property
    def is_latest(self) -> bool:
        return self._next is None

    def next_or_none(self) -> Optional["AsyncEvent[T]"]:
        return self._next

    def latest(self) -> "AsyncEvent[T]":
        node = self
        while node._next is not None:
            node = node._next
        return node

    def create_next(self, value: T) -> "AsyncEvent[T]":
        """Append a new version after the LATEST node and return it."""
        tail = self.latest()
        nxt = AsyncEvent(value)
        tail._next = nxt
        tail._next_ready.set()
        return nxt

    async def when_next(self) -> "AsyncEvent[T]":
        await self._next_ready.wait()
        assert self._next is not None
        return self._next

    async def changes(self) -> AsyncIterator[T]:
        node = self
        while True:
            yield node.value
            node = await node.when_next()

    async def when(self, predicate) -> "AsyncEvent[T]":
        node = self
        while not predicate(node.value):
            node = await node.when_next()
        return node

    def __repr__(self) -> str:
        return f"AsyncEvent({self.value!r}, latest={self.is_latest})"


class LockReentryError(RuntimeError):
    """Raised when a task re-acquires a key it already holds (CheckedFail)."""


_held_keys: contextvars.ContextVar[frozenset] = contextvars.ContextVar(
    "stl_fusion_tpu_held_lock_keys", default=frozenset()
)


class AsyncLockSet:
    """Per-key asyncio locks, created on demand and dropped when uncontended.

    Reentry from the same task context raises LockReentryError — mirroring the
    reference's ``LockReentryMode.CheckedFail`` used by the compute
    single-flight path (ComputedRegistry.cs:31,47).
    """

    def __init__(self, name: str = "locks"):
        self._name = name
        self._locks: dict[Hashable, Tuple[asyncio.Lock, int]] = {}

    def __len__(self) -> int:
        return len(self._locks)

    def lock(self, key: Hashable) -> "_LockScope":
        return _LockScope(self, key)

    def _acquire_entry(self, key: Hashable) -> asyncio.Lock:
        entry = self._locks.get(key)
        if entry is None:
            lock = asyncio.Lock()
            self._locks[key] = (lock, 1)
            return lock
        lock, refs = entry
        self._locks[key] = (lock, refs + 1)
        return lock

    def _release_entry(self, key: Hashable) -> None:
        lock, refs = self._locks[key]
        if refs <= 1:
            del self._locks[key]
        else:
            self._locks[key] = (lock, refs - 1)


class _LockScope:
    __slots__ = ("_set", "_key", "_lock", "_token")

    def __init__(self, lock_set: AsyncLockSet, key: Hashable):
        self._set = lock_set
        self._key = key
        self._lock: Optional[asyncio.Lock] = None
        self._token = None

    async def __aenter__(self):
        held = _held_keys.get()
        marker = (id(self._set), self._key)
        if marker in held:
            raise LockReentryError(
                f"reentrant acquisition of {self._key!r} in lock set {self._set._name!r}"
            )
        self._lock = self._set._acquire_entry(self._key)
        try:
            await self._lock.acquire()
        except BaseException:
            self._set._release_entry(self._key)
            self._lock = None
            raise
        self._token = _held_keys.set(held | {marker})
        return self

    async def __aexit__(self, *exc):
        if self._token is not None:
            _held_keys.reset(self._token)
            self._token = None
        if self._lock is not None:
            self._lock.release()
            self._set._release_entry(self._key)
            self._lock = None
        return False


class ChannelClosedError(Exception):
    pass


class Channel(Generic[T]):
    """Bounded async channel with explicit close (≈ System.Threading.Channels)."""

    def __init__(self, maxsize: int = 0):
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self._closed = False
        self._close_error: Optional[BaseException] = None

    @property
    def is_closed(self) -> bool:
        return self._closed

    async def send(self, item: T) -> None:
        if self._closed:
            raise ChannelClosedError(str(self._close_error or "channel closed"))
        await self._queue.put(item)

    def try_send(self, item: T) -> bool:
        if self._closed:
            return False
        try:
            self._queue.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def receive(self) -> T:
        while True:
            if self._closed:
                try:
                    item = self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    raise ChannelClosedError(str(self._close_error or "channel closed"))
            else:
                get = asyncio.ensure_future(self._queue.get())
                try:
                    item = await get
                except asyncio.CancelledError:
                    get.cancel()
                    raise
            if item is _CLOSED_SENTINEL:
                # propagate the wake-up to other blocked receivers, then report closed
                try:
                    self._queue.put_nowait(_CLOSED_SENTINEL)
                except asyncio.QueueFull:
                    pass
                raise ChannelClosedError(str(self._close_error or "channel closed"))
            return item

    def close(self, error: Optional[BaseException] = None) -> None:
        self._closed = True
        self._close_error = error
        # wake any blocked receiver
        try:
            self._queue.put_nowait(_CLOSED_SENTINEL)
        except asyncio.QueueFull:
            pass

    async def __aiter__(self) -> AsyncIterator[T]:
        while True:
            try:
                yield await self.receive()
            except ChannelClosedError:
                return


_CLOSED_SENTINEL: Any = object()


class ChannelPair(Generic[T]):
    """A reader/writer pair of channels forming one endpoint of a duplex link."""

    def __init__(self, reader: Channel, writer: Channel):
        self.reader = reader
        self.writer = writer

    def close(self, error: Optional[BaseException] = None) -> None:
        self.reader.close(error)
        self.writer.close(error)


def create_twisted_pair(maxsize: int = 128) -> Tuple[ChannelPair, ChannelPair]:
    """Two endpoints wired so one side's writer is the other side's reader.

    The in-memory transport for RPC protocol tests (ChannelPair.CreateTwisted,
    src/Stl/Channels/ChannelPair.cs; used by Stl.Rpc/Testing/RpcTestClient.cs).
    """
    a_to_b: Channel = Channel(maxsize)
    b_to_a: Channel = Channel(maxsize)
    return ChannelPair(reader=b_to_a, writer=a_to_b), ChannelPair(reader=a_to_b, writer=b_to_a)
