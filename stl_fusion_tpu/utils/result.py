"""Result[T] — value-or-error union.

TPU-native re-expression of the reference's ``Result<T>`` (src/Stl/Result.cs):
an immutable pair ``(value, error)`` where exactly one side is meaningful.
Computed nodes store their output as a Result so errors are memoized and
propagated through the dependency graph the same way values are.
"""
from __future__ import annotations

from typing import Any, Callable, Generic, Optional, TypeVar

T = TypeVar("T")
U = TypeVar("U")

__all__ = ["Result", "ok", "error"]


class Result(Generic[T]):
    """Immutable value-or-error union."""

    __slots__ = ("_value", "_error")

    def __init__(self, value: Optional[T] = None, error: Optional[BaseException] = None):
        self._value = value
        self._error = error

    # -- constructors ------------------------------------------------------
    @staticmethod
    def ok(value: T) -> "Result[T]":
        return Result(value=value)

    @staticmethod
    def err(exc: BaseException) -> "Result[Any]":
        if exc is None:
            raise ValueError("error must not be None")
        return Result(error=exc)

    @staticmethod
    def capture(fn: Callable[[], T]) -> "Result[T]":
        try:
            return Result.ok(fn())
        except Exception as e:  # noqa: BLE001 - memoize any error
            return Result.err(e)

    # -- accessors ---------------------------------------------------------
    @property
    def has_value(self) -> bool:
        return self._error is None

    @property
    def has_error(self) -> bool:
        return self._error is not None

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    @property
    def value(self) -> T:
        """Return the value, raising the stored error if there is one."""
        if self._error is not None:
            raise self._error
        return self._value  # type: ignore[return-value]

    @property
    def value_or_default(self) -> Optional[T]:
        return None if self._error is not None else self._value

    def unwrap(self) -> T:
        return self.value

    # -- combinators -------------------------------------------------------
    def map(self, fn: Callable[[T], U]) -> "Result[U]":
        if self._error is not None:
            return Result(error=self._error)
        return Result.capture(lambda: fn(self._value))  # type: ignore[arg-type]

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Result):
            return NotImplemented
        if self.has_error != other.has_error:
            return False
        if self.has_error:
            # errors compare by type + args (exceptions aren't value-comparable)
            return (
                type(self._error) is type(other._error)
                and self._error.args == other._error.args  # type: ignore[union-attr]
            )
        return self._value == other._value

    def __hash__(self) -> int:
        if self.has_error:
            return hash((type(self._error), self._error.args))  # type: ignore[union-attr]
        try:
            return hash(self._value)
        except TypeError:
            return hash(id(self._value))

    def __repr__(self) -> str:
        if self.has_error:
            return f"Result.err({self._error!r})"
        return f"Result.ok({self._value!r})"


def ok(value: T) -> Result[T]:
    return Result.ok(value)


def error(exc: BaseException) -> Result[Any]:
    return Result.err(exc)
