"""L0 substrate: results, versions, clocks, async primitives, timers.

TPU-native re-expression of the reference's base library (src/Stl/ — see
SURVEY.md §2.9). Everything above (computed graph, states, commands, RPC,
device graph mirror) builds on these.
"""
from .async_chain import AsyncChain, RetryDelaySeq, WorkerBase
from .async_utils import (
    AsyncEvent,
    AsyncLockSet,
    Channel,
    ChannelClosedError,
    ChannelPair,
    LockReentryError,
    create_twisted_pair,
)
from .caching import ComputingCache, FastComputingCache, FileSystemCache
from .collections import OptionSet, RecentlySeenMap
from .concurrency import StochasticCounter
from .errors import ExceptionInfo, RemoteError, ServiceError, TransientError, register_exception_type
from .ltag import ClockBasedVersionGenerator, LTag, LTagVersionGenerator, VersionGenerator
from .moment import CpuClock, Moment, MomentClock, MomentClockSet, SystemClock, TestClock
from .result import Result, error, ok
from .requirements import MUST_EXIST, Requirement, RequirementError, must_exist
from .serialization import WireSerializer, decode, dumps, encode, loads, register_wire_type, wire_type
from .text import Symbol
from .timer_set import ConcurrentTimerSet

__all__ = [
    "AsyncChain", "RetryDelaySeq", "WorkerBase",
    "AsyncEvent", "AsyncLockSet", "Channel", "ChannelClosedError", "ChannelPair",
    "LockReentryError", "create_twisted_pair",
    "OptionSet", "RecentlySeenMap",
    "ComputingCache", "FastComputingCache", "FileSystemCache", "StochasticCounter",
    "MUST_EXIST", "Requirement", "RequirementError", "must_exist", "Symbol",
    "ExceptionInfo", "RemoteError", "ServiceError", "TransientError", "register_exception_type",
    "ClockBasedVersionGenerator", "LTag", "LTagVersionGenerator", "VersionGenerator",
    "CpuClock", "Moment", "MomentClock", "MomentClockSet", "SystemClock", "TestClock",
    "Result", "error", "ok",
    "WireSerializer", "decode", "dumps", "encode", "loads", "register_wire_type", "wire_type",
    "ConcurrentTimerSet",
]
