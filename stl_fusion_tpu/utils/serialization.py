"""Wire serialization: tagged JSON with a type registry.

Re-expression of the reference's serialization stack
(src/Stl/Serialization/ — TextOrBytes, MemoryPack/JSON dual serializers;
src/Stl.Rpc/Configuration/RpcByteArgumentSerializer.cs:8-60). The reference
writes each argument with a polymorphic type prefix; here every non-primitive
value is encoded as ``{"$t": <registered name>, ...fields}``. Dataclasses
register via ``@wire_type``; primitives, lists, dicts, bytes (base64),
LTag, and ExceptionInfo are built in.

JSON keeps the protocol debuggable and host-portable; the payload rides as
UTF-8 bytes (TextOrBytes ≈ ``bytes`` here). A binary codec can be swapped in
per-peer the way the reference swaps MemoryPack for JSON.
"""
from __future__ import annotations

import base64
import dataclasses
import json
from typing import Any, Callable, Dict, Optional, Tuple, Type, TypeVar

from .errors import ExceptionInfo
from .ltag import LTag

T = TypeVar("T")

__all__ = [
    "wire_type",
    "register_wire_type",
    "encode",
    "decode",
    "dumps",
    "loads",
    "deep_tuple",
    "WireSerializer",
]


def deep_tuple(v):
    """Wire decode turns tuples into lists (JSON has no tuple); values used
    as cache/codec keys or replayed method args must re-tuple DEEPLY to be
    hashable again. THE shared helper — remote-table keys, checkpoint codec
    keys, KwArgsTail restore and explain-request args all decode through
    this one definition."""
    return tuple(deep_tuple(x) for x in v) if isinstance(v, list) else v

_BY_NAME: Dict[str, Tuple[Type, Callable[[Any], dict], Callable[[dict], Any]]] = {}
_BY_TYPE: Dict[Type, str] = {}


def register_wire_type(
    cls: Type[T],
    name: Optional[str] = None,
    to_dict: Optional[Callable[[T], dict]] = None,
    from_dict: Optional[Callable[[dict], T]] = None,
) -> Type[T]:
    n = name or cls.__name__
    if to_dict is None:
        if not dataclasses.is_dataclass(cls):
            raise TypeError(f"{cls} needs explicit to_dict/from_dict (not a dataclass)")
        fields = [f.name for f in dataclasses.fields(cls)]
        to_dict = lambda obj: {f: getattr(obj, f) for f in fields}  # noqa: E731
        from_dict = lambda d: cls(**d)  # noqa: E731
    _BY_NAME[n] = (cls, to_dict, from_dict)  # type: ignore[arg-type]
    _BY_TYPE[cls] = n
    return cls


def wire_type(name: Optional[str] = None):
    """Class decorator registering a dataclass for wire transport."""

    def deco(cls: Type[T]) -> Type[T]:
        return register_wire_type(cls, name if isinstance(name, str) else None)

    if isinstance(name, type):  # bare @wire_type
        cls, name = name, None
        return register_wire_type(cls)
    return deco


register_wire_type(
    ExceptionInfo, "ExceptionInfo", lambda e: e.to_dict(), lambda d: ExceptionInfo.from_dict(d)
)
register_wire_type(LTag, "LTag", lambda v: {"v": int(v)}, lambda d: LTag(d["v"]))


def _register_ndarray() -> None:
    """numpy arrays travel as raw bytes + dtype + shape (the batch-read
    payload shape — a JSON float list would dominate the wire cost of the
    vectorized read path)."""
    import numpy as np

    register_wire_type(
        np.ndarray,
        "ndarray",
        to_dict=lambda a: {
            "data": a.tobytes(),
            "dtype": str(a.dtype),
            "shape": list(a.shape),
        },
        from_dict=lambda d: np.frombuffer(d["data"], dtype=np.dtype(d["dtype"]))
        .reshape(d["shape"])
        .copy(),
    )


_register_ndarray()


def encode(value: Any) -> Any:
    """Value → JSON-compatible structure with $t tags."""
    if value is None or isinstance(value, (bool, int, float, str)):
        if isinstance(value, int) and type(value) is not int and type(value) is not bool:
            # int subclass (e.g. LTag) — fall through to registered encoding
            pass
        else:
            return value
    t = type(value)
    if t in (list, tuple):
        return [encode(v) for v in value]
    if t is dict:
        return {"$t": "dict", "items": [[encode(k), encode(v)] for k, v in value.items()]}
    if t in (bytes, bytearray, memoryview):
        return {"$t": "bytes", "b64": base64.b64encode(bytes(value)).decode("ascii")}
    name = _BY_TYPE.get(t)
    if name is None:
        for base, n in _BY_TYPE.items():
            if isinstance(value, base):
                name = n
                break
    if name is None:
        raise TypeError(f"type {t.__name__} is not wire-registered; use @wire_type")
    _, to_dict, _ = _BY_NAME[name]
    return {"$t": name, "d": {k: encode(v) for k, v in to_dict(value).items()}}


def decode(data: Any) -> Any:
    if data is None or isinstance(data, (bool, int, float, str)):
        return data
    if isinstance(data, list):
        return [decode(v) for v in data]
    if isinstance(data, dict):
        tag = data.get("$t")
        if tag == "dict":
            return {_hashable(decode(k)): decode(v) for k, v in data["items"]}
        if tag == "bytes":
            return base64.b64decode(data["b64"])
        if tag is None:
            return {k: decode(v) for k, v in data.items()}
        entry = _BY_NAME.get(tag)
        if entry is None:
            raise TypeError(f"unknown wire type {tag!r}")
        _, _, from_dict = entry
        return from_dict({k: decode(v) for k, v in data["d"].items()})
    raise TypeError(f"cannot decode {type(data).__name__}")


def _hashable(v: Any) -> Any:
    return tuple(v) if isinstance(v, list) else v


def dumps(value: Any) -> bytes:
    return json.dumps(encode(value), separators=(",", ":")).encode("utf-8")


def loads(data: bytes) -> Any:
    return decode(json.loads(data.decode("utf-8")))


class WireSerializer:
    """Pluggable serializer facade (per-peer swappable, like the reference)."""

    def dumps(self, value: Any) -> bytes:
        return dumps(value)

    def loads(self, data: bytes) -> Any:
        return loads(data)
