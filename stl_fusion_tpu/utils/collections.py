"""Small collection utilities: RecentlySeenMap, OptionSet.

Re-expressions of src/Stl/Collections/RecentlySeenMap.cs (dedup with
age+count bounds — the operation-completion dedup window) and
src/Stl/Collections/OptionSet.cs (typed per-context property bag used by
CommandContext.Items).

The reference's RefHashSetSlim1-4 inline-storage sets exist to avoid
allocation for tiny edge sets; CPython's ``set`` already pools small tables,
so graph edges here use plain sets — the device-side CSR mirror is where the
real edge-storage optimization lives (stl_fusion_tpu.graph).
"""
from __future__ import annotations

import collections
import time
from typing import Any, Dict, Generic, Hashable, Optional, Tuple, Type, TypeVar

T = TypeVar("T")

__all__ = ["RecentlySeenMap", "OptionSet"]


class RecentlySeenMap(Generic[T]):
    """Bounded has-this-been-seen map: capacity + max-age eviction."""

    def __init__(self, capacity: int = 10_000, max_age: float = 600.0, clock=None):
        self.capacity = capacity
        self.max_age = max_age
        self._clock = clock
        self._entries: "collections.OrderedDict[Hashable, Tuple[float, T]]" = collections.OrderedDict()

    def _now(self) -> float:
        return self._clock.now() if self._clock is not None else time.monotonic()

    def try_add(self, key: Hashable, value: T = None) -> bool:  # type: ignore[assignment]
        """True if key was new (and is now recorded); False if recently seen."""
        self._prune()
        if key in self._entries:
            return False
        self._entries[key] = (self._now(), value)
        return True

    def get(self, key: Hashable) -> Optional[T]:
        entry = self._entries.get(key)
        return entry[1] if entry is not None else None

    def __contains__(self, key: Hashable) -> bool:
        return key in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def _prune(self) -> None:
        now = self._now()
        cutoff = now - self.max_age
        while self._entries:
            key, (ts, _) = next(iter(self._entries.items()))
            if ts < cutoff or len(self._entries) >= self.capacity:
                self._entries.popitem(last=False)
            else:
                break


class OptionSet:
    """Typed property bag: one slot per key (usually a type)."""

    def __init__(self):
        self._items: Dict[Any, Any] = {}

    def get(self, key: Type[T]) -> Optional[T]:
        return self._items.get(key)

    def set(self, value: Any, key: Any = None) -> None:
        self._items[key if key is not None else type(value)] = value

    def remove(self, key: Any) -> None:
        self._items.pop(key, None)

    def __contains__(self, key: Any) -> bool:
        return key in self._items

    def __len__(self) -> int:
        return len(self._items)

    def keys(self):
        return self._items.keys()
