"""LTag — compact int64 version tags for computed nodes.

Re-expression of the reference's ``LTag`` (src/Stl/LTag.cs:14-58) and
``LTagVersionGenerator`` (src/Stl/Versioning/Providers/LTagVersionGenerator.cs:5-21).
A version is a non-zero int64 rendered base-62 with an ``@`` prefix. The
generator never hands out the version it was asked to move past (the
"never repeats current" rule) so an invalidated node can always be told
apart from its recomputed successor.

On the TPU side versions live as an ``int32``/``int64`` lane in the CSR
mirror (see stl_fusion_tpu.graph), so LTag stays a plain int subclass —
zero-copy into jnp arrays.
"""
from __future__ import annotations

import itertools
import random
import time
from typing import Optional

__all__ = ["LTag", "VersionGenerator", "LTagVersionGenerator", "ClockBasedVersionGenerator"]

_BASE62 = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz"
_INT64_MASK = (1 << 63) - 1  # keep versions positive int64 for device arrays


class LTag(int):
    """Non-zero int64 version tag; ``LTag(0)`` is the "no version" sentinel."""

    __slots__ = ()

    @property
    def is_none(self) -> bool:
        return int(self) == 0

    def format(self) -> str:
        n = int(self)
        if n == 0:
            return "@0"
        digits = []
        while n:
            n, r = divmod(n, 62)
            digits.append(_BASE62[r])
        return "@" + "".join(reversed(digits))

    @staticmethod
    def parse(s: str) -> "LTag":
        if not s or s[0] != "@":
            raise ValueError(f"invalid LTag literal: {s!r}")
        n = 0
        for ch in s[1:]:
            n = n * 62 + _BASE62.index(ch)
        return LTag(n)

    def __repr__(self) -> str:
        return self.format()

    __str__ = __repr__


LTag.NONE = LTag(0)  # type: ignore[attr-defined]


class VersionGenerator:
    """Abstract version source."""

    def next(self, current: Optional[LTag] = None) -> LTag:
        raise NotImplementedError


class LTagVersionGenerator(VersionGenerator):
    """Monotonic counter from a random origin; never returns `current` or 0.

    CPython's itertools.count is GIL-atomic, giving a lock-free thread-safe
    source (the reference uses an interlocked increment).
    """

    __slots__ = ("_counter",)

    def __init__(self, seed: Optional[int] = None):
        rng = random.Random(seed)
        start = rng.getrandbits(62) | 1
        self._counter = itertools.count(start)

    def next(self, current: Optional[LTag] = None) -> LTag:
        while True:
            v = LTag(next(self._counter) & _INT64_MASK)
            if v != 0 and (current is None or v != current):
                return v


class ClockBasedVersionGenerator(VersionGenerator):
    """Versions from a nanosecond clock; strictly increasing, never `current`.

    Mirrors src/Stl/Versioning/Providers/ClockBasedVersionGenerator.cs.
    """

    __slots__ = ("_last",)

    def __init__(self):
        self._last = 0

    def next(self, current: Optional[LTag] = None) -> LTag:
        v = time.time_ns() & _INT64_MASK
        if v <= self._last:
            v = self._last + 1
        if current is not None and v == int(current):
            v += 1
        self._last = v
        return LTag(v)
