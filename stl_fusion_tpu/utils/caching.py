"""Standalone single-flight caches (src/Stl/Caching/).

Pre-Fusion-style caches the reference ships alongside the computed graph:

- ``ComputingCache`` (Caching/ComputingCache.cs) — async cache where a miss
  runs the computer exactly once per key while concurrent readers await the
  in-flight task (single-flight via per-key futures).
- ``FastComputingCache`` — same contract, lock-striped fast path.  CPython's
  GIL makes a dict + per-key future already the fast path, so it shares the
  implementation with a smaller default lock granularity.
- ``FileSystemCache`` (Caching/FileSystemCache.cs) — bytes-on-disk cache
  keyed by hashed key, used for durable memoization.
"""
from __future__ import annotations

import asyncio
import hashlib
import os
import tempfile
from typing import Awaitable, Callable, Dict, Generic, Hashable, Optional, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")

__all__ = ["ComputingCache", "FastComputingCache", "FileSystemCache"]


class ComputingCache(Generic[K, V]):
    """Async memoizing cache with single-flight computes.

    ``get(key)`` returns the cached value or awaits the (single) in-flight
    computation for that key; errors are not cached (matching the
    reference's task-removal on failure).
    """

    def __init__(self, computer: Callable[[K], Awaitable[V]], capacity: Optional[int] = None):
        self._computer = computer
        self._capacity = capacity
        self._values: Dict[K, V] = {}
        self._in_flight: Dict[K, "asyncio.Task[V]"] = {}

    def try_get(self, key: K) -> Optional[V]:
        return self._values.get(key)

    def __contains__(self, key: K) -> bool:
        return key in self._values

    def __len__(self) -> int:
        return len(self._values)

    async def get(self, key: K) -> V:
        if key in self._values:
            return self._values[key]
        task = self._in_flight.get(key)
        if task is None:
            # the compute runs in its own task so one caller's cancellation
            # can't poison the other waiters (shield only protects a waiter
            # from its OWN cancellation)
            task = asyncio.ensure_future(self._compute(key))
            self._in_flight[key] = task
        return await asyncio.shield(task)

    async def _compute(self, key: K) -> V:
        try:
            value = await self._computer(key)
        except BaseException:
            self._in_flight.pop(key, None)
            raise
        self._store(key, value)
        self._in_flight.pop(key, None)
        return value

    def invalidate(self, key: K) -> None:
        self._values.pop(key, None)

    def clear(self) -> None:
        self._values.clear()

    def _store(self, key: K, value: V) -> None:
        if self._capacity is not None and len(self._values) >= self._capacity and key not in self._values:
            self._values.pop(next(iter(self._values)))
        self._values[key] = value


class FastComputingCache(ComputingCache[K, V]):
    """Same contract as ComputingCache; kept as a distinct type for parity
    with the reference (Caching/ComputingCache.cs declares both — the fast
    variant differs only in locking strategy, which the GIL subsumes)."""


class FileSystemCache(Generic[K]):
    """Durable bytes cache: one file per key under ``root``.

    Keys are hashed (sha256 hex) into file names, so any hashable/printable
    key works. Values are ``bytes``.
    """

    def __init__(self, root: str, extension: str = ".bin"):
        self.root = root
        self.extension = extension
        os.makedirs(root, exist_ok=True)

    def _path(self, key: K) -> str:
        digest = hashlib.sha256(repr(key).encode()).hexdigest()[:40]
        return os.path.join(self.root, digest + self.extension)

    def try_get(self, key: K) -> Optional[bytes]:
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def set(self, key: K, value: bytes) -> None:
        path = self._path(key)
        # unique tmp per writer: concurrent set() on one key must not share
        # a tmp file, or replace() could publish interleaved bytes
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(value)
            os.replace(tmp, path)  # atomic publish
        except BaseException:
            try:
                os.remove(tmp)
            except FileNotFoundError:
                pass
            raise

    def remove(self, key: K) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def clear(self) -> None:
        for name in os.listdir(self.root):
            if name.endswith(self.extension):
                try:
                    os.remove(os.path.join(self.root, name))
                except FileNotFoundError:
                    pass
