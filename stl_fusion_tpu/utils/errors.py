"""Wire-safe exception transport.

Re-expression of ``ExceptionInfo`` (src/Stl/Serialization/ExceptionInfo.cs):
an exception captured as (type-name, message) that can cross a process
boundary and be reconstructed — as the original type when it's a registered
known type, else as ``RemoteError`` carrying the original type name.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Type

__all__ = ["ExceptionInfo", "RemoteError", "TransientError", "ServiceError", "register_exception_type"]


class RemoteError(Exception):
    """An exception whose concrete type is unknown on this side of the wire."""

    def __init__(self, type_name: str, message: str):
        super().__init__(message)
        self.type_name = type_name

    def __str__(self) -> str:
        return f"{self.type_name}: {super().__str__()}"


class TransientError(Exception):
    """Marker base for retryable failures (≈ ITransientException)."""


class ServiceError(Exception):
    """Generic service-side failure."""


_KNOWN: Dict[str, Type[BaseException]] = {}


def register_exception_type(cls: Type[BaseException], name: Optional[str] = None) -> Type[BaseException]:
    """Register an exception type for faithful wire round-trips. Decorator-friendly."""
    _KNOWN[name or cls.__name__] = cls
    return cls


for _cls in (ValueError, KeyError, LookupError, IndexError, RuntimeError, TypeError,
             NotImplementedError, TimeoutError, PermissionError, ConnectionError,
             TransientError, ServiceError):
    register_exception_type(_cls)


@dataclass(frozen=True)
class ExceptionInfo:
    type_name: str
    message: str

    @staticmethod
    def capture(exc: BaseException) -> "ExceptionInfo":
        if isinstance(exc, RemoteError):
            return ExceptionInfo(exc.type_name, str(Exception.__str__(exc)))
        return ExceptionInfo(type(exc).__name__, str(exc))

    def to_exception(self) -> BaseException:
        cls = _KNOWN.get(self.type_name)
        if cls is not None:
            try:
                return cls(self.message)
            except Exception:  # noqa: BLE001 — constructor mismatch
                pass
        return RemoteError(self.type_name, self.message)

    def to_dict(self) -> dict:
        return {"type": self.type_name, "message": self.message}

    @staticmethod
    def from_dict(d: dict) -> "ExceptionInfo":
        return ExceptionInfo(d["type"], d["message"])
