"""ConcurrentTimerSet — quantized bulk timers over a min-heap.

Re-expression of the reference's ``ConcurrentTimerSet<TTimer>``
(src/Stl/Time/ConcurrentTimerSet.cs:12-38) over ``TimerSet`` +
``RadixHeapSet`` (src/Stl/Collections/RadixHeapSet.cs). Fusion uses two of
these for keep-alive and auto-invalidation (Fusion/Internal/Timeouts.cs:3-34)
with 0.2 s quanta — timers fire in batches on quantum ticks, so millions of
computed nodes share one background task instead of one timer each.

Python build: a single asyncio task per set, a heapq keyed by fire-time, and
a dict for O(1) add-or-update/remove. Clock-aware so TestClock drives it.
"""
from __future__ import annotations

import asyncio
import heapq
import itertools
from typing import Callable, Dict, Generic, Hashable, List, Optional, Tuple, TypeVar

from .moment import CpuClock, MomentClock

T = TypeVar("T", bound=Hashable)

__all__ = ["ConcurrentTimerSet"]


class ConcurrentTimerSet(Generic[T]):
    """Bulk timer set: ``add_or_update(item, fire_at)``; fires ``handler(item)``.

    Items are hashable; re-adding an item moves its deadline (stale heap
    entries are skipped via a sequence check, the standard lazy-deletion
    heap pattern).
    """

    def __init__(
        self,
        handler: Callable[[T], None],
        quanta: float = 0.05,
        clock: Optional[MomentClock] = None,
        name: str = "timers",
    ):
        self._handler = handler
        self._quanta = quanta
        self._clock = clock or CpuClock()
        self._name = name
        self._heap: List[Tuple[float, int, T]] = []
        self._entries: Dict[T, Tuple[int, float]] = {}  # item -> (latest seq, fire_at)
        self._seq = itertools.count()
        self._task: Optional[asyncio.Task] = None
        self._wake: Optional[asyncio.Event] = None
        self._stopped = False

    def __len__(self) -> int:
        return len(self._entries)

    # -- mutation ----------------------------------------------------------
    def add_or_update(self, item: T, fire_at: float) -> None:
        seq = next(self._seq)
        self._entries[item] = (seq, fire_at)
        was_empty = not self._heap
        heapq.heappush(self._heap, (fire_at, seq, item))
        self._ensure_running()
        # the loop ticks every quantum while the heap is non-empty; a wake
        # is only needed to un-park it from the empty-heap idle wait
        if was_empty and self._wake is not None:
            self._wake.set()

    def add_or_update_to_later(self, item: T, fire_at: float, grid: float = 0.0) -> None:
        """Only move the deadline forward (keep-alive renewal semantics).

        Deadlines snap up to a grid — at least the quantum, or the caller's
        coarser ``grid`` — so renewals inside one grid cell are a dict probe
        + compare with no heap churn (the reference's ConcurrentTimer
        quantum dedup, ConcurrentTimerSet.cs:12-38). Keep-alive callers pass
        ``grid = duration/64``: firing up to ~1.6% late is invisible there,
        and it caps heap pushes at 64 per item per lifetime.
        """
        q = self._quanta if grid < self._quanta else grid
        fire_at = (fire_at // q + 1.0) * q
        cur = self._entries.get(item)
        if cur is None or fire_at > cur[1]:
            self.add_or_update(item, fire_at)

    def remove(self, item: T) -> bool:
        return self._entries.pop(item, None) is not None

    # -- loop --------------------------------------------------------------
    def _ensure_running(self) -> None:
        if self._task is None or self._task.done():
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return  # no loop: timers fire via fire_all_due() or on next in-loop add
            self._wake = asyncio.Event()
            self._stopped = False
            self._task = loop.create_task(self._run(), name=f"timer-set:{self._name}")

    async def _run(self) -> None:
        assert self._wake is not None
        while not self._stopped:
            self._fire_due()
            if not self._heap:
                # idle: park until a timer is added
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(), timeout=5.0)
                except asyncio.TimeoutError:
                    if not self._heap:
                        return  # park the task entirely; restarted on next add
                continue
            await self._clock.delay(self._quanta)

    def _fire_due(self) -> None:
        now = self._clock.now()
        while self._heap and self._heap[0][0] <= now:
            _, seq, item = heapq.heappop(self._heap)
            entry = self._entries.get(item)
            if entry is None or entry[0] != seq:
                continue  # stale (updated or removed)
            del self._entries[item]
            try:
                self._handler(item)
            except Exception:  # noqa: BLE001 — timer handlers must not kill the wheel
                pass

    def fire_all_due(self) -> None:
        """Synchronous tick — lets tests drive the wheel with a TestClock."""
        self._fire_due()

    async def stop(self) -> None:
        self._stopped = True
        if self._wake is not None:
            self._wake.set()
        if self._task is not None:
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
