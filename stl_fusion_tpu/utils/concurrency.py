"""Concurrency helpers (src/Stl/Concurrency/).

``StochasticCounter`` (Concurrency/StochasticCounter.cs) — an approximate
event counter that only pays for an atomic increment on a random 1-in-2^k
sample of calls. The reference's ComputedRegistry uses it to trigger pruning
"roughly every N operations" without a contended counter. Under the GIL a
plain int increment is cheap, but the *sampling* contract still matters: the
registry analogue here asks ``increment()`` and gets back a sampled
approximate total (or None when the call wasn't sampled), so prune cadence
matches the reference's stochastic behavior.
"""
from __future__ import annotations

import random
from typing import Optional

__all__ = ["StochasticCounter"]


class StochasticCounter:
    def __init__(self, sample_period_log2: int = 4, rng: Optional[random.Random] = None):
        if not 0 <= sample_period_log2 <= 30:
            raise ValueError("sample_period_log2 must be in [0, 30]")
        self.sample_period = 1 << sample_period_log2
        self._mask = self.sample_period - 1
        self._rng = rng or random.Random()
        self._value = 0

    @property
    def approximate_value(self) -> int:
        return self._value

    @approximate_value.setter
    def approximate_value(self, value: int) -> None:
        self._value = value

    def increment(self) -> Optional[int]:
        """Sampled increment: returns the new approximate total on sampled
        calls (1 in sample_period), None otherwise."""
        if self._rng.getrandbits(32) & self._mask:
            return None
        self._value += self.sample_period
        return self._value

    def decrement(self) -> Optional[int]:
        if self._rng.getrandbits(32) & self._mask:
            return None
        self._value = max(0, self._value - self.sample_period)
        return self._value

    def reset(self) -> None:
        self._value = 0
