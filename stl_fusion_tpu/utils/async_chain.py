"""AsyncChain + WorkerBase — composable background-work lifecycles.

Re-expression of the reference's ``AsyncChain`` (src/Stl/Async/AsyncChain.cs,
AsyncChainExt.cs) and ``WorkerBase``/``ProcessorBase``
(src/Stl/Async/WorkerBase.cs, ProcessorBase.cs). Every background worker in
the reference — graph pruner, op-log reader, RPC peers — is an AsyncChain of
named steps with retry/cycle/delay combinators, hosted by a WorkerBase with
a cancellation-scoped lifetime. Same shape here on asyncio.
"""
from __future__ import annotations

import asyncio
import logging
import random
from dataclasses import dataclass, replace
from typing import Awaitable, Callable, Optional, Sequence

__all__ = ["AsyncChain", "RetryDelaySeq", "WorkerBase"]

log = logging.getLogger("stl_fusion_tpu")


@dataclass(frozen=True)
class RetryDelaySeq:
    """Jittered exponential backoff sequence (src/Stl/Time/RetryDelaySeq.cs)."""

    min_delay: float = 0.5
    max_delay: float = 10.0
    spread: float = 0.1
    multiplier: float = 1.41421356  # sqrt(2), the reference default

    def __getitem__(self, failed_try_count: int) -> float:
        if failed_try_count <= 0:
            return 0.0
        d = self.min_delay * (self.multiplier ** (failed_try_count - 1))
        d = min(d, self.max_delay)
        return max(0.0, d * (1.0 + random.uniform(-self.spread, self.spread)))


@dataclass(frozen=True)
class AsyncChain:
    """A named async step; combinators return new chains (immutable)."""

    name: str
    start: Callable[[], Awaitable[None]]

    async def run(self) -> None:
        await self.start()

    def append_delay(self, delay: float) -> "AsyncChain":
        async def _run() -> None:
            await self.start()
            await asyncio.sleep(delay)

        return replace(self, name=f"{self.name}+delay({delay})", start=_run)

    def retry_forever(self, delays: Optional[RetryDelaySeq] = None) -> "AsyncChain":
        seq = delays or RetryDelaySeq()

        async def _run() -> None:
            failures = 0
            while True:
                try:
                    await self.start()
                    return
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001
                    failures += 1
                    delay = seq[failures]
                    log.debug("%s failed (%s), retry #%d in %.2fs", self.name, e, failures, delay)
                    await asyncio.sleep(delay)

        return replace(self, name=f"{self.name}.retry_forever", start=_run)

    def cycle_forever(self) -> "AsyncChain":
        async def _run() -> None:
            while True:
                await self.start()

        return replace(self, name=f"{self.name}.cycle_forever", start=_run)

    def log_boundary(self, logger: Optional[logging.Logger] = None) -> "AsyncChain":
        lg = logger or log

        async def _run() -> None:
            lg.debug("%s: started", self.name)
            try:
                await self.start()
                lg.debug("%s: completed", self.name)
            except asyncio.CancelledError:
                lg.debug("%s: cancelled", self.name)
                raise
            except Exception:
                lg.exception("%s: failed", self.name)
                raise

        return replace(self, start=_run)

    @staticmethod
    def from_steps(name: str, steps: Sequence["AsyncChain"]) -> "AsyncChain":
        async def _run() -> None:
            await asyncio.gather(*(s.start() for s in steps))

        return AsyncChain(name, _run)


class WorkerBase:
    """Start/stop lifecycle around one background task.

    Subclasses implement ``on_run``; ``start()`` is idempotent; ``stop()``
    cancels and awaits. ``when_stopped()`` exposes completion.
    """

    def __init__(self, name: Optional[str] = None):
        self._worker_name = name or type(self).__name__
        self._task: Optional[asyncio.Task] = None
        self._stop_requested = False

    @property
    def is_running(self) -> bool:
        return self._task is not None and not self._task.done()

    def start(self) -> "WorkerBase":
        if self._task is None or self._task.done():
            self._stop_requested = False
            loop = asyncio.get_event_loop()
            self._task = loop.create_task(self._run_guarded(), name=self._worker_name)
        return self

    async def _run_guarded(self) -> None:
        try:
            await self.on_run()
        except asyncio.CancelledError:
            pass
        except Exception:  # noqa: BLE001
            log.exception("worker %s crashed", self._worker_name)

    async def on_run(self) -> None:
        raise NotImplementedError

    async def stop(self) -> None:
        self._stop_requested = True
        task = self._task
        if task is None:
            return
        # cancel-until-dead: on py ≤ 3.11, asyncio.wait_for SWALLOWS a
        # cancellation when its inner future completes in the same event-loop
        # step (the bpo-42130 family) — a worker parked in wait_for whose
        # wake-up fired exactly at stop() time absorbs the cancel and runs
        # forever, deadlocking the stop() awaiter (observed as a rare hang of
        # the op-log reader restart under chaos). Re-cancel until the task is
        # actually done; asyncio.wait never raises, and _run_guarded consumes
        # the task's own CancelledError, so nothing leaks. _task stays set
        # until the task is REALLY dead — is_running/when_stopped/start must
        # not observe "stopped" while on_run still executes.
        grace = 0.2
        while not task.done():
            task.cancel()
            await asyncio.wait([task], timeout=grace)
            # first re-cancel covers the swallow; after that, escalate the
            # grace so a worker legitimately mid-async-cleanup isn't hammered
            # with a fresh CancelledError every 200 ms
            grace = 1.0
        if self._task is task:
            self._task = None

    async def when_stopped(self) -> None:
        if self._task is not None:
            try:
                await asyncio.shield(self._task)
            except asyncio.CancelledError:
                pass
