"""Moments and clocks.

Re-expression of the reference's ``Moment`` / ``IMomentClock`` / ``CpuClock`` /
``TestClock`` (src/Stl/Time/, src/Stl/Time/Testing/). A Moment is a plain
float of seconds; clocks are swappable so tests control time (the reference's
``UseTestClock`` pattern, tests/Stl.Tests/RpcTestBase.cs:25).
"""
from __future__ import annotations

import asyncio
import time
from typing import Optional

__all__ = ["Moment", "MomentClock", "SystemClock", "CpuClock", "TestClock", "MomentClockSet"]

Moment = float  # seconds


class MomentClock:
    """Abstract clock: now + cancellable async delay."""

    def now(self) -> Moment:
        raise NotImplementedError

    async def delay(self, seconds: float) -> None:
        if seconds > 0:
            await asyncio.sleep(seconds)


class SystemClock(MomentClock):
    """Wall clock (epoch seconds)."""

    def now(self) -> Moment:
        return time.time()


class CpuClock(MomentClock):
    """Monotonic clock — the default for timeouts and timer wheels."""

    def now(self) -> Moment:
        return time.monotonic()


class TestClock(MomentClock):
    """Controllable clock: offset + speed multiplier over the real clock.

    ``advance(dt)`` jumps time forward; pending ``delay`` calls re-check on a
    short real-time quantum so advanced time releases them promptly.
    """

    __test__ = False  # not a pytest class

    def __init__(self, offset: float = 0.0, speed: float = 1.0):
        self._origin = time.monotonic()
        self.offset = offset
        self.speed = speed

    def now(self) -> Moment:
        return (time.monotonic() - self._origin) * self.speed + self.offset

    def advance(self, seconds: float) -> None:
        self.offset += seconds

    async def delay(self, seconds: float) -> None:
        target = self.now() + seconds
        while self.now() < target:
            await asyncio.sleep(min(0.005, max(0.0, (target - self.now()) / max(self.speed, 1e-9))))


class MomentClockSet:
    """The bundle of clocks a hub runs on (system/cpu/ui); swap for tests."""

    def __init__(
        self,
        system: Optional[MomentClock] = None,
        cpu: Optional[MomentClock] = None,
    ):
        self.system = system or SystemClock()
        self.cpu = cpu or CpuClock()

    @staticmethod
    def for_tests(test_clock: Optional[TestClock] = None) -> "MomentClockSet":
        c = test_clock or TestClock()
        return MomentClockSet(system=c, cpu=c)
