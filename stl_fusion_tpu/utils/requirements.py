"""Requirement — declarative value validation (src/Stl/Requirements/).

The reference models "this value must satisfy X or throw a well-known
error" as composable ``Requirement<T>`` objects: ``MustExistRequirement``
(non-null/default check), ``FuncRequirement`` (predicate + error factory),
combined via ``&``. Services use them as ``user.Require(User.MustExist)``.

Here a ``Requirement`` wraps a predicate and an error factory; ``check``
returns the value (for chaining) or raises. ``MUST_EXIST`` rejects ``None``
and empty strings/collections the way the reference's default-value check
rejects CLR defaults.
"""
from __future__ import annotations

from typing import Callable, Generic, Optional, TypeVar

T = TypeVar("T")

__all__ = ["Requirement", "RequirementError", "MUST_EXIST", "must_exist"]


class RequirementError(ValueError):
    """Raised when a required condition does not hold."""


class Requirement(Generic[T]):
    def __init__(
        self,
        predicate: Callable[[T], bool],
        error_factory: Optional[Callable[[T], Exception]] = None,
        description: str = "requirement",
    ):
        self._predicate = predicate
        self._error_factory = error_factory or (
            lambda value: RequirementError(f"{description} failed for {value!r}")
        )
        self.description = description

    def is_satisfied(self, value: T) -> bool:
        try:
            return bool(self._predicate(value))
        except Exception:
            return False

    def check(self, value: T) -> T:
        """Return ``value`` if the requirement holds, else raise."""
        if not self.is_satisfied(value):
            raise self._error_factory(value)
        return value

    def with_error(self, error_factory: Callable[[T], Exception]) -> "Requirement[T]":
        return Requirement(self._predicate, error_factory, self.description)

    def __and__(self, other: "Requirement[T]") -> "Requirement[T]":
        def both(value: T) -> T:
            self.check(value)
            other.check(value)
            return value

        combined: Requirement[T] = Requirement(
            lambda v: self.is_satisfied(v) and other.is_satisfied(v),
            description=f"{self.description} & {other.description}",
        )

        def _raise(value: T) -> Exception:
            try:
                both(value)
            except Exception as e:  # noqa: BLE001 — re-raise whichever side failed
                return e
            return RequirementError(combined.description)

        return combined.with_error(_raise)


def _exists(value: object) -> bool:
    if value is None:
        return False
    try:
        size = len(value)  # type: ignore[arg-type]
    except TypeError:
        return True  # numbers, objects — any non-None scalar exists
    return size > 0  # empty str/bytes/list/dict/set are "missing"


MUST_EXIST: Requirement = Requirement(_exists, description="must exist")


def must_exist(value: Optional[T], what: str = "value") -> T:
    """Shorthand for ``MUST_EXIST.check`` with a named error message."""
    if not _exists(value):
        raise RequirementError(f"{what} is required but missing")
    return value  # type: ignore[return-value]
