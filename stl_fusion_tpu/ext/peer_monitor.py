"""RpcPeerStateMonitor — connection state as a reactive state.

Re-expression of src/Stl.Fusion/Extensions/RpcPeerStateMonitor.cs:6-70:
exposes a peer's connection state (+ reconnects-at) as a MutableState so
UIs can render "reconnecting in 3s…" banners that live-update.
"""
from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Optional

from ..core.hub import FusionHub
from ..rpc.peer import RpcClientPeer
from ..state.mutable import MutableState
from ..utils.async_chain import WorkerBase

__all__ = ["RpcPeerState", "RpcPeerStateMonitor"]


@dataclass(frozen=True)
class RpcPeerState:
    is_connected: bool
    error: Optional[str] = None
    reconnects_at: Optional[float] = None
    #: the peer gave up (unrecoverable connect error): no reconnect is
    #: coming, so UIs should render a hard failure, not a retry banner
    is_terminated: bool = False
    #: circuit-breaker state ("closed"/"open"/"half-open") when a
    #: resilience.PeerCircuitBreaker is installed on the peer, else None —
    #: "open" means the peer is QUARANTINED (dials parked), which UIs should
    #: render differently from an ordinary reconnect countdown
    breaker: Optional[str] = None


class RpcPeerStateMonitor(WorkerBase):
    def __init__(self, peer: RpcClientPeer, hub: Optional[FusionHub] = None):
        super().__init__(f"peer-monitor:{peer.ref}")
        self.peer = peer
        self.state: MutableState = MutableState(
            RpcPeerState(is_connected=False), hub, name=f"peer-state:{peer.ref}"
        )

    async def on_run(self) -> None:
        ev = self.peer.connection_state
        while True:
            s = ev.value
            breaker = getattr(self.peer, "breaker", None)
            self.state.set(
                RpcPeerState(
                    is_connected=s.is_connected,
                    error=str(s.error) if s.error else None,
                    # a terminated peer never retries: suppress any stale
                    # retry timestamp so UIs don't render a reconnect banner
                    reconnects_at=(
                        None if s.is_terminated else getattr(self.peer, "reconnects_at", None)
                    ),
                    is_terminated=s.is_terminated,
                    breaker=breaker.state if breaker is not None else None,
                )
            )
            if breaker is None:
                ev = await ev.when_next()
                continue
            # a breaker transitions WITHOUT a connection event too (open →
            # half-open in the dial gate, half-open → closed on probe-stable
            # timeout) — wake on whichever chain moves first so a recovered
            # peer is never rendered as quarantined until its next disconnect
            conn_next = asyncio.ensure_future(ev.when_next())
            brk_next = asyncio.ensure_future(breaker.changes.latest().when_next())
            try:
                done, _pending = await asyncio.wait(
                    {conn_next, brk_next}, return_when=asyncio.FIRST_COMPLETED
                )
            finally:
                # asyncio.wait never cancels its children — without this, a
                # monitor stopped while parked here leaks both waiter tasks
                # ("Task was destroyed but it is pending!" at loop close)
                for p in (conn_next, brk_next):
                    if not p.done():
                        p.cancel()
            if conn_next in done:
                ev = conn_next.result()
