"""RpcPeerStateMonitor — connection state as a reactive state.

Re-expression of src/Stl.Fusion/Extensions/RpcPeerStateMonitor.cs:6-70:
exposes a peer's connection state (+ reconnects-at) as a MutableState so
UIs can render "reconnecting in 3s…" banners that live-update.
"""
from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Optional

from ..core.hub import FusionHub
from ..rpc.peer import RpcClientPeer
from ..state.mutable import MutableState
from ..utils.async_chain import WorkerBase

__all__ = ["RpcPeerState", "RpcPeerStateMonitor"]


@dataclass(frozen=True)
class RpcPeerState:
    is_connected: bool
    error: Optional[str] = None
    reconnects_at: Optional[float] = None
    #: the peer gave up (unrecoverable connect error): no reconnect is
    #: coming, so UIs should render a hard failure, not a retry banner
    is_terminated: bool = False


class RpcPeerStateMonitor(WorkerBase):
    def __init__(self, peer: RpcClientPeer, hub: Optional[FusionHub] = None):
        super().__init__(f"peer-monitor:{peer.ref}")
        self.peer = peer
        self.state: MutableState = MutableState(
            RpcPeerState(is_connected=False), hub, name=f"peer-state:{peer.ref}"
        )

    async def on_run(self) -> None:
        ev = self.peer.connection_state
        while True:
            s = ev.value
            self.state.set(
                RpcPeerState(
                    is_connected=s.is_connected,
                    error=str(s.error) if s.error else None,
                    # a terminated peer never retries: suppress any stale
                    # retry timestamp so UIs don't render a reconnect banner
                    reconnects_at=(
                        None if s.is_terminated else getattr(self.peer, "reconnects_at", None)
                    ),
                    is_terminated=s.is_terminated,
                )
            )
            ev = await ev.when_next()
