"""Typed messaging helpers over a pluggable broker (Stl.Redis analogue).

Re-expression of src/Stl.Redis/ (RedisDb, RedisPub/RedisSub, RedisQueue,
RedisStreamer, RedisSequenceSet) without binding to a Redis server: the
broker surface is the small abstract ``MessageBroker`` (publish/subscribe
byte channels + atomic counters), with a process-local ``InMemoryBroker``
default; a real Redis/network-backed broker plugs in by implementing the
same surface. All typed helpers serialize via the framework wire format,
mirroring how the reference routes RedisDb values through its serializers.

``BrokerChangeNotifier`` adapts a pub/sub channel to the operation-log
reader's wake-up protocol — the analogue of
Redis/Operations/RedisOperationLogChangeNotifier.cs (SURVEY §2.6).
"""
from __future__ import annotations

import asyncio
from typing import Any, AsyncIterator, Callable, Dict, Generic, List, Optional, TypeVar

from ..utils.serialization import decode, dumps, encode, loads

T = TypeVar("T")

__all__ = [
    "MessageBroker",
    "InMemoryBroker",
    "PubSub",
    "TypedQueue",
    "Streamer",
    "SequenceSet",
    "BrokerChangeNotifier",
]


class MessageBroker:
    """Minimal broker surface: named byte channels, work queues, counters."""

    def publish(self, channel: str, payload: bytes) -> None:
        raise NotImplementedError

    def subscribe(self, channel: str, handler: Callable[[bytes], None]) -> Callable[[], None]:
        """Register a handler; returns an unsubscribe callable."""
        raise NotImplementedError

    def queue_push(self, name: str, payload: bytes) -> None:
        """Append to a broker-resident work queue (each item popped once)."""
        raise NotImplementedError

    async def queue_pop(self, name: str) -> bytes:
        raise NotImplementedError

    def next_value(self, key: str, at_least: int = 0) -> int:
        """Atomic monotone counter (≈ RedisSequenceSet.Next)."""
        raise NotImplementedError

    def reset_value(self, key: str, value: int = 0) -> None:
        raise NotImplementedError


class InMemoryBroker(MessageBroker):
    def __init__(self):
        self._subscribers: Dict[str, List[Callable[[bytes], None]]] = {}
        self._queues: Dict[str, "asyncio.Queue[bytes]"] = {}
        self._counters: Dict[str, int] = {}

    def publish(self, channel: str, payload: bytes) -> None:
        for handler in list(self._subscribers.get(channel, ())):
            handler(payload)

    def subscribe(self, channel: str, handler: Callable[[bytes], None]) -> Callable[[], None]:
        self._subscribers.setdefault(channel, []).append(handler)

        def unsubscribe() -> None:
            handlers = self._subscribers.get(channel, [])
            if handler in handlers:
                handlers.remove(handler)

        return unsubscribe

    def _queue(self, name: str) -> "asyncio.Queue[bytes]":
        q = self._queues.get(name)
        if q is None:
            q = self._queues[name] = asyncio.Queue()
        return q

    def queue_push(self, name: str, payload: bytes) -> None:
        self._queue(name).put_nowait(payload)

    async def queue_pop(self, name: str) -> bytes:
        return await self._queue(name).get()

    def next_value(self, key: str, at_least: int = 0) -> int:
        value = max(self._counters.get(key, 0), at_least) + 1
        self._counters[key] = value
        return value

    def reset_value(self, key: str, value: int = 0) -> None:
        self._counters[key] = value


class PubSub(Generic[T]):
    """Typed pub/sub channel (≈ RedisPub/RedisSub)."""

    def __init__(self, broker: MessageBroker, channel: str):
        self.broker = broker
        self.channel = channel

    def publish(self, value: T) -> None:
        self.broker.publish(self.channel, encode(dumps(value)))

    def subscribe(self, handler: Callable[[T], None]) -> Callable[[], None]:
        return self.broker.subscribe(self.channel, lambda raw: handler(loads(decode(raw))))

    def stream(self) -> "asyncio.Queue[T]":
        """Subscribe into an asyncio queue (reader cancels by unsubscribing
        via ``queue.unsubscribe()``)."""
        queue: "asyncio.Queue[T]" = asyncio.Queue()
        loop = asyncio.get_event_loop()
        unsubscribe = self.subscribe(lambda v: loop.call_soon_threadsafe(queue.put_nowait, v))
        queue.unsubscribe = unsubscribe  # type: ignore[attr-defined]
        return queue


class TypedQueue(Generic[T]):
    """Typed work queue (≈ RedisQueue). The item buffer lives in the
    BROKER, not the instance, so concurrent consumers — even separate
    TypedQueue instances over the same broker+name — each pop distinct
    items (the multi-worker setup the Redis analogue implies)."""

    def __init__(self, broker: MessageBroker, name: str):
        self.broker = broker
        self.name = name

    def enqueue(self, value: T) -> None:
        self.broker.queue_push(f"queue:{self.name}", encode(dumps(value)))

    async def dequeue(self, timeout: Optional[float] = None) -> T:
        pop = self.broker.queue_pop(f"queue:{self.name}")
        raw = await (pop if timeout is None else asyncio.wait_for(pop, timeout))
        return loads(decode(raw))

    def close(self) -> None:
        pass  # nothing instance-local to release; kept for API symmetry


class Streamer(Generic[T]):
    """Replayable typed stream (≈ RedisStreamer): items are appended with
    monotone positions; late readers replay the backlog then follow live."""

    def __init__(self, broker: MessageBroker, name: str, max_backlog: int = 4096):
        self.broker = broker
        self.name = name
        self.max_backlog = max_backlog
        self._backlog: List[T] = []
        self._base = 0  # absolute stream position of _backlog[0]
        self._events: List[asyncio.Event] = []
        self._done = False
        self._unsubscribe = broker.subscribe(f"stream:{name}", self._on_raw)

    def _on_raw(self, raw: bytes) -> None:
        kind, value = loads(decode(raw))
        if kind == "end":
            self._done = True
        else:
            self._backlog.append(value)
            excess = len(self._backlog) - self.max_backlog
            if excess > 0:
                del self._backlog[:excess]
                self._base += excess  # readers track absolute positions
        for e in self._events:
            e.set()

    def append(self, value: T) -> None:
        self.broker.publish(f"stream:{self.name}", encode(dumps(("item", value))))

    def complete(self) -> None:
        self.broker.publish(f"stream:{self.name}", encode(dumps(("end", None))))

    async def read(self, from_start: bool = True) -> AsyncIterator[T]:
        """Replay the retained backlog (items older than ``max_backlog``
        are gone — a slow reader skips forward rather than mis-indexing),
        then follow live until ``complete()``. Positions are absolute."""
        pos = self._base if from_start else self._base + len(self._backlog)
        event = asyncio.Event()
        self._events.append(event)
        try:
            while True:
                while True:
                    # re-clamp EVERY iteration: the producer may trim while
                    # this reader's consumer is suspended at the yield
                    pos = max(pos, self._base)
                    if pos >= self._base + len(self._backlog):
                        break
                    item = self._backlog[pos - self._base]
                    pos += 1
                    yield item
                if self._done:
                    return
                event.clear()
                await event.wait()
        finally:
            self._events.remove(event)

    def close(self) -> None:
        self._unsubscribe()


class SequenceSet:
    """Monotone named sequences (≈ RedisSequenceSet): ``next`` never
    repeats and can be bumped past an externally-observed value."""

    def __init__(self, broker: MessageBroker, prefix: str = "seq"):
        self.broker = broker
        self.prefix = prefix

    def next(self, key: str, at_least: int = 0) -> int:
        return self.broker.next_value(f"{self.prefix}:{key}", at_least)

    def reset(self, key: str, value: int = 0) -> None:
        self.broker.reset_value(f"{self.prefix}:{key}", value)


class BrokerChangeNotifier:
    """Operation-log wake-up over a broker channel (≈ Redis op-log change
    notifier): hosts publish after committing; readers' events wake."""

    def __init__(self, broker: MessageBroker, channel: str = "oplog-changed"):
        self.broker = broker
        self.channel = channel
        self._events: List[asyncio.Event] = []
        self._unsubscribe = broker.subscribe(channel, self._on_message)

    def _on_message(self, _raw: bytes) -> None:
        for e in self._events:
            e.set()

    def subscribe(self) -> asyncio.Event:
        e = asyncio.Event()
        self._events.append(e)
        return e

    def unsubscribe(self, event: asyncio.Event) -> None:
        if event in self._events:
            self._events.remove(event)

    def notify(self) -> None:
        self.broker.publish(self.channel, b"\x01")

    def close(self) -> None:
        self._events.clear()
        self._unsubscribe()
