"""KeyValueStore — invalidation-aware KV storage.

Re-expression of src/Stl.Fusion.Ext.Services/Extensions/ — IKeyValueStore /
DbKeyValueStore / SandboxedKeyValueStore: reads are compute methods, writes
are commands whose completion invalidates exactly the touched keys (+ the
affected prefix listings), with optional expiration handled by a trimmer.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

from ..commands.handlers import command_handler
from ..core.context import is_invalidating
from ..core.hub import FusionHub
from ..core.service import ComputeService, compute_method
from ..utils.serialization import wire_type

__all__ = [
    "KeyValueStore",
    "SqliteKeyValueStore",
    "SandboxedKeyValueStore",
    "SetCommand",
    "RemoveCommand",
]


@wire_type("KvSet")
@dataclasses.dataclass(frozen=True)
class SetCommand:
    key: str
    value: str
    expires_at: Optional[float] = None


@wire_type("KvRemove")
@dataclasses.dataclass(frozen=True)
class RemoveCommand:
    key: str


class KeyValueStore(ComputeService):
    """In-memory by default; subclasses swap the storage hooks for durable
    backends (`SqliteKeyValueStore` ≈ the reference's DbKeyValueStore)."""

    def __init__(self, hub: Optional[FusionHub] = None):
        super().__init__(hub)
        self._data: Dict[str, Tuple[str, Optional[float]]] = {}

    # ---------------------------------------------------------- storage hooks
    def _load(self, key: str) -> Optional[Tuple[str, Optional[float]]]:
        return self._data.get(key)

    def _store(self, key: str, value: str, expires_at: Optional[float]) -> None:
        self._data[key] = (value, expires_at)

    def _delete(self, key: str) -> None:
        self._data.pop(key, None)

    def _all_keys(self) -> Tuple[str, ...]:
        return tuple(self._data.keys())

    def _expired_keys(self, now: float) -> Tuple[str, ...]:
        return tuple(
            k for k, (_v, exp) in self._data.items() if exp is not None and exp <= now
        )

    # ------------------------------------------------------------------ reads
    @compute_method
    async def get(self, key: str) -> Optional[str]:
        entry = self._load(key)
        if entry is None:
            return None
        value, expires_at = entry
        if expires_at is not None and expires_at <= time.time():
            return None
        return value

    @compute_method
    async def count_by_prefix(self, prefix: str) -> int:
        return sum(1 for k in self._all_keys() if k.startswith(prefix))

    @compute_method
    async def list_key_suffixes(self, prefix: str) -> tuple:
        return tuple(sorted(k[len(prefix):] for k in self._all_keys() if k.startswith(prefix)))

    # ------------------------------------------------------------------ writes
    @command_handler
    async def set(self, command: SetCommand):
        if is_invalidating():
            await self._invalidate_key(command.key)
            return
        self._store(command.key, command.value, command.expires_at)

    @command_handler
    async def remove(self, command: RemoveCommand):
        if is_invalidating():
            await self._invalidate_key(command.key)
            return
        self._delete(command.key)

    async def _invalidate_key(self, key: str) -> None:
        await self.get(key)
        # prefix listings that could include this key
        for i in range(len(key) + 1):
            await self.count_by_prefix(key[:i])
            await self.list_key_suffixes(key[:i])

    # ------------------------------------------------------------------ trimmer
    async def trim_expired(self) -> int:
        """Expiration sweep (≈ DbKeyValueStore's trimmer worker)."""
        expired = self._expired_keys(time.time())
        from ..core.context import invalidating

        for k in expired:
            self._delete(k)
            with invalidating():
                await self._invalidate_key(k)
        return len(expired)


class SqliteKeyValueStore(KeyValueStore):
    """Durable KV store over stdlib sqlite (≈ DbKeyValueStore,
    Ext.Services/Extensions/Services/DbKeyValueStore.cs — store-agnostic
    here because no external DB exists in-image). Same compute/command
    surface; only the storage hooks differ, so invalidation semantics are
    inherited unchanged."""

    def __init__(self, path: str, hub: Optional[FusionHub] = None):
        import sqlite3

        super().__init__(hub)
        self._db = sqlite3.connect(path)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS kv (key TEXT PRIMARY KEY, value TEXT, expires_at REAL)"
        )
        self._db.commit()

    def _load(self, key: str) -> Optional[Tuple[str, Optional[float]]]:
        row = self._db.execute("SELECT value, expires_at FROM kv WHERE key=?", (key,)).fetchone()
        return (row[0], row[1]) if row is not None else None

    def _store(self, key: str, value: str, expires_at: Optional[float]) -> None:
        self._db.execute(
            "INSERT INTO kv VALUES (?,?,?) ON CONFLICT(key) DO UPDATE SET value=excluded.value, "
            "expires_at=excluded.expires_at",
            (key, value, expires_at),
        )
        self._db.commit()

    def _delete(self, key: str) -> None:
        self._db.execute("DELETE FROM kv WHERE key=?", (key,))
        self._db.commit()

    def _all_keys(self) -> Tuple[str, ...]:
        return tuple(r[0] for r in self._db.execute("SELECT key FROM kv"))

    def _expired_keys(self, now: float) -> Tuple[str, ...]:
        rows = self._db.execute(
            "SELECT key FROM kv WHERE expires_at IS NOT NULL AND expires_at <= ?", (now,)
        ).fetchall()
        return tuple(r[0] for r in rows)

    def close(self) -> None:
        self._db.close()


class SandboxedKeyValueStore:
    """Session-scoped view of a KeyValueStore: every key maps under the
    session's private prefix, so one session cannot read or clobber
    another's keys (≈ SandboxedKeyValueStore,
    Ext.Services/Extensions/Services/SandboxedKeyValueStore.cs). Delegates
    to the underlying store's compute methods, so dependency capture and
    invalidation flow through unchanged."""

    def __init__(self, store: KeyValueStore, session):
        from urllib.parse import quote

        self.store = store
        # the session id is URL-encoded (no unescaped '/') so a crafted id
        # like "a/b" cannot alias session "a"'s sandbox with key "b/..."
        # — the reference formats keys the same way
        # (SandboxedKeyValueStore.cs key formatting)
        self.prefix = f"@sandbox/{quote(session.id, safe='')}/"

    def _k(self, key: str) -> str:
        return self.prefix + key

    async def get(self, key: str) -> Optional[str]:
        return await self.store.get(self._k(key))

    async def count(self) -> int:
        return await self.store.count_by_prefix(self.prefix)

    async def list_keys(self) -> tuple:
        return await self.store.list_key_suffixes(self.prefix)

    async def set(self, key: str, value: str, expires_at: Optional[float] = None):
        return await self._commander().call(SetCommand(self._k(key), value, expires_at))

    async def remove(self, key: str):
        return await self._commander().call(RemoveCommand(self._k(key)))

    def _commander(self):
        from ..core.service import hub_of

        return hub_of(self.store).commander
