"""KeyValueStore — invalidation-aware KV storage.

Re-expression of src/Stl.Fusion.Ext.Services/Extensions/ — IKeyValueStore /
DbKeyValueStore / SandboxedKeyValueStore: reads are compute methods, writes
are commands whose completion invalidates exactly the touched keys (+ the
affected prefix listings), with optional expiration handled by a trimmer.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

from ..commands.handlers import command_handler
from ..core.context import is_invalidating
from ..core.hub import FusionHub
from ..core.service import ComputeService, compute_method
from ..utils.serialization import wire_type

__all__ = ["KeyValueStore", "SetCommand", "RemoveCommand"]


@wire_type("KvSet")
@dataclasses.dataclass(frozen=True)
class SetCommand:
    key: str
    value: str
    expires_at: Optional[float] = None


@wire_type("KvRemove")
@dataclasses.dataclass(frozen=True)
class RemoveCommand:
    key: str


class KeyValueStore(ComputeService):
    def __init__(self, hub: Optional[FusionHub] = None):
        super().__init__(hub)
        self._data: Dict[str, Tuple[str, Optional[float]]] = {}

    # ------------------------------------------------------------------ reads
    @compute_method
    async def get(self, key: str) -> Optional[str]:
        entry = self._data.get(key)
        if entry is None:
            return None
        value, expires_at = entry
        if expires_at is not None and expires_at <= time.time():
            return None
        return value

    @compute_method
    async def count_by_prefix(self, prefix: str) -> int:
        return sum(1 for k in self._data if k.startswith(prefix))

    @compute_method
    async def list_key_suffixes(self, prefix: str) -> tuple:
        return tuple(sorted(k[len(prefix):] for k in self._data if k.startswith(prefix)))

    # ------------------------------------------------------------------ writes
    @command_handler
    async def set(self, command: SetCommand):
        if is_invalidating():
            await self._invalidate_key(command.key)
            return
        self._data[command.key] = (command.value, command.expires_at)

    @command_handler
    async def remove(self, command: RemoveCommand):
        if is_invalidating():
            await self._invalidate_key(command.key)
            return
        self._data.pop(command.key, None)

    async def _invalidate_key(self, key: str) -> None:
        await self.get(key)
        # prefix listings that could include this key
        for i in range(len(key) + 1):
            await self.count_by_prefix(key[:i])
            await self.list_key_suffixes(key[:i])

    # ------------------------------------------------------------------ trimmer
    async def trim_expired(self) -> int:
        """Expiration sweep (≈ DbKeyValueStore's trimmer worker)."""
        now = time.time()
        expired = [k for k, (_v, exp) in self._data.items() if exp is not None and exp <= now]
        from ..core.context import invalidating

        for k in expired:
            del self._data[k]
            with invalidating():
                await self._invalidate_key(k)
        return len(expired)
