"""Plugin host — discovery + capability metadata + dependency-ordered start.

Re-expression of src/Stl.Plugins/ (PluginHost.cs, FileSystemPluginFinder.cs,
Metadata/PluginSetInfo.cs): plugins are classes marked with ``@plugin``
carrying capability tags and dependency edges; a finder scans python
modules/packages for them; the host instantiates singletons in dependency
order and answers capability queries. BASELINE.json names this as the
backend registration point — e.g. alternative operation-log stores or
transports register themselves as plugins.
"""
from __future__ import annotations

import importlib
import logging
import pkgutil
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Type

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["plugin", "PluginInfo", "PluginSetInfo", "PluginHost", "find_plugins"]


@dataclass(frozen=True)
class PluginInfo:
    plugin_type: Type
    name: str
    capabilities: Tuple[str, ...] = ()
    dependencies: Tuple[str, ...] = ()  # names of plugins that must start first


def plugin(
    cls: Optional[Type] = None,
    *,
    name: Optional[str] = None,
    capabilities: Sequence[str] = (),
    dependencies: Sequence[str] = (),
):
    """Mark a class as a plugin (≈ the reference's plugin attribute +
    PluginInfo metadata)."""

    def decorate(klass: Type) -> Type:
        klass.__plugin_info__ = PluginInfo(  # type: ignore[attr-defined]
            klass,
            name or klass.__name__,
            tuple(capabilities),
            tuple(dependencies),
        )
        return klass

    return decorate(cls) if cls is not None else decorate


def find_plugins(module_names: Iterable[str], recurse: bool = True) -> List[PluginInfo]:
    """Scan modules (and optionally their submodules) for ``@plugin``
    classes (≈ FileSystemPluginFinder's assembly scan)."""
    infos: List[PluginInfo] = []
    seen_modules = set()

    def scan_module(mod) -> None:
        if mod.__name__ in seen_modules:
            return
        seen_modules.add(mod.__name__)
        for attr_name in dir(mod):
            attr = getattr(mod, attr_name, None)
            info = getattr(attr, "__plugin_info__", None)
            if isinstance(info, PluginInfo) and info.plugin_type is attr:
                if info not in infos:
                    infos.append(info)
        if recurse and hasattr(mod, "__path__"):
            for sub in pkgutil.iter_modules(mod.__path__):
                try:
                    scan_module(importlib.import_module(f"{mod.__name__}.{sub.name}"))
                except Exception:  # noqa: BLE001 — a broken module skips, not aborts
                    log.exception("plugin scan failed for %s.%s", mod.__name__, sub.name)

    for name in module_names:
        scan_module(importlib.import_module(name))
    return infos


@dataclass
class PluginSetInfo:
    """Immutable-ish metadata for a discovered plugin set (≈ PluginSetInfo)."""

    plugins: List[PluginInfo] = field(default_factory=list)

    @property
    def names(self) -> List[str]:
        return [p.name for p in self.plugins]

    def by_capability(self, capability: str) -> List[PluginInfo]:
        return [p for p in self.plugins if capability in p.capabilities]

    def get(self, name: str) -> Optional[PluginInfo]:
        for p in self.plugins:
            if p.name == name:
                return p
        return None

    def start_order(self) -> List[PluginInfo]:
        """Topological order by declared dependencies; cycles raise."""
        by_name = {p.name: p for p in self.plugins}
        order: List[PluginInfo] = []
        state: Dict[str, int] = {}  # 1 = visiting, 2 = done

        def visit(p: PluginInfo) -> None:
            mark = state.get(p.name, 0)
            if mark == 2:
                return
            if mark == 1:
                raise ValueError(f"plugin dependency cycle through {p.name!r}")
            state[p.name] = 1
            for dep in p.dependencies:
                dep_info = by_name.get(dep)
                if dep_info is None:
                    raise LookupError(f"plugin {p.name!r} depends on unknown {dep!r}")
                visit(dep_info)
            state[p.name] = 2
            order.append(p)

        for p in self.plugins:
            visit(p)
        return order


class PluginHost:
    """Instantiates plugins (singletons, dependency-ordered) and serves
    capability queries (≈ PluginHost)."""

    def __init__(
        self,
        infos: Sequence[PluginInfo],
        factory: Optional[Callable[[PluginInfo, "PluginHost"], Any]] = None,
    ):
        self.set_info = PluginSetInfo(list(infos))
        self._factory = factory or (lambda info, host: info.plugin_type())
        self._instances: Dict[str, Any] = {}
        for info in self.set_info.start_order():
            self._instances[info.name] = self._factory(info, self)

    @staticmethod
    def from_modules(module_names: Iterable[str], **kwargs) -> "PluginHost":
        return PluginHost(find_plugins(module_names), **kwargs)

    def get(self, name_or_type) -> Any:
        if isinstance(name_or_type, str):
            instance = self._instances.get(name_or_type)
        else:
            info = getattr(name_or_type, "__plugin_info__", None)
            instance = self._instances.get(info.name) if info else None
        if instance is None:
            raise LookupError(f"plugin {name_or_type!r} is not hosted")
        return instance

    def with_capability(self, capability: str) -> List[Any]:
        return [self._instances[p.name] for p in self.set_info.by_capability(capability)]

    def __contains__(self, name: str) -> bool:
        return name in self._instances

    def __len__(self) -> int:
        return len(self._instances)
