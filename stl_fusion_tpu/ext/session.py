"""Session — opaque client identity flowing through calls.

Re-expression of src/Stl.Fusion/Session/ — Session.cs:14-60 (min 8 chars,
``~`` default placeholder, ``@tenantId`` suffix), SessionResolver, and the
server-side default-session replacement middleware
(Fusion.Server/Rpc/DefaultSessionReplacerRpcMiddleware.cs): clients send the
placeholder, the connection substitutes its real bound session.
"""
from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import Optional

from ..utils.serialization import register_wire_type

__all__ = ["Session", "SessionResolver", "replace_default_sessions"]

DEFAULT_PLACEHOLDER = "~"
MIN_ID_LENGTH = 8


@dataclass(frozen=True)
class Session:
    id: str

    def __post_init__(self):
        if self.id != DEFAULT_PLACEHOLDER and len(self.id) < MIN_ID_LENGTH:
            raise ValueError(f"session id must be ≥{MIN_ID_LENGTH} chars")

    @property
    def is_default(self) -> bool:
        return self.id == DEFAULT_PLACEHOLDER

    @property
    def tenant_id(self) -> str:
        _, sep, tenant = self.id.partition("@")
        return tenant if sep else ""

    @staticmethod
    def default() -> "Session":
        return Session(DEFAULT_PLACEHOLDER)

    @staticmethod
    def new(tenant_id: str = "") -> "Session":
        sid = secrets.token_urlsafe(15)
        return Session(f"{sid}@{tenant_id}" if tenant_id else sid)

    def __repr__(self) -> str:
        return f"Session({self.id[:8]}…)" if not self.is_default else "Session(~)"


register_wire_type(Session, "Session", lambda s: {"id": s.id}, lambda d: Session(d["id"]))


def replace_default_sessions(args: list, session: Session, session_cls: type = Session) -> list:
    """THE default-session substitution: swap every default-placeholder
    Session in an args list for the caller-bound real one. Shared by the
    HTTP session middleware, the RPC inbound middleware, and resolver-based
    flows so the replacement semantics can never drift apart
    (≈ DefaultSessionReplacerRpcMiddleware.cs)."""
    return [session if isinstance(a, session_cls) and a.is_default else a for a in args]


class SessionResolver:
    """Holds the ambient session for a connection/scope; replaces the
    default placeholder in inbound calls (≈ SessionMiddleware +
    DefaultSessionReplacerRpcMiddleware)."""

    def __init__(self, session: Optional[Session] = None):
        self._session = session

    @property
    def has_session(self) -> bool:
        return self._session is not None

    @property
    def session(self) -> Session:
        if self._session is None:
            self._session = Session.new()
        return self._session

    def resolve(self, incoming: Session) -> Session:
        """Default placeholder → this connection's real session."""
        return self.session if incoming.is_default else incoming
