"""Auth — session-keyed authentication as a compute service.

Re-expression of src/Stl.Fusion.Ext.Contracts/Authentication/IAuth.cs +
Ext.Services InMemoryAuthService: ``get_user``/``get_session_info`` are
compute methods (so UIs LIVE-update on sign-in/out anywhere in the cluster),
sign-in/sign-out/edit are commands whose replay invalidates exactly the
affected session/user reads.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

from ..commands.handlers import command_handler
from ..core.context import is_invalidating
from ..core.hub import FusionHub
from ..core.service import ComputeService, compute_method
from ..utils.serialization import wire_type
from .session import Session

__all__ = [
    "User",
    "SessionInfo",
    "SetupSessionCommand",
    "SignInCommand",
    "SignOutCommand",
    "EditUserCommand",
    "InvalidateUserSessionsCommand",
    "InMemoryAuthService",
    "SqliteAuthService",
]


@wire_type("AuthUser")
@dataclasses.dataclass(frozen=True)
class User:
    id: str
    name: str
    claims: tuple = ()  # ((key, value), ...)

    @property
    def is_authenticated(self) -> bool:
        return bool(self.id)


@wire_type("SessionInfo")
@dataclasses.dataclass(frozen=True)
class SessionInfo:
    session_id: str
    user_id: str = ""
    created_at: float = 0.0
    last_seen_at: float = 0.0
    # where the session lives (≈ SessionInfo.IPAddress/UserAgent): set by
    # SetupSession from the transport, drives ServerAuthHelper's
    # "must re-setup" check when a session moves networks/browsers
    ip_address: str = ""
    user_agent: str = ""
    # forced sign-out is a flag ON the session row, exactly like the
    # reference (DbSessionInfo.IsSignOutForced): the row survives sign-out,
    # sign-in throws while it's set, sign-out no-ops while it's set
    # (DbAuthService.cs:84-92, DbAuthService.Backend.cs:42-43)
    is_sign_out_forced: bool = False

    @property
    def is_authenticated(self) -> bool:
        return bool(self.user_id)


@wire_type("SetupSession")
@dataclasses.dataclass(frozen=True)
class SetupSessionCommand:
    """Create/refresh the session row with transport facts
    (≈ AuthBackend_SetupSession). Empty ip/user_agent mean "keep current"
    — which is how presence updates ride the same command."""

    session: Session
    ip_address: str = ""
    user_agent: str = ""


@wire_type("SignIn")
@dataclasses.dataclass(frozen=True)
class SignInCommand:
    session: Session
    user: User


@wire_type("SignOut")
@dataclasses.dataclass(frozen=True)
class SignOutCommand:
    session: Session
    force: bool = False


@wire_type("EditUser")
@dataclasses.dataclass(frozen=True)
class EditUserCommand:
    session: Session
    name: str


@wire_type("InvalidateUserSessions")
@dataclasses.dataclass(frozen=True)
class InvalidateUserSessionsCommand:
    """Replay-only marker stashed into the enclosing Operation's items when
    a command changes which user a session belongs to: the pre-command
    user_id is captured at execution time (the reference captures the old
    SessionInfo via Operation Items, DbAuthService.cs:54-58) so the
    invalidation replay can reach ``get_user_sessions(old_user_id)`` after
    the session row no longer mentions that user. Execution branch is a
    no-op; it also rides the op log, so other hosts invalidate too."""

    user_id: str


class InMemoryAuthService(ComputeService):
    """IAuth + IAuthBackend in one service. In-memory by default; the
    storage hooks are the override surface for durable backends
    (`SqliteAuthService` ≈ DbAuthService)."""

    def __init__(self, hub: Optional[FusionHub] = None):
        super().__init__(hub)
        self._sessions: Dict[str, SessionInfo] = {}
        self._users: Dict[str, User] = {}
        #: injectable timestamps (≈ MomentClockSet): ServerAuthHelper's
        #: staleness checks and this service's last_seen stamps must share
        #: one clock, or tests with a fake clock diverge from reality
        self.clock = time.time

    # ---------------------------------------------------------- storage hooks
    def _load_session(self, session_id: str) -> Optional[SessionInfo]:
        return self._sessions.get(session_id)

    def _store_session(self, info: SessionInfo) -> None:
        self._sessions[info.session_id] = info

    def _load_user(self, user_id: str) -> Optional[User]:
        return self._users.get(user_id)

    def _store_user(self, user: User) -> None:
        self._users[user.id] = user

    def _session_ids_of(self, user_id: str) -> tuple:
        return tuple(
            sorted(sid for sid, i in self._sessions.items() if user_id and i.user_id == user_id)
        )

    # ------------------------------------------------------------------ reads (IAuth)
    @compute_method
    async def get_session_info(self, session: Session) -> Optional[SessionInfo]:
        return self._load_session(session.id)

    @compute_method
    async def get_user(self, session: Session) -> Optional[User]:
        info = await self.get_session_info(session)
        if info is None or not info.user_id:
            return None
        return self._load_user(info.user_id)

    @compute_method
    async def is_sign_out_forced(self, session: Session) -> bool:
        info = self._load_session(session.id)
        return info is not None and info.is_sign_out_forced

    @compute_method
    async def get_user_sessions(self, user_id: str) -> tuple:
        return self._session_ids_of(user_id)

    # ------------------------------------------------------------------ commands
    @command_handler
    async def setup_session(self, command: SetupSessionCommand):
        """Create or refresh the session row with transport facts
        (≈ AuthBackend_SetupSession in DbAuthService.Backend.cs): user
        binding and the forced flag are preserved; empty ip/agent keep the
        stored values (the presence-update shape)."""
        if is_invalidating():
            await self._invalidate_session(command.session)
            return
        now = self.clock()
        existing = self._load_session(command.session.id)
        base = existing if existing is not None else SessionInfo(
            command.session.id, created_at=now
        )
        self._store_session(
            dataclasses.replace(
                base,
                last_seen_at=now,
                ip_address=command.ip_address or base.ip_address,
                user_agent=command.user_agent or base.user_agent,
            )
        )

    @command_handler
    async def sign_in(self, command: SignInCommand):
        if is_invalidating():
            await self._invalidate_session(command.session)
            await self.get_user_sessions(command.user.id)
            return
        now = self.clock()
        existing = self._load_session(command.session.id)
        if existing is not None and existing.is_sign_out_forced:
            # a force-signed-out session is permanently unavailable
            # (DbAuthService.Backend.cs:42-43, Errors.SessionUnavailable)
            raise PermissionError("session is unavailable (forced sign-out)")
        if existing is not None and existing.user_id and existing.user_id != command.user.id:
            # the session is being reassigned: the OLD user's session list
            # changes too — capture their id for the replay
            self._capture_user_sessions_invalidation(existing.user_id)
        self._store_user(command.user)
        base = existing if existing is not None else SessionInfo(
            command.session.id, created_at=now
        )
        self._store_session(
            dataclasses.replace(base, user_id=command.user.id, last_seen_at=now)
        )

    @command_handler
    async def sign_out(self, command: SignOutCommand):
        if is_invalidating():
            await self._invalidate_session(command.session)
            return
        info = self._load_session(command.session.id)
        if info is not None and info.is_sign_out_forced:
            return  # already forced out — no-op (DbAuthService.cs:84-85)
        if info is not None and info.user_id:
            # the replay can't recover the old user_id from the (by then
            # rewritten) session row — capture it now, like the reference's
            # SignOut invalidating GetUserSessions via the operation-captured
            # SessionInfo (DbAuthService.cs:54-58)
            self._capture_user_sessions_invalidation(info.user_id)
        now = self.clock()
        base = info if info is not None else SessionInfo(command.session.id, created_at=now)
        self._store_session(
            dataclasses.replace(
                base, user_id="", last_seen_at=now, is_sign_out_forced=command.force
            )
        )

    @command_handler
    async def edit_user(self, command: EditUserCommand):
        if is_invalidating():
            await self._invalidate_session(command.session)
            return
        info = self._load_session(command.session.id)
        if info is None or not info.user_id:
            raise PermissionError("not signed in")
        user = self._load_user(info.user_id)
        self._store_user(dataclasses.replace(user, name=command.name))

    @command_handler
    async def _invalidate_user_sessions(self, command: InvalidateUserSessionsCommand):
        if is_invalidating():
            await self.get_user_sessions(command.user_id)
        # execution branch: nothing to do — the marker only exists to be
        # replayed (it enters the pipeline via Operation.items, not call())

    def _capture_user_sessions_invalidation(self, user_id: str) -> None:
        from ..operations.pipeline import current_operation

        op = current_operation()
        if op is not None:
            op.items.append(InvalidateUserSessionsCommand(user_id))

    async def _invalidate_session(self, session: Session) -> None:
        await self.get_session_info(session)
        await self.get_user(session)
        await self.is_sign_out_forced(session)


class SqliteAuthService(InMemoryAuthService):
    """Durable auth over stdlib sqlite (≈ DbAuthService,
    Ext.Services/Authentication/Services/DbAuthService.cs — store-agnostic
    because no external DB exists in-image). Sessions and users survive
    restarts; the compute/command surface and invalidation semantics are
    inherited unchanged — only the storage hooks differ."""

    def __init__(self, path: str, hub: Optional[FusionHub] = None):
        import json
        import sqlite3

        super().__init__(hub)
        self._json = json
        self._db = sqlite3.connect(path)
        self._db.executescript(
            "CREATE TABLE IF NOT EXISTS auth_users ("
            " id TEXT PRIMARY KEY, name TEXT, claims TEXT);"
            "CREATE TABLE IF NOT EXISTS auth_sessions ("
            " session_id TEXT PRIMARY KEY, user_id TEXT,"
            " created_at REAL, last_seen_at REAL, is_sign_out_forced INTEGER,"
            " ip_address TEXT DEFAULT '', user_agent TEXT DEFAULT '');"
        )
        # migrate pre-r2 databases lacking the transport columns
        cols = {r[1] for r in self._db.execute("PRAGMA table_info(auth_sessions)")}
        for col in ("ip_address", "user_agent"):
            if col not in cols:
                self._db.execute(
                    f"ALTER TABLE auth_sessions ADD COLUMN {col} TEXT DEFAULT ''"
                )
        self._db.commit()

    def _load_session(self, session_id: str) -> Optional[SessionInfo]:
        row = self._db.execute(
            "SELECT session_id, user_id, created_at, last_seen_at, is_sign_out_forced,"
            " ip_address, user_agent"
            " FROM auth_sessions WHERE session_id=?",
            (session_id,),
        ).fetchone()
        if row is None:
            return None
        return SessionInfo(
            row[0], row[1], row[2], row[3],
            is_sign_out_forced=bool(row[4]), ip_address=row[5], user_agent=row[6],
        )

    def _store_session(self, info: SessionInfo) -> None:
        # full-row upsert in ONE statement: the session row (incl. the
        # forced flag) can never be torn by a crash between writes
        self._db.execute(
            "INSERT OR REPLACE INTO auth_sessions VALUES (?,?,?,?,?,?,?)",
            (
                info.session_id,
                info.user_id,
                info.created_at,
                info.last_seen_at,
                int(info.is_sign_out_forced),
                info.ip_address,
                info.user_agent,
            ),
        )
        self._db.commit()

    def _load_user(self, user_id: str) -> Optional[User]:
        row = self._db.execute(
            "SELECT id, name, claims FROM auth_users WHERE id=?", (user_id,)
        ).fetchone()
        if row is None:
            return None
        claims = tuple(tuple(c) for c in self._json.loads(row[2] or "[]"))
        return User(row[0], row[1], claims)

    def _store_user(self, user: User) -> None:
        self._db.execute(
            "INSERT INTO auth_users VALUES (?,?,?) ON CONFLICT(id) DO UPDATE SET"
            " name=excluded.name, claims=excluded.claims",
            (user.id, user.name, self._json.dumps([list(c) for c in user.claims])),
        )
        self._db.commit()

    def _session_ids_of(self, user_id: str) -> tuple:
        if not user_id:
            return ()
        rows = self._db.execute(
            "SELECT session_id FROM auth_sessions WHERE user_id=? ORDER BY session_id",
            (user_id,),
        ).fetchall()
        return tuple(r[0] for r in rows)

    def close(self) -> None:
        self._db.close()
