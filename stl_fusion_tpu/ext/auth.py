"""Auth — session-keyed authentication as a compute service.

Re-expression of src/Stl.Fusion.Ext.Contracts/Authentication/IAuth.cs +
Ext.Services InMemoryAuthService: ``get_user``/``get_session_info`` are
compute methods (so UIs LIVE-update on sign-in/out anywhere in the cluster),
sign-in/sign-out/edit are commands whose replay invalidates exactly the
affected session/user reads.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

from ..commands.handlers import command_handler
from ..core.context import is_invalidating
from ..core.hub import FusionHub
from ..core.service import ComputeService, compute_method
from ..utils.serialization import wire_type
from .session import Session

__all__ = ["User", "SessionInfo", "SignInCommand", "SignOutCommand", "EditUserCommand", "InMemoryAuthService"]


@wire_type("AuthUser")
@dataclasses.dataclass(frozen=True)
class User:
    id: str
    name: str
    claims: tuple = ()  # ((key, value), ...)

    @property
    def is_authenticated(self) -> bool:
        return bool(self.id)


@wire_type("SessionInfo")
@dataclasses.dataclass(frozen=True)
class SessionInfo:
    session_id: str
    user_id: str = ""
    created_at: float = 0.0
    last_seen_at: float = 0.0

    @property
    def is_authenticated(self) -> bool:
        return bool(self.user_id)


@wire_type("SignIn")
@dataclasses.dataclass(frozen=True)
class SignInCommand:
    session: Session
    user: User


@wire_type("SignOut")
@dataclasses.dataclass(frozen=True)
class SignOutCommand:
    session: Session
    force: bool = False


@wire_type("EditUser")
@dataclasses.dataclass(frozen=True)
class EditUserCommand:
    session: Session
    name: str


class InMemoryAuthService(ComputeService):
    """IAuth + IAuthBackend in one in-memory service."""

    def __init__(self, hub: Optional[FusionHub] = None):
        super().__init__(hub)
        self._sessions: Dict[str, SessionInfo] = {}
        self._users: Dict[str, User] = {}

    # ------------------------------------------------------------------ reads (IAuth)
    @compute_method
    async def get_session_info(self, session: Session) -> Optional[SessionInfo]:
        return self._sessions.get(session.id)

    @compute_method
    async def get_user(self, session: Session) -> Optional[User]:
        info = await self.get_session_info(session)
        if info is None or not info.user_id:
            return None
        return self._users.get(info.user_id)

    @compute_method
    async def is_sign_out_forced(self, session: Session) -> bool:
        info = self._sessions.get(session.id)
        return info is None and session.id in getattr(self, "_forced_out", set())

    @compute_method
    async def get_user_sessions(self, user_id: str) -> tuple:
        return tuple(sorted(sid for sid, i in self._sessions.items() if i.user_id == user_id))

    # ------------------------------------------------------------------ commands
    @command_handler
    async def sign_in(self, command: SignInCommand):
        if is_invalidating():
            await self._invalidate_session(command.session)
            await self.get_user_sessions(command.user.id)
            return
        now = time.time()
        self._users[command.user.id] = command.user
        self._sessions[command.session.id] = SessionInfo(
            session_id=command.session.id,
            user_id=command.user.id,
            created_at=now,
            last_seen_at=now,
        )

    @command_handler
    async def sign_out(self, command: SignOutCommand):
        if is_invalidating():
            await self._invalidate_session(command.session)
            return
        info = self._sessions.pop(command.session.id, None)
        if command.force:
            if not hasattr(self, "_forced_out"):
                self._forced_out = set()
            self._forced_out.add(command.session.id)
        _ = info

    @command_handler
    async def edit_user(self, command: EditUserCommand):
        if is_invalidating():
            await self._invalidate_session(command.session)
            return
        info = self._sessions.get(command.session.id)
        if info is None or not info.user_id:
            raise PermissionError("not signed in")
        user = self._users[info.user_id]
        self._users[info.user_id] = dataclasses.replace(user, name=command.name)

    async def _invalidate_session(self, session: Session) -> None:
        await self.get_session_info(session)
        await self.get_user(session)
        await self.is_sign_out_forced(session)
