"""Extension services (SURVEY.md §2.8): time, KV store, auth, sessions,
peer monitoring."""
from .auth import (
    EditUserCommand,
    InMemoryAuthService,
    SessionInfo,
    SignInCommand,
    SignOutCommand,
    User,
)
from .fusion_time import FusionTime
from .kv_store import KeyValueStore, RemoveCommand, SetCommand
from .peer_monitor import RpcPeerState, RpcPeerStateMonitor
from .session import Session, SessionResolver

__all__ = [
    "EditUserCommand",
    "InMemoryAuthService",
    "SessionInfo",
    "SignInCommand",
    "SignOutCommand",
    "User",
    "FusionTime",
    "KeyValueStore",
    "RemoveCommand",
    "SetCommand",
    "RpcPeerState",
    "RpcPeerStateMonitor",
    "Session",
    "SessionResolver",
]
