"""Extension services (SURVEY.md §2.8): time, KV store, auth, sessions,
peer monitoring."""
from .auth import (
    EditUserCommand,
    InMemoryAuthService,
    SessionInfo,
    SetupSessionCommand,
    SignInCommand,
    SignOutCommand,
    SqliteAuthService,
    User,
)
from .server_auth import Principal, ServerAuthHelper, principal_from_headers
from .fusion_time import FusionTime
from .kv_store import (
    KeyValueStore,
    RemoveCommand,
    SandboxedKeyValueStore,
    SetCommand,
    SqliteKeyValueStore,
)
from .multitenancy import (
    PerTenantWorkerHost,
    Tenant,
    TenantNotFoundError,
    TenantRegistry,
    TenantResolver,
)
from .peer_monitor import RpcPeerState, RpcPeerStateMonitor
from .plugins import PluginHost, PluginInfo, PluginSetInfo, find_plugins, plugin
from .session import Session, SessionResolver
from .streams import (
    BrokerChangeNotifier,
    InMemoryBroker,
    MessageBroker,
    PubSub,
    SequenceSet,
    Streamer,
    TypedQueue,
)

__all__ = [
    "EditUserCommand",
    "InMemoryAuthService",
    "Principal",
    "ServerAuthHelper",
    "SessionInfo",
    "SetupSessionCommand",
    "SignInCommand",
    "SignOutCommand",
    "User",
    "principal_from_headers",
    "FusionTime",
    "KeyValueStore",
    "RemoveCommand",
    "SandboxedKeyValueStore",
    "SetCommand",
    "SqliteAuthService",
    "SqliteKeyValueStore",
    "PerTenantWorkerHost",
    "Tenant",
    "TenantNotFoundError",
    "TenantRegistry",
    "TenantResolver",
    "RpcPeerState",
    "RpcPeerStateMonitor",
    "Session",
    "SessionResolver",
    "PluginHost",
    "PluginInfo",
    "PluginSetInfo",
    "find_plugins",
    "plugin",
    "BrokerChangeNotifier",
    "InMemoryBroker",
    "MessageBroker",
    "PubSub",
    "SequenceSet",
    "Streamer",
    "TypedQueue",
]
