"""ServerAuthHelper — transport auth state ⇄ fusion auth sync.

Re-expression of src/Stl.Fusion.Server/Authentication/ServerAuthHelper.cs:9-213:
per request, compare the TRANSPORT's authentication principal (in ASP.NET,
``HttpContext.User`` filled by the cookie/OAuth middleware; here, a
principal extracted from trusted reverse-proxy headers — the
``X-Auth-Request-*`` pattern — or injected by tests) against the fusion
session's user, and reconcile by issuing the SAME commands a user-driven
flow would:

- session row missing / moved networks / presence stale → ``SetupSession``
  (ServerAuthHelper.cs:87-95);
- transport authenticated but fusion user differs → ``SignIn`` with a user
  built from the principal's claims (:98-104, CreateOrUpdateUser :180-204);
- transport anonymous but fusion user present (and not ``keep_signed_in``)
  → ``SignOut`` (:105-107);
- always: presence update, after the important work (:109-112).

Because reconciliation is commands-through-the-commander, every sync rides
the full operations pipeline: invalidations replay, the op log records it,
other hosts see it — a cookie-authenticated page load updates live UIs
everywhere, which is the whole point of the reference class.
"""
from __future__ import annotations

import time
from typing import Awaitable, Callable, Dict, Optional, Tuple

from .auth import SetupSessionCommand, SignInCommand, SignOutCommand, User
from .session import Session

__all__ = ["Principal", "ServerAuthHelper", "principal_from_headers"]


class Principal:
    """The transport's view of 'who is making this request'
    (≈ ClaimsPrincipal reduced to what the sync needs)."""

    __slots__ = ("schema", "id", "name", "claims")

    def __init__(self, schema: str, id: str, name: str = "", claims: Tuple = ()):
        self.schema = schema
        self.id = id
        self.name = name or id
        self.claims = tuple(claims)


#: Trusted reverse-proxy headers (the oauth2-proxy convention) — the
#: in-image stand-in for ASP.NET's authentication middleware output. ONLY
#: meaningful behind a proxy that strips client-supplied copies.
HEADER_ID = "x-auth-request-user"
HEADER_NAME = "x-auth-request-preferred-username"
HEADER_SCHEMA = "x-auth-request-schema"


def principal_from_headers(headers: Dict[str, str]) -> Optional[Principal]:
    uid = headers.get(HEADER_ID, "")
    if not uid:
        return None
    return Principal(
        schema=headers.get(HEADER_SCHEMA, "proxy"),
        id=uid,
        name=headers.get(HEADER_NAME, uid),
    )


class ServerAuthHelper:
    def __init__(
        self,
        auth,
        commander,
        session_info_update_period: float = 30.0,
        keep_signed_in: bool = False,
        clock: Callable[[], float] = time.time,
    ):
        self.auth = auth
        self.commander = commander
        self.session_info_update_period = session_info_update_period
        self.keep_signed_in = keep_signed_in
        self.clock = clock

    async def update_auth_state(
        self,
        session: Session,
        principal: Optional[Principal],
        ip_address: str = "",
        user_agent: str = "",
        principal_authoritative: bool = True,
    ) -> None:
        """The reconciliation decision tree (ServerAuthHelper.cs:73-113)."""
        info = await self.auth.get_session_info(session)
        # empty incoming ip/user_agent means "transport didn't report one",
        # mirroring SetupSessionCommand's empty-means-keep write semantics —
        # comparing it against a stored non-empty value would flag must_setup
        # on EVERY request while the keep-semantics write never converges
        # (ADVICE r2), flooding the shared op log
        must_setup = (
            info is None
            or (bool(ip_address) and info.ip_address != ip_address)
            or (bool(user_agent) and info.user_agent != user_agent)
            or info.last_seen_at + self.session_info_update_period < self.clock()
        )
        if must_setup:
            await self.commander.call(
                SetupSessionCommand(session, ip_address, user_agent)
            )
        if not principal_authoritative:
            # the transport could not vouch for who is calling (untrusted
            # peer): neither sign in NOR sign out — an unauthenticated
            # direct request must not revoke a signed-in session. Session
            # setup/presence above still ran; they carry no identity.
            await self._update_presence(session)
            return
        user = await self.auth.get_user(session)
        try:
            if principal is not None:
                if await self.auth.is_sign_out_forced(session):
                    # a force-closed session stays signed out no matter what
                    # the transport says — attempting SignIn would raise
                    # PermissionError on EVERY request (the service rejects
                    # forced sessions) and 500 the whole API
                    pass
                elif not self._is_same_user(user, principal):
                    await self.commander.call(
                        SignInCommand(session, self._create_or_update_user(user, principal))
                    )
            elif user is not None and not self.keep_signed_in:
                await self.commander.call(SignOutCommand(session))
        finally:
            # presence last, once the important things are done (:109-112)
            await self._update_presence(session)

    # -- protected surface (the reference's virtual methods) ---------------
    def _is_same_user(self, user: Optional[User], principal: Principal) -> bool:
        if user is None:
            return False
        identity = ("identity", f"{principal.schema}/{principal.id}")
        return identity in user.claims

    def _create_or_update_user(self, user: Optional[User], principal: Principal) -> User:
        """≈ CreateOrUpdateUser (:180-204): build a fusion User from the
        principal; an existing user keeps its id and extra claims, only the
        authenticated identity is (re)stamped."""
        identity = ("identity", f"{principal.schema}/{principal.id}")
        if user is None:
            return User(principal.id, principal.name, (identity,) + principal.claims)
        claims = tuple(c for c in user.claims if c[0] != "identity") + (identity,)
        return User(user.id, user.name, claims)

    async def _update_presence(self, session: Session) -> None:
        """Bump last_seen_at — throttled, because presence here is a
        command that rides the op log (the reference's UpdatePresence
        no-ops internally when fresh; unthrottled per-request presence
        would flood the shared log)."""
        info = await self.auth.get_session_info(session)
        if (
            info is not None
            and info.last_seen_at + self.session_info_update_period / 4 >= self.clock()
        ):
            return
        # empty ip/agent = "keep stored values": only last_seen_at moves
        await self.commander.call(SetupSessionCommand(session))
