"""Multitenancy — tenant registry, resolver, per-tenant workers.

Re-expression of src/Stl/Multitenancy/ (ITenantRegistry, ITenantResolver,
DefaultTenantResolver; default single-tenant registration
FusionBuilder.cs:126-132) and the per-tenant worker scaffolding of
src/Stl.Fusion.EntityFramework (DbTenantWorkerBase, DbWorkerBase,
IMultitenantDbContextFactory): each tenant gets its own operation-log
store and its own background readers, so invalidation traffic never
crosses tenant boundaries.

Tenant identity rides the Session's ``@tenantId`` suffix
(ext/session.py) — the resolver maps sessions to registered tenants.
"""
from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..utils.async_chain import WorkerBase
from .session import Session

log = logging.getLogger("stl_fusion_tpu")

__all__ = [
    "Tenant",
    "TenantRegistry",
    "TenantResolver",
    "TenantNotFoundError",
    "PerTenantWorkerHost",
]


@dataclass(frozen=True)
class Tenant:
    id: str
    title: str = ""
    is_active: bool = True
    #: overload-plane lane flag (ISSUE 12): a priority (paying) tenant's
    #: attaches ride the edge AdmissionController's priority lane —
    #: admitted ahead of anonymous cold attaches and exempt from
    #: pressure shedding (EDGE.md "Overload behavior")
    priority: bool = False

    @property
    def is_default(self) -> bool:
        return self.id == ""


Tenant.DEFAULT = Tenant("")  # type: ignore[attr-defined]


class TenantNotFoundError(KeyError):
    pass


class TenantRegistry:
    """All known tenants. Single-tenant mode (the default) exposes just the
    default tenant — matching the reference's SingleTenantRegistry."""

    def __init__(self, single_tenant: bool = True):
        self.single_tenant = single_tenant
        self._tenants: Dict[str, Tenant] = {"": Tenant.DEFAULT}  # type: ignore[attr-defined]
        self._change_listeners: List[Callable[[Tenant, str], None]] = []

    @property
    def all_tenants(self) -> List[Tenant]:
        return list(self._tenants.values())

    @property
    def active_tenants(self) -> List[Tenant]:
        return [t for t in self._tenants.values() if t.is_active]

    def get(self, tenant_id: str) -> Tenant:
        tenant = self._tenants.get(tenant_id)
        if tenant is None:
            raise TenantNotFoundError(tenant_id)
        return tenant

    def try_get(self, tenant_id: str) -> Optional[Tenant]:
        return self._tenants.get(tenant_id)

    def add(self, tenant: Tenant) -> Tenant:
        if self.single_tenant and not tenant.is_default:
            raise ValueError("registry is in single-tenant mode")
        self._tenants[tenant.id] = tenant
        self._notify(tenant, "added")
        return tenant

    def remove(self, tenant_id: str) -> None:
        if tenant_id == "":
            raise ValueError("the default tenant cannot be removed")
        tenant = self._tenants.pop(tenant_id, None)
        if tenant is not None:
            self._notify(tenant, "removed")

    def on_change(self, listener: Callable[[Tenant, str], None]) -> None:
        """listener(tenant, "added"|"removed")"""
        self._change_listeners.append(listener)

    def _notify(self, tenant: Tenant, change: str) -> None:
        for listener in list(self._change_listeners):
            try:
                listener(tenant, change)
            except Exception:  # noqa: BLE001
                log.exception("tenant change listener failed")


class TenantResolver:
    """Session → Tenant (≈ DefaultTenantResolver): the session's
    ``@tenantId`` suffix selects the registered tenant; no suffix (or no
    session) resolves to the default tenant."""

    def __init__(self, registry: TenantRegistry):
        self.registry = registry

    def resolve(self, session: Optional[Session] = None) -> Tenant:
        if session is None or not session.tenant_id:
            return self.registry.get("")
        return self.registry.get(session.tenant_id)


class PerTenantWorkerHost:
    """Runs one worker per active tenant (≈ DbTenantWorkerBase): the
    factory builds a tenant's worker (e.g. its OperationLogReader); workers
    start for tenants present at ``start()`` and follow registry changes.
    """

    def __init__(self, registry: TenantRegistry, worker_factory: Callable[[Tenant], WorkerBase]):
        self.registry = registry
        self.worker_factory = worker_factory
        self.workers: Dict[str, WorkerBase] = {}
        self._orphans: List[WorkerBase] = []  # removed off-loop; stopped in stop()
        self._pending_adds: List[Tenant] = []  # added off-loop; started by flush_pending()
        self._started = False
        registry.on_change(self._on_tenant_change)

    def start(self) -> "PerTenantWorkerHost":
        self._started = True
        for tenant in self.registry.active_tenants:
            self._start_worker(tenant)
        self.flush_pending()
        return self

    def flush_pending(self) -> None:
        """Start workers for tenants added from outside the event loop
        (call from loop context, e.g. a periodic maintenance task)."""
        pending, self._pending_adds = self._pending_adds, []
        for tenant in pending:
            # re-check against the registry: the tenant may have been
            # removed (or the host stopped) since it was parked
            if self._started and self.registry.try_get(tenant.id) is not None:
                self._start_worker(tenant)

    async def stop(self) -> None:
        self._started = False
        self._pending_adds.clear()
        workers, self.workers = list(self.workers.values()), {}
        orphans, self._orphans = self._orphans, []
        for w in workers + orphans:
            await w.stop()

    def _start_worker(self, tenant: Tenant) -> None:
        if tenant.id in self.workers:
            return
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            # registry mutated off-loop: a worker can't start here — park
            # the tenant until flush_pending() runs in loop context
            self._pending_adds.append(tenant)
            log.warning("tenant %s added off-loop; worker starts at flush_pending()", tenant.id)
            return
        worker = self.worker_factory(tenant)
        self.workers[tenant.id] = worker
        worker.start()

    def _on_tenant_change(self, tenant: Tenant, change: str) -> None:
        if not self._started:
            return
        if change == "added" and tenant.is_active:
            self._start_worker(tenant)
        elif change == "removed":
            self._pending_adds = [t for t in self._pending_adds if t.id != tenant.id]
            worker = self.workers.pop(tenant.id, None)
            if worker is None:
                return
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                # registry mutation off-loop (config reload thread): the
                # worker can't be stopped here — park it for stop()
                self._orphans.append(worker)
                log.warning("tenant %s removed off-loop; worker stops at host stop()", tenant.id)
                return
            task = loop.create_task(worker.stop())

            def observe(t: "asyncio.Task") -> None:
                if not t.cancelled() and t.exception() is not None:
                    log.error("tenant worker stop failed: %s", t.exception())

            task.add_done_callback(observe)
