"""FusionTime — time as a dependency.

Re-expression of src/Stl.Fusion/Extensions/IFusionTime.cs +
Internal/FusionTime.cs: compute methods returning the current time that
auto-invalidate, so anything depending on them re-renders as time passes —
the canonical demonstration that ANY changing input can be a graph node.
"""
from __future__ import annotations

import time
from typing import Optional

from ..core.hub import FusionHub
from ..core.service import ComputeService, compute_method

__all__ = ["FusionTime"]


class FusionTime(ComputeService):
    def __init__(self, hub: Optional[FusionHub] = None, update_period: float = 1.0):
        super().__init__(hub)
        self.update_period = update_period

    @compute_method(auto_invalidation_delay=1.0)
    async def get_utc_now(self) -> float:
        """Epoch seconds; auto-invalidates every update period."""
        return time.time()

    @compute_method(auto_invalidation_delay=1.0)
    async def get_moments_ago(self, moment: float) -> str:
        """Human '5 seconds ago' string that keeps itself fresh."""
        delta = max(time.time() - moment, 0.0)
        for unit, size in (("day", 86400.0), ("hour", 3600.0), ("minute", 60.0)):
            if delta >= size:
                n = int(delta // size)
                return f"{n} {unit}{'s' if n != 1 else ''} ago"
        n = int(delta)
        return f"{n} second{'s' if n != 1 else ''} ago"
