"""ShardMapRouter — epoch-stamped call routing + the server-side fence.

The client half and the server half of one protocol:

- :class:`ShardMapRouter` installs as ``RpcHub.call_router`` (it is
  callable with the classic ``(service, method, args) -> ref`` signature)
  and additionally exposes ``route()`` — the header-stamping variant the
  hub/client layers prefer: every routed call carries ``@shard`` (the
  key's virtual shard) and ``@epoch`` (the client's map epoch), plus
  ``@failover`` when a read was deliberately sent to the shard's replica
  because the owner is unreachable (breaker open, dial backoff, or
  terminated). Commands/mutations NEVER fail over — a write accepted by a
  non-owner is a split brain; they fail fast with
  :class:`~.shard_map.ShardMovedError` instead.
- :func:`install_cluster_guard` appends an inbound middleware on a member's
  hub that REJECTS calls whose ``@shard`` this member does not own under
  its current map (``@failover`` widens acceptance to the replica). The
  rejection is a normal ``$sys.error`` reply carrying a ``ShardMovedError``
  with the member's current map — the client applies it and retries once
  (bounded), which is the client's lazy map-sync path: no subscription
  needed, staleness self-corrects on first contact. A client stamping a
  NEWER epoch than ours is let through: it routed here per a map we have
  not learned yet, and per that map we are the owner.

Routing keys: the shard of a call is derived from ``repr(args[key_arg])``
(matching the historic ``consistent_hash_router`` contract); command
envelopes route by their payload's ``shard_key()``/first field when the
argument is a registered command (see ``key_for``).
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Tuple

from ..diagnostics.hotkeys import global_hotkeys
from ..diagnostics.metrics import global_metrics
from ..rpc.message import (
    COMPUTE_SYSTEM_SERVICE,
    DIAG_SYSTEM_SERVICE,
    MEMBER_SYSTEM_SERVICE,
    SYSTEM_SERVICE,
    TABLE_SYSTEM_SERVICE,
    RpcMessage,
)
from ..utils.errors import ExceptionInfo
from ..utils.serialization import dumps
from .shard_map import ShardMap, ShardMovedError

log = logging.getLogger("stl_fusion_tpu")

__all__ = [
    "SHARD_HEADER",
    "EPOCH_HEADER",
    "FAILOVER_HEADER",
    "ShardMapRouter",
    "install_cluster_guard",
    "install_cluster_client",
]

SHARD_HEADER = "@shard"
EPOCH_HEADER = "@epoch"
FAILOVER_HEADER = "@failover"

#: the command-bridge RPC service name (commands/rpc_bridge.py) — imported
#: as a literal to keep this module cycle-free; commands always fail fast
#: on an unreachable owner instead of failing over
DEFAULT_COMMAND_SERVICES = ("$commander",)

_SYSTEM_SERVICES = frozenset(
    {
        SYSTEM_SERVICE,
        COMPUTE_SYSTEM_SERVICE,
        TABLE_SYSTEM_SERVICE,
        DIAG_SYSTEM_SERVICE,
        MEMBER_SYSTEM_SERVICE,
    }
)


class ShardMapRouter:
    """key → virtual shard → owner member, against a live epoch-versioned
    :class:`ShardMap`. Installable anywhere an ``RpcCallRouter`` fits."""

    def __init__(
        self,
        rpc_hub,
        members: Optional[List[str]] = None,
        shard_map: Optional[ShardMap] = None,
        key_arg: int = 0,
        n_shards: int = 256,
        command_services: Tuple[str, ...] = DEFAULT_COMMAND_SERVICES,
        key_fn: Optional[Callable[[str, str, tuple], str]] = None,
        failover_ttl: float = 2.0,
    ):
        if shard_map is None:
            if not members:
                raise ValueError("ShardMapRouter needs members or an explicit shard_map")
            shard_map = ShardMap.initial(members, n_shards=n_shards)
        self.rpc_hub = rpc_hub
        self.shard_map = shard_map
        self.key_arg = key_arg
        self.command_services = frozenset(command_services)
        self.key_fn = key_fn
        #: lifetime of a failover-served computed. The replica's ``$sys-c``
        #: subscription cannot see the owner's writes, and an owner that
        #: recovers WITHOUT an epoch change (outage shorter than the
        #: failure timeout) fences nothing — so failover reads must expire
        #: on a clock: the client layer schedules an invalidation this many
        #: seconds after serving one, and the re-read routes back to the
        #: owner. Sized to the membership failure timeout: outages longer
        #: than that evict the owner, and the reshard fence takes over.
        self.failover_ttl = failover_ttl
        #: callbacks ``(old_map, new_map)`` fired on every applied epoch —
        #: the rebalancer's trigger (cluster/rebalancer.py)
        self.on_map_change: List[Callable[[ShardMap, ShardMap], None]] = []
        # -- counters (collector-exported; report()["cluster"]) -----------
        self.routed_calls: Dict[str, int] = {}
        self.failover_reads = 0
        self.maps_applied = 0
        self.moved_rejections_seen = 0  # ShardMovedErrors whose map we applied
        global_metrics().register_collector(self, ShardMapRouter._collect_metrics)
        global_metrics().set_aggregation("fusion_shard_map_epoch", "max")

    def _collect_metrics(self) -> dict:
        out = {
            "fusion_shard_map_epoch": self.shard_map.epoch,
            "fusion_failover_reads_total": self.failover_reads,
            "fusion_shard_maps_applied_total": self.maps_applied,
            "fusion_routed_calls_total": sum(self.routed_calls.values()),
        }
        for peer, n in self.routed_calls.items():
            out[f'fusion_routed_calls_total{{peer="{peer}"}}'] = n
        return out

    # ------------------------------------------------------------------ keys
    def key_for(self, service: str, method: str, args: tuple) -> str:
        if self.key_fn is not None:
            return self.key_fn(service, method, args)
        if len(args) > self.key_arg:
            arg = args[self.key_arg]
            # command envelopes (the bridge forwards the command object as
            # arg0): route by the command's own shard key when it names one
            shard_key = getattr(arg, "shard_key", None)
            if callable(shard_key):
                return repr(shard_key())
            return repr(arg)
        return service

    def shard_for(self, service: str, method: str, args: tuple) -> int:
        return self.shard_map.shard_of(self.key_for(service, method, args))

    # ------------------------------------------------------------------ routing
    def _down(self, ref: str) -> bool:
        """Is the member unreachable RIGHT NOW, by signals the process
        already tracks: an open circuit breaker, a terminated peer, or a
        client peer sitting in dial-retry backoff (``reconnects_at`` is
        only ever set while the last dial has failed)."""
        peer = self.rpc_hub.peers.get(ref)
        if peer is None:
            return False  # never dialed: optimistically up
        breaker = getattr(peer, "breaker", None)
        if breaker is not None and breaker.state == "open":
            return True
        if peer.connection_state.latest().value.is_terminated:
            return True
        return getattr(peer, "reconnects_at", None) is not None

    def route(self, service: str, method: str, args: tuple) -> Tuple[str, tuple]:
        """``(peer_ref, headers)`` for one call. Raises ``ShardMovedError``
        for a command whose owner is unreachable (fail fast — never
        split-brain a write onto a replica)."""
        smap = self.shard_map
        shard = smap.shard_of(self.key_for(service, method, args))
        # attribution (ISSUE 19): per-shard routing pressure, plus the
        # shard|method sketch the straggler table joins against ("the
        # slow shard's hottest keys")
        board = global_hotkeys()
        board.offer("routed_shards", str(shard))
        board.offer("shard_keys", f"{shard}|{service}.{method}")
        # owner from the cached assignment table (O(1)); the rendezvous
        # re-sort in owners_for_shard stays off this per-call path
        owner = smap.owner_of_shard(shard)
        if owner is None:
            raise ShardMovedError(f"shard map epoch {smap.epoch} has no members")
        headers = ((SHARD_HEADER, str(shard)), (EPOCH_HEADER, str(smap.epoch)))
        if self._down(owner):
            if service in self.command_services:
                raise ShardMovedError(
                    f"owner {owner} of shard {shard} is unreachable; "
                    f"commands fail fast (no split-brain failover)",
                    shard_map=smap,
                )
            replica = smap.replica_of_shard(shard)
            if replica is not None and not self._down(replica):
                self.failover_reads += 1
                self.routed_calls[replica] = self.routed_calls.get(replica, 0) + 1
                return replica, headers + ((FAILOVER_HEADER, "1"),)
        self.routed_calls[owner] = self.routed_calls.get(owner, 0) + 1
        return owner, headers

    def headers_for(
        self, service: str, method: str, args: tuple, peer_ref: Optional[str] = None
    ) -> tuple:
        """Stamp headers for a call whose peer was ALREADY chosen (the
        per-peer FusionClients a RoutingComputeProxy caches): same shard +
        epoch stamp, plus ``@failover`` when the chosen peer is not the
        owner — the guard then accepts the replica."""
        smap = self.shard_map
        shard = smap.shard_of(self.key_for(service, method, args))
        headers = [(SHARD_HEADER, str(shard)), (EPOCH_HEADER, str(smap.epoch))]
        if peer_ref is not None and peer_ref != smap.owner_of_shard(shard):
            headers.append((FAILOVER_HEADER, "1"))
        return tuple(headers)

    def __call__(self, service: str, method: str, args: tuple) -> str:
        return self.route(service, method, args)[0]

    # ------------------------------------------------------------------ maps
    def apply_map(self, new_map: ShardMap) -> bool:
        """Adopt a newer epoch (older/equal epochs are ignored — epochs
        totally order maps). Fires ``on_map_change`` callbacks."""
        old = self.shard_map
        if new_map.epoch <= old.epoch:
            return False
        self.shard_map = new_map
        self.maps_applied += 1
        for cb in list(self.on_map_change):
            try:
                cb(old, new_map)
            except Exception:  # noqa: BLE001 — one bad listener never blocks the map
                log.exception("shard-map change callback failed")
        return True

    def apply_wire_map(self, wire: Optional[dict]) -> bool:
        if not wire:
            return False
        try:
            new_map = ShardMap.from_wire(wire)
        except (KeyError, ValueError, TypeError):
            return False
        return self.apply_map(new_map)

    def note_moved(self, error: ShardMovedError) -> bool:
        """Apply the map a rejection carried (the client's lazy sync)."""
        self.moved_rejections_seen += 1
        return self.apply_wire_map(error.map_wire)

    def snapshot(self) -> dict:
        smap = self.shard_map
        return {
            "epoch": smap.epoch,
            "members": list(smap.members),
            "n_shards": smap.n_shards,
            "coordinator": smap.coordinator,
            "routed_calls": dict(self.routed_calls),
            "failover_reads": self.failover_reads,
            "maps_applied": self.maps_applied,
            "moved_rejections_seen": self.moved_rejections_seen,
        }


# ---------------------------------------------------------------------- server


def install_cluster_guard(rpc_hub, member) -> Callable:
    """Append the shard-fence middleware on a member's hub: calls stamped
    with a ``@shard`` this member does not own (under ITS current map) are
    answered with a ``$sys.error`` carrying a ``ShardMovedError`` + the
    current map, and never dispatched. Unstamped calls and system frames
    pass through untouched (wire compat with cluster-unaware clients).
    Returns the middleware (callers may remove it to uninstall)."""

    async def guard(peer, message: RpcMessage, nxt):
        shard_h = message.header(SHARD_HEADER)
        if shard_h is None or message.service in _SYSTEM_SERVICES:
            await nxt(message)
            return
        smap = member.shard_map
        epoch_h = message.header(EPOCH_HEADER)
        try:
            shard = int(shard_h)
            client_epoch = int(epoch_h) if epoch_h is not None else -1
        except ValueError:
            await nxt(message)  # malformed stamp: treat as unstamped
            return
        if client_epoch > smap.epoch:
            # the client learned a map we have not: per THAT map it chose
            # us, and honoring it avoids a reject-retry livelock while the
            # coordinator's broadcast is in flight
            await nxt(message)
            return
        if client_epoch == smap.epoch:
            width = 2 if message.header(FAILOVER_HEADER) else 1
            if member.member_id in smap.owners_for_shard(shard, width):
                await nxt(message)
                return
        # stale epoch (client_epoch < ours) is rejected OUTRIGHT, even when
        # the stale map happens to agree on this shard's owner: the reject-
        # apply-retry round trip is the client's ONLY guaranteed map sync
        # (a client that connected after the last epoch change has nobody
        # pushing maps to it until the next change) — one bounded retry
        # buys every later call a correct stamp. Same-epoch disagreement
        # (possible only under a split coordinator) also lands here: loud
        # rejection, never a silently-wrong owner.
        member.stale_rejections += 1
        err = ShardMovedError(
            f"shard {shard} is owned by {smap.owner_of_shard(shard)} at epoch "
            f"{smap.epoch}, not {member.member_id} (caller stamped epoch "
            f"{client_epoch})",
            shard_map=smap,
        )
        if message.call_id:
            await peer.send(
                RpcMessage(
                    message.call_type_id,
                    message.call_id,
                    SYSTEM_SERVICE,
                    "error",
                    dumps(ExceptionInfo.capture(err)),
                )
            )

    rpc_hub.inbound_middlewares.append(guard)
    return guard


# ---------------------------------------------------------------------- client


def install_cluster_client(rpc_hub, router: ShardMapRouter):
    """Wire a CLIENT hub into the control plane: ``$sys-m.map`` pushes from
    any connected member apply to the router (which fires the rebalancer's
    fencing). Returns the router for chaining. The other client sync path —
    ``ShardMovedError`` rejections — needs no installation; the hub/client
    layers apply those maps wherever they catch the error."""
    from ..utils.serialization import loads

    def handler(peer, message: RpcMessage):
        if message.method == "map":
            (wire,) = loads(message.argument_data)
            if isinstance(wire, ShardMap):  # wire-typed payload decodes directly
                router.apply_map(wire)
            elif isinstance(wire, dict):
                router.apply_wire_map(wire)

    rpc_hub.member_system_handler = handler
    return router
