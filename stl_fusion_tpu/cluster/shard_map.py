"""Epoch-versioned shard map — the cluster's one routing truth.

The reference's only scaling story is a user-pluggable consistent-hash call
router over a STATIC pool (samples/MultiServerRpc/Program.cs:58-76); our
port was faithfully static: sha1-mod-N over a fixed peer list, so one
member change silently remapped ~(N-1)/N of all keys. This module replaces
that with the two-level mapping every elastic system converges on:

    key --sha1 mod V--> virtual shard --rendezvous hashing--> owner member

- **V virtual shards** (default 256): the unit of movement and of cache
  fencing. A key's shard NEVER changes; only shard→member assignments do.
- **Rendezvous (highest-random-weight) hashing** per shard: owner = the
  member with the highest sha1(member|shard) score. Removing a member moves
  EXACTLY the shards it owned (~V/N); adding one moves ~V/(N+1) — the
  minimal-movement property the modulo router lacked, with no ring state to
  replicate (the assignment is a pure function of the member set).
- **Epochs**: every membership change mints ``epoch + 1``. Epochs totally
  order maps; routers/guards compare epochs, never member lists.
- **Wire-serializable and tiny**: only ``(epoch, members, n_shards)``
  travels — the V-entry assignment is derived deterministically on both
  ends (sha1, never the salted builtin ``hash()``, so it is identical
  across processes and restarts).

``diff(old, new)`` is THE primitive everything else consumes: the
rebalancer fences exactly the moved shards' client caches, tests assert
minimal movement through it, and the flight recorder journals it.

Pure module by design: stdlib + utils only (rpc/client/core import it
function-locally without cycles).
"""
from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from functools import cached_property
from typing import Optional, Sequence, Tuple

from ..utils.errors import register_exception_type
from ..utils.serialization import register_wire_type

__all__ = ["DEFAULT_SHARDS", "ShardMap", "ShardMovedError"]

DEFAULT_SHARDS = 256


def _score(member: str, shard: int) -> int:
    """Rendezvous weight of ``member`` for ``shard`` — sha1-based so the
    ranking is stable across processes, restarts, and PYTHONHASHSEED."""
    digest = hashlib.sha1(f"{member}|{shard}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


@dataclass(frozen=True)
class ShardMap:
    """One immutable epoch of the cluster's shard assignment."""

    epoch: int
    members: Tuple[str, ...]
    n_shards: int = DEFAULT_SHARDS

    # ------------------------------------------------------------------ build
    @staticmethod
    def initial(members: Sequence[str], n_shards: int = DEFAULT_SHARDS, epoch: int = 0) -> "ShardMap":
        """Bootstrap map. Epoch 0 by convention: a joiner's seed view, which
        ANY coordinator-minted map (epoch ≥ 1) overrides."""
        return ShardMap(epoch=epoch, members=tuple(sorted(set(members))), n_shards=n_shards)

    def with_members(self, members: Sequence[str]) -> "ShardMap":
        """The next epoch for a changed member set (identical sets still
        bump — an epoch is a membership DECISION, not a diff)."""
        return ShardMap(
            epoch=self.epoch + 1,
            members=tuple(sorted(set(members))),
            n_shards=self.n_shards,
        )

    # ------------------------------------------------------------------ lookup
    @cached_property
    def assignment(self) -> Tuple[str, ...]:
        """shard id → owner member (derived, deterministic, cached)."""
        if not self.members:
            return ()
        return tuple(
            max(self.members, key=lambda m, s=shard: (_score(m, s), m))
            for shard in range(self.n_shards)
        )

    def shard_of(self, key: str) -> int:
        digest = hashlib.sha1(str(key).encode()).digest()
        return int.from_bytes(digest[:8], "big") % self.n_shards

    def owner_of_shard(self, shard: int) -> Optional[str]:
        assignment = self.assignment
        return assignment[shard % self.n_shards] if assignment else None

    def owner_of(self, key: str) -> Optional[str]:
        return self.owner_of_shard(self.shard_of(key))

    def owners_for_shard(self, shard: int, count: int = 2) -> Tuple[str, ...]:
        """The first ``count`` members in the shard's rendezvous order —
        entry 0 is the owner, entry 1 the read-failover replica."""
        if not self.members:
            return ()
        ranked = sorted(
            self.members, key=lambda m: (_score(m, shard % self.n_shards), m), reverse=True
        )
        return tuple(ranked[:count])

    def replica_of_shard(self, shard: int) -> Optional[str]:
        owners = self.owners_for_shard(shard, 2)
        return owners[1] if len(owners) > 1 else None

    @property
    def coordinator(self) -> Optional[str]:
        """Deterministic single coordinator: the lowest member id. A control
        -plane convention, NOT consensus — CLUSTER.md documents what that
        does and does not guarantee."""
        return min(self.members) if self.members else None

    # ------------------------------------------------------------------ diff
    @staticmethod
    def diff(old: "ShardMap", new: "ShardMap") -> Tuple[int, ...]:
        """Shard ids whose owner changed between two maps (deterministic,
        ascending) — the fence set the rebalancer drives."""
        if old.n_shards != new.n_shards:
            return tuple(range(new.n_shards))
        a, b = old.assignment, new.assignment
        if not a or not b:
            return tuple(range(new.n_shards)) if a != b else ()
        return tuple(s for s in range(new.n_shards) if a[s] != b[s])

    # ------------------------------------------------------------------ wire
    def to_wire(self) -> dict:
        return {"epoch": self.epoch, "members": list(self.members), "n_shards": self.n_shards}

    @staticmethod
    def from_wire(d: dict) -> "ShardMap":
        return ShardMap(
            epoch=int(d["epoch"]),
            members=tuple(d["members"]),
            n_shards=int(d.get("n_shards", DEFAULT_SHARDS)),
        )

    def __repr__(self) -> str:
        return (
            f"ShardMap(epoch={self.epoch}, members={list(self.members)}, "
            f"V={self.n_shards})"
        )


# assignment is derived — only (epoch, members, n_shards) travels
register_wire_type(
    ShardMap,
    to_dict=lambda m: m.to_wire(),
    from_dict=ShardMap.from_wire,
)


class ShardMovedError(Exception):
    """A call landed on a member that does not own its key's shard (or the
    owner is unreachable for a command). Carries the rejecting side's
    CURRENT map so the caller can apply-and-retry once.

    Travels over the ``$sys.error`` ExceptionInfo channel, which transports
    only ``(type_name, message)`` — so the map rides embedded in the
    message string (``...|map={json}``) and the single-argument constructor
    re-parses it on the receiving side. Registered as a known exception
    type, so both ends that imported this module reconstruct the real class
    (a cluster-unaware process sees a plain ``RemoteError``, which is fine:
    no cluster, no retry logic)."""

    _MARK = "|map="

    def __init__(self, message: str = "", shard_map: Optional[ShardMap] = None):
        if shard_map is not None and self._MARK not in message:
            message = f"{message}{self._MARK}{json.dumps(shard_map.to_wire(), separators=(',', ':'))}"
        super().__init__(message)
        self.map_wire: Optional[dict] = None
        if self._MARK in message:
            try:
                self.map_wire = json.loads(message.partition(self._MARK)[2])
            except (ValueError, TypeError):
                self.map_wire = None

    @property
    def shard_map(self) -> Optional[ShardMap]:
        if self.map_wire is None:
            return None
        try:
            return ShardMap.from_wire(self.map_wire)
        except (KeyError, ValueError, TypeError):
            return None


register_exception_type(ShardMovedError)
