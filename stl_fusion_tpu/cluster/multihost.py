"""Multi-host mesh bring-up over REAL process boundaries (ISSUE 15).

PR 9's routed mesh was oracle-exact at 80M nodes, but on 8 virtual devices
in ONE process — the "cross-host" leg never crossed a process boundary.
This module stands up the honest version: each emulated host is a separate
OS process owning its own XLA CPU device pool
(``--xla_force_host_platform_device_count``), joined into ONE global device
mesh through ``jax.distributed.initialize`` with the gloo CPU collectives
backend. A ``ppermute``/``all_to_all`` issued inside the routed wave then
moves bytes between processes — the DCN leg is exercised, not merely
counted (the MULTICHIP protocol's standing complaint).

Layout contract (what :class:`~.placement.DevicePlacement`'s host axis
leans on): ``jax.devices()`` orders the global pool process 0 first, so
host ``h`` owns the contiguous device range ``[h*dph, (h+1)*dph)`` —
:func:`init_multihost` VERIFIES this against each device's
``process_index`` instead of assuming it.

Three pieces:

- :func:`init_multihost` — called by a HOST process after import, before
  any jax computation. Reads the ``FUSION_MH_*`` env the launcher set (or
  explicit args), configures gloo + ``jax.distributed``, validates the
  device/process layout, and returns a :class:`MultiHostContext`.
  ``n_hosts=1`` short-circuits to a single-process context (no
  distributed runtime) so the same worker script runs both shapes — the
  chaos ladder's "survivor serves alone" phase is exactly that.
- :func:`launch_hosts` — called by an ORCHESTRATOR (perf driver, CI
  smoke): spawns one OS process per host with the right env
  (``XLA_FLAGS`` device emulation, coordinator address, process id) and
  returns the Popen handles. Killing one of them IS the host-kill chaos
  primitive.
- :class:`MultiHostContext` — the bring-up facts (process id, host count,
  devices per host) + helpers the routed graph and the perf workers use:
  the global mesh, member naming, host-of-device math, and a collective
  barrier for phase sequencing.

Gotcha (measured, not theoretical): setting
``jax_cpu_collectives_implementation=gloo`` WITHOUT then initializing
``jax.distributed`` breaks single-process CPU client creation on this
jax — so the gloo config is applied only on the genuinely multi-process
path.
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

__all__ = [
    "MultiHostContext",
    "init_multihost",
    "launch_hosts",
    "host_env",
    "pick_coordinator",
    "ENV_NUM_HOSTS",
    "ENV_PROCESS_ID",
    "ENV_COORDINATOR",
    "ENV_DEVICES_PER_HOST",
]

ENV_NUM_HOSTS = "FUSION_MH_NUM_HOSTS"
ENV_PROCESS_ID = "FUSION_MH_PROCESS_ID"
ENV_COORDINATOR = "FUSION_MH_COORDINATOR"
ENV_DEVICES_PER_HOST = "FUSION_MH_DEVICES_PER_HOST"

_DEVCOUNT_FLAG = "--xla_force_host_platform_device_count"


@dataclass
class MultiHostContext:
    """One host process's view of the multi-host mesh."""

    process_id: int
    n_hosts: int
    devices_per_host: int
    coordinator: Optional[str] = None

    @property
    def n_dev(self) -> int:
        return self.n_hosts * self.devices_per_host

    @property
    def is_multiprocess(self) -> bool:
        return self.n_hosts > 1

    def host_of_device(self, dev: int) -> int:
        return dev // self.devices_per_host

    def member_names(self, prefix: str = "h") -> List[str]:
        """One cluster member per host process — the natural mapping the
        perf workers and the placement's ``mesh_members`` use."""
        return [f"{prefix}{i}" for i in range(self.n_hosts)]

    def mesh(self):
        """1-D global graph mesh over every device of every host."""
        from ..parallel.mesh import graph_mesh

        return graph_mesh()

    def sync(self, tag: str = "fusion-mh") -> None:
        """Collective barrier across every host process (no-op single
        host). Used between worker phases so asymmetric host work (the
        DCN leg's server/client split) never interleaves with a phase
        that dispatches collectives."""
        if not self.is_multiprocess:
            return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)

    def shutdown(self) -> None:
        if not self.is_multiprocess:
            return
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:  # noqa: BLE001 — already torn down / peer gone
            # best-effort: a chaos-killed peer can leave the coordinator
            # unreachable, and shutdown-on-exit must not mask the run's
            # real result; counted by the caller's exit path, not here
            pass


def init_multihost(
    n_hosts: Optional[int] = None,
    process_id: Optional[int] = None,
    coordinator: Optional[str] = None,
    devices_per_host: Optional[int] = None,
) -> MultiHostContext:
    """Join (or short-circuit) the multi-host mesh from a host process.

    Arguments default from the ``FUSION_MH_*`` env :func:`launch_hosts`
    exports. Must run before the first jax computation; the XLA device
    count itself comes from ``XLA_FLAGS`` which the LAUNCHER set (it is
    baked at backend creation and cannot be set here)."""
    n_hosts = int(os.environ.get(ENV_NUM_HOSTS, "1")) if n_hosts is None else n_hosts
    process_id = (
        int(os.environ.get(ENV_PROCESS_ID, "0")) if process_id is None else process_id
    )
    coordinator = coordinator or os.environ.get(ENV_COORDINATOR)
    import jax

    # the axon site plugin force-selects the TPU platform at interpreter
    # start and beats JAX_PLATFORMS=cpu (verify skill gotcha); the emulated
    # hosts are CPU pools by contract
    try:
        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend already initialized
        pass
    if n_hosts > 1:
        if not coordinator:
            raise ValueError(f"multi-host init needs a coordinator ({ENV_COORDINATOR})")
        # gloo ONLY on the real multi-process path: configuring it without
        # jax.distributed.initialize breaks CPU client creation outright
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=n_hosts,
            process_id=process_id,
        )
    local = jax.local_device_count()
    if devices_per_host is None:
        devices_per_host = int(os.environ.get(ENV_DEVICES_PER_HOST, str(local)))
    if local != devices_per_host:
        raise RuntimeError(
            f"host {process_id} has {local} local devices, expected "
            f"{devices_per_host} (launcher XLA_FLAGS mismatch)"
        )
    if jax.process_count() != n_hosts:
        raise RuntimeError(
            f"distributed runtime spans {jax.process_count()} processes, "
            f"expected {n_hosts}"
        )
    # the placement's host axis assumes host h == the contiguous device
    # block [h*dph, (h+1)*dph) — verify against the real process layout
    for i, d in enumerate(jax.devices()):
        if d.process_index != i // devices_per_host:
            raise RuntimeError(
                f"global device {i} belongs to process {d.process_index}, "
                f"host-axis contract expects {i // devices_per_host}"
            )
    from ..diagnostics.metrics import global_metrics

    reg = global_metrics()
    g = reg.gauge(
        "fusion_mesh_hosts",
        help="host processes joined into the global device mesh",
    )
    g.set(n_hosts)
    reg.set_aggregation("fusion_mesh_hosts", "max")
    return MultiHostContext(
        process_id=process_id,
        n_hosts=n_hosts,
        devices_per_host=devices_per_host,
        coordinator=coordinator,
    )


def pick_coordinator(host: str = "127.0.0.1") -> str:
    """A free coordinator address on this machine (bind-then-release; the
    distributed service binds it again moments later)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return f"{host}:{s.getsockname()[1]}"


def _with_device_count(xla_flags: str, devices_per_host: int) -> str:
    kept = [
        f for f in xla_flags.split() if not f.startswith(_DEVCOUNT_FLAG + "=")
    ]
    kept.append(f"{_DEVCOUNT_FLAG}={devices_per_host}")
    return " ".join(kept)


def host_env(
    n_hosts: int,
    process_id: int,
    coordinator: str,
    devices_per_host: int,
    base_env: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """The child env for one emulated host process. Preserves the parent
    environment (PYTHONPATH especially: the axon site dir must survive or
    every jax import in the child fails) and overrides the mesh vars."""
    env = dict(base_env if base_env is not None else os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = _with_device_count(env.get("XLA_FLAGS", ""), devices_per_host)
    env[ENV_NUM_HOSTS] = str(n_hosts)
    env[ENV_PROCESS_ID] = str(process_id)
    env[ENV_COORDINATOR] = coordinator
    env[ENV_DEVICES_PER_HOST] = str(devices_per_host)
    env.setdefault("PYTHONUNBUFFERED", "1")
    return env


def launch_hosts(
    argv: Sequence[str],
    n_hosts: int,
    devices_per_host: int,
    coordinator: Optional[str] = None,
    env: Optional[Dict[str, str]] = None,
    stdout=None,
    stderr=None,
) -> List[subprocess.Popen]:
    """Spawn ``n_hosts`` OS processes running ``argv`` (typically
    ``[sys.executable, worker_script, ...]``), each configured as one
    emulated host of the shared mesh. The caller owns the handles —
    ``procs[i].kill()`` is the host-kill chaos primitive, ``wait()`` the
    join. ``stdout``/``stderr`` apply to every child (default: inherit,
    so worker gate output lands in the orchestrator's log)."""
    coordinator = coordinator or pick_coordinator()
    procs: List[subprocess.Popen] = []
    for i in range(n_hosts):
        procs.append(
            subprocess.Popen(
                list(argv),
                env=host_env(n_hosts, i, coordinator, devices_per_host, base_env=env),
                stdout=stdout,
                stderr=stderr,
            )
        )
    return procs


if __name__ == "__main__":  # tiny self-check harness (used by tests)
    ctx = init_multihost()
    import jax
    import numpy as np
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import GRAPH_AXIS, shard_map_compat

    mesh = ctx.mesh()
    sh = NamedSharding(mesh, P(GRAPH_AXIS))
    x = jax.device_put(np.arange(ctx.n_dev * 8, dtype=np.int32), sh)

    @jax.jit
    def f(x):
        @shard_map_compat(mesh=mesh, in_specs=(P(GRAPH_AXIS),), out_specs=P(GRAPH_AXIS))
        def inner(xl):
            return xl + lax.psum(xl.sum(), GRAPH_AXIS)

        return inner(x)

    y = f(x)
    total = int(np.asarray(ctx.n_dev * 8 * (ctx.n_dev * 8 - 1) // 2))
    got = np.asarray(y.addressable_shards[0].data)
    want = np.asarray(x.addressable_shards[0].data) + total
    ok = bool(np.array_equal(got, want))
    print(
        f"multihost-selfcheck host={ctx.process_id}/{ctx.n_hosts} "
        f"dph={ctx.devices_per_host} psum_ok={ok}",
        flush=True,
    )
    ctx.shutdown()
    sys.exit(0 if ok else 1)
