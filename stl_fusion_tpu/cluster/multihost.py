"""Multi-host mesh bring-up over REAL process boundaries (ISSUE 15).

PR 9's routed mesh was oracle-exact at 80M nodes, but on 8 virtual devices
in ONE process — the "cross-host" leg never crossed a process boundary.
This module stands up the honest version: each emulated host is a separate
OS process owning its own XLA CPU device pool
(``--xla_force_host_platform_device_count``), joined into ONE global device
mesh through ``jax.distributed.initialize`` with the gloo CPU collectives
backend. A ``ppermute``/``all_to_all`` issued inside the routed wave then
moves bytes between processes — the DCN leg is exercised, not merely
counted (the MULTICHIP protocol's standing complaint).

Layout contract (what :class:`~.placement.DevicePlacement`'s host axis
leans on): ``jax.devices()`` orders the global pool process 0 first, so
host ``h`` owns the contiguous device range ``[h*dph, (h+1)*dph)`` —
:func:`init_multihost` VERIFIES this against each device's
``process_index`` instead of assuming it.

Three pieces:

- :func:`init_multihost` — called by a HOST process after import, before
  any jax computation. Reads the ``FUSION_MH_*`` env the launcher set (or
  explicit args), configures gloo + ``jax.distributed``, validates the
  device/process layout, and returns a :class:`MultiHostContext`.
  ``n_hosts=1`` short-circuits to a single-process context (no
  distributed runtime) so the same worker script runs both shapes — the
  chaos ladder's "survivor serves alone" phase is exactly that.
- :func:`launch_hosts` — called by an ORCHESTRATOR (perf driver, CI
  smoke): spawns one OS process per host with the right env
  (``XLA_FLAGS`` device emulation, coordinator address, process id) and
  returns the Popen handles. Killing one of them IS the host-kill chaos
  primitive.
- :class:`MultiHostContext` — the bring-up facts (process id, host count,
  devices per host) + helpers the routed graph and the perf workers use:
  the global mesh, member naming, host-of-device math, and a collective
  barrier for phase sequencing.

Gotcha (measured, not theoretical): setting
``jax_cpu_collectives_implementation=gloo`` WITHOUT then initializing
``jax.distributed`` breaks single-process CPU client creation on this
jax — so the gloo config is applied only on the genuinely multi-process
path.

Elastic world mechanics (ISSUE 16): ``jax.distributed.initialize`` can
run exactly once per process (it refuses after backends exist), and the
coordination service it installs is all-or-nothing — any task death
propagates a fatal error that ABORTS every survivor from inside the
error-polling agent (measured: SIGKILL a peer and the survivor dies
rc=-6 in ``PollForError`` with no Python frame on the stack). Both
properties are wrong for a mesh that must outlive its members, so this
module owns the world lifecycle directly:

- :func:`form_world` builds the coordination service (process 0) and
  client through ``xla_extension`` and installs them into jax's
  ``global_state`` — repeatable any number of times per process.
- :func:`detach_world` gracefully retires the coordination agent AFTER
  backend formation (``client.shutdown()`` is itself the cross-host
  barrier). The gloo pairs are already established peer-to-peer, so
  collectives keep running — but with no agent left polling, a later
  peer death can no longer abort the survivor. Failure detection moves
  where it belongs: :class:`~.mesh_controller.MeshController`.
- :func:`teardown_world` abandons a (possibly wedged) world in-process:
  drop the service/client refs, clear backends + jit caches, reset the
  collectives config. A dispatch thread blocked inside a wedged gloo
  collective keeps the OLD backend alive as a zombie (C++ offers no
  cancellation); the fresh world forms on new ports regardless — that
  leaked thread is the measured cost of surviving without a restart.
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

__all__ = [
    "MultiHostContext",
    "init_multihost",
    "launch_hosts",
    "host_env",
    "pick_coordinator",
    "form_world",
    "detach_world",
    "teardown_world",
    "world_is_formed",
    "ENV_NUM_HOSTS",
    "ENV_PROCESS_ID",
    "ENV_COORDINATOR",
    "ENV_DEVICES_PER_HOST",
    "ENV_ASYNC_DEPTH",
    "async_depth_env",
]

ENV_NUM_HOSTS = "FUSION_MH_NUM_HOSTS"
ENV_PROCESS_ID = "FUSION_MH_PROCESS_ID"
ENV_COORDINATOR = "FUSION_MH_COORDINATOR"
ENV_DEVICES_PER_HOST = "FUSION_MH_DEVICES_PER_HOST"
#: asynchronous frontier execution across real host processes (ISSUE 17):
#: > 0 switches every routed wave a worker builds to async mode at that
#: speculation depth; 0 (default) keeps the bulk-synchronous exchange.
#: One shared parsing site so the scale / geometry / elastic workers and
#: the orchestrator can never disagree on the mode under test.
ENV_ASYNC_DEPTH = "FUSION_MH_ASYNC_DEPTH"


def async_depth_env(default: int = 0) -> int:
    """The async speculation depth this process should run routed waves
    at (``FUSION_MH_ASYNC_DEPTH``; 0 = synchronous per-level exchange).
    Every host process of a mesh must agree — the wave program is SPMD —
    which is why workers read the env rather than taking a per-call
    argument."""
    try:
        depth = int(os.environ.get(ENV_ASYNC_DEPTH, str(default)))
    except ValueError:
        return default
    return max(depth, 0)

_DEVCOUNT_FLAG = "--xla_force_host_platform_device_count"


@dataclass
class MultiHostContext:
    """One host process's view of the multi-host mesh."""

    process_id: int
    n_hosts: int
    devices_per_host: int
    coordinator: Optional[str] = None
    #: the coordination agent has been retired (detach_world): collectives
    #: still run over the established gloo pairs, but cross-host phase
    #: sequencing must come from the caller's own machinery, and shutdown
    #: is a local drop instead of a coordinated barrier
    detached: bool = False

    @property
    def n_dev(self) -> int:
        return self.n_hosts * self.devices_per_host

    @property
    def is_multiprocess(self) -> bool:
        return self.n_hosts > 1

    def host_of_device(self, dev: int) -> int:
        return dev // self.devices_per_host

    def member_names(self, prefix: str = "h") -> List[str]:
        """One cluster member per host process — the natural mapping the
        perf workers and the placement's ``mesh_members`` use."""
        return [f"{prefix}{i}" for i in range(self.n_hosts)]

    def mesh(self):
        """1-D global graph mesh over every device of every host."""
        from ..parallel.mesh import graph_mesh

        return graph_mesh()

    def sync(self, tag: str = "fusion-mh") -> None:
        """Collective barrier across every host process (no-op single
        host). Used between worker phases so asymmetric host work (the
        DCN leg's server/client split) never interleaves with a phase
        that dispatches collectives."""
        if not self.is_multiprocess:
            return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)

    def detach(self) -> bool:
        """Retire this host's coordination agent (see :func:`detach_world`).
        Blocks until every host calls it — the agent's shutdown barrier IS
        the cross-host synchronization point."""
        if not self.is_multiprocess or self.detached:
            return False
        self.detached = detach_world()
        return self.detached

    def shutdown(self) -> None:
        if not self.is_multiprocess:
            return
        if self.detached:
            # no agent left to coordinate a barrier through — local drop
            teardown_world(rebuild_local=False)
            return
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:  # noqa: BLE001 — already torn down / peer gone
            # best-effort: a chaos-killed peer can leave the coordinator
            # unreachable, and shutdown-on-exit must not mask the run's
            # real result; counted by the caller's exit path, not here
            pass


def _global_state():
    from jax._src import distributed as jdist

    return jdist.global_state


def world_is_formed() -> bool:
    """Whether a coordination client is currently installed (a DETACHED
    world reports False — its agent is gone by design)."""
    return _global_state().client is not None


def form_world(
    n_hosts: int,
    process_id: int,
    coordinator: str,
    *,
    heartbeat_interval_s: int = 2,
    max_missing_heartbeats: int = 10,
    init_timeout_s: int = 60,
    shutdown_timeout_s: int = 30,
) -> None:
    """Bring up the ``jax.distributed`` world directly (service on process
    0 + client everywhere), installing the handles into jax's
    ``global_state`` exactly as ``jax.distributed.initialize`` would —
    minus its once-per-process restriction, so a surviving process can
    re-form over a new member set after :func:`teardown_world`.

    Idempotence guard: refuses when a client is already installed —
    tear the old world down first, don't stack worlds."""
    import jax
    from jax._src.lib import xla_extension

    state = _global_state()
    if state.client is not None:
        raise RuntimeError("a coordination client is already installed; "
                           "teardown_world() before re-forming")
    try:
        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend already initialized
        pass
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    if process_id == 0 and state.service is None:
        port = coordinator.rsplit(":", 1)[1]
        state.service = xla_extension.get_distributed_runtime_service(
            f"[::]:{port}",
            n_hosts,
            heartbeat_interval=heartbeat_interval_s,
            max_missing_heartbeats=max_missing_heartbeats,
        )
    client = xla_extension.get_distributed_runtime_client(
        coordinator,
        process_id,
        init_timeout=init_timeout_s,
        shutdown_timeout=shutdown_timeout_s,
        heartbeat_interval=heartbeat_interval_s,
        max_missing_heartbeats=max_missing_heartbeats,
        # destruction must NEVER imply a barrier: teardown_world drops the
        # ref with the peer possibly dead, and a destructor that dials the
        # coordinator would wedge the survivor right back
        shutdown_on_destruction=False,
        use_compression=True,
    )
    client.connect()
    state.client = client
    state.process_id = process_id
    state.num_processes = n_hosts
    state.coordinator_address = coordinator


def detach_world() -> bool:
    """Gracefully retire the coordination agent AFTER world formation.

    ``client.shutdown()`` runs the coordination service's own shutdown
    barrier, so every host blocks here until all of them detach — a free
    synchronization point. Afterwards the established gloo communicators
    keep serving collectives, but no agent is left error-polling: a peer
    SIGKILL surfaces as a wedged collective (detectable, survivable)
    instead of a process abort (measured rc=-6 without this). Returns
    False when no client is installed (single-host or already detached)."""
    state = _global_state()
    if state.client is None:
        return False
    state.client.shutdown()
    state.client = None
    return True


def teardown_world(*, rebuild_local: bool = True) -> None:
    """Abandon the current world in-process: drop the coordination
    handles, clear backends and jit caches, and (by default) reset the
    collectives config so the next backend is a plain local CPU pool.

    Safe with a collective wedged on another thread: that thread keeps
    the old backend alive as an abandoned zombie (no cancellation exists
    for an in-flight gloo op), while new backends form independently on
    fresh ports. Callers re-enter :func:`form_world` afterwards — or just
    compute locally when ``rebuild_local`` left the config at ``none``."""
    import jax

    state = _global_state()
    # the dead-peer case: no graceful shutdown is possible; dropping the
    # refs is the teardown (shutdown_on_destruction=False by contract)
    state.client = None
    if state.service is not None:
        try:
            state.service.shutdown()
        except Exception:  # noqa: BLE001 — peers gone mid-barrier; the
            # service is being abandoned either way
            pass
        state.service = None
    state.preemption_sync_manager = None
    state.process_id = 0
    state.num_processes = 1  # the pristine default — the CPU backend
    # factory passes this straight through as num_nodes and rejects None
    state.coordinator_address = None
    if rebuild_local:
        # 'none' (string) is the real local implementation — Python None
        # is rejected by this jax's config validator
        jax.config.update("jax_cpu_collectives_implementation", "none")
    from jax.extend import backend as _jeb

    _jeb.clear_backends()
    jax.clear_caches()


def init_multihost(
    n_hosts: Optional[int] = None,
    process_id: Optional[int] = None,
    coordinator: Optional[str] = None,
    devices_per_host: Optional[int] = None,
) -> MultiHostContext:
    """Join (or short-circuit) the multi-host mesh from a host process.

    Arguments default from the ``FUSION_MH_*`` env :func:`launch_hosts`
    exports. Must run before the first jax computation; the XLA device
    count itself comes from ``XLA_FLAGS`` which the LAUNCHER set (it is
    baked at backend creation and cannot be set here)."""
    n_hosts = int(os.environ.get(ENV_NUM_HOSTS, "1")) if n_hosts is None else n_hosts
    process_id = (
        int(os.environ.get(ENV_PROCESS_ID, "0")) if process_id is None else process_id
    )
    coordinator = coordinator or os.environ.get(ENV_COORDINATOR)
    import jax

    # the axon site plugin force-selects the TPU platform at interpreter
    # start and beats JAX_PLATFORMS=cpu (verify skill gotcha); the emulated
    # hosts are CPU pools by contract
    try:
        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend already initialized
        pass
    if n_hosts > 1:
        if not coordinator:
            raise ValueError(f"multi-host init needs a coordinator ({ENV_COORDINATOR})")
        # gloo ONLY on the real multi-process path: configuring it without
        # a distributed world breaks CPU client creation outright.
        # form_world (not jax.distributed.initialize) so the SAME process
        # can tear down and re-form after a member change — the elastic
        # mesh's whole point (ISSUE 16)
        form_world(n_hosts, process_id, coordinator)
    local = jax.local_device_count()
    if devices_per_host is None:
        devices_per_host = int(os.environ.get(ENV_DEVICES_PER_HOST, str(local)))
    if local != devices_per_host:
        raise RuntimeError(
            f"host {process_id} has {local} local devices, expected "
            f"{devices_per_host} (launcher XLA_FLAGS mismatch)"
        )
    if jax.process_count() != n_hosts:
        raise RuntimeError(
            f"distributed runtime spans {jax.process_count()} processes, "
            f"expected {n_hosts}"
        )
    # the placement's host axis assumes host h == the contiguous device
    # block [h*dph, (h+1)*dph) — verify against the real process layout
    for i, d in enumerate(jax.devices()):
        if d.process_index != i // devices_per_host:
            raise RuntimeError(
                f"global device {i} belongs to process {d.process_index}, "
                f"host-axis contract expects {i // devices_per_host}"
            )
    from ..diagnostics.metrics import global_metrics

    reg = global_metrics()
    g = reg.gauge(
        "fusion_mesh_hosts",
        help="host processes joined into the global device mesh",
    )
    g.set(n_hosts)
    reg.set_aggregation("fusion_mesh_hosts", "max")
    return MultiHostContext(
        process_id=process_id,
        n_hosts=n_hosts,
        devices_per_host=devices_per_host,
        coordinator=coordinator,
    )


def pick_coordinator(host: str = "127.0.0.1") -> str:
    """A free coordinator address on this machine (bind-then-release; the
    distributed service binds it again moments later)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return f"{host}:{s.getsockname()[1]}"


def _with_device_count(xla_flags: str, devices_per_host: int) -> str:
    kept = [
        f for f in xla_flags.split() if not f.startswith(_DEVCOUNT_FLAG + "=")
    ]
    kept.append(f"{_DEVCOUNT_FLAG}={devices_per_host}")
    return " ".join(kept)


def host_env(
    n_hosts: int,
    process_id: int,
    coordinator: str,
    devices_per_host: int,
    base_env: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """The child env for one emulated host process. Preserves the parent
    environment (PYTHONPATH especially: the axon site dir must survive or
    every jax import in the child fails) and overrides the mesh vars."""
    env = dict(base_env if base_env is not None else os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = _with_device_count(env.get("XLA_FLAGS", ""), devices_per_host)
    env[ENV_NUM_HOSTS] = str(n_hosts)
    env[ENV_PROCESS_ID] = str(process_id)
    env[ENV_COORDINATOR] = coordinator
    env[ENV_DEVICES_PER_HOST] = str(devices_per_host)
    env.setdefault("PYTHONUNBUFFERED", "1")
    return env


def launch_hosts(
    argv: Sequence[str],
    n_hosts: int,
    devices_per_host: int,
    coordinator: Optional[str] = None,
    env: Optional[Dict[str, str]] = None,
    stdout=None,
    stderr=None,
) -> List[subprocess.Popen]:
    """Spawn ``n_hosts`` OS processes running ``argv`` (typically
    ``[sys.executable, worker_script, ...]``), each configured as one
    emulated host of the shared mesh. The caller owns the handles —
    ``procs[i].kill()`` is the host-kill chaos primitive, ``wait()`` the
    join. ``stdout``/``stderr`` apply to every child (default: inherit,
    so worker gate output lands in the orchestrator's log)."""
    coordinator = coordinator or pick_coordinator()
    procs: List[subprocess.Popen] = []
    for i in range(n_hosts):
        procs.append(
            subprocess.Popen(
                list(argv),
                env=host_env(n_hosts, i, coordinator, devices_per_host, base_env=env),
                stdout=stdout,
                stderr=stderr,
            )
        )
    return procs


if __name__ == "__main__":  # tiny self-check harness (used by tests)
    ctx = init_multihost()
    import jax
    import numpy as np
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import GRAPH_AXIS, shard_map_compat

    mesh = ctx.mesh()
    sh = NamedSharding(mesh, P(GRAPH_AXIS))
    x = jax.device_put(np.arange(ctx.n_dev * 8, dtype=np.int32), sh)

    @jax.jit
    def f(x):
        @shard_map_compat(mesh=mesh, in_specs=(P(GRAPH_AXIS),), out_specs=P(GRAPH_AXIS))
        def inner(xl):
            return xl + lax.psum(xl.sum(), GRAPH_AXIS)

        return inner(x)

    y = f(x)
    total = int(np.asarray(ctx.n_dev * 8 * (ctx.n_dev * 8 - 1) // 2))
    got = np.asarray(y.addressable_shards[0].data)
    want = np.asarray(x.addressable_shards[0].data) + total
    ok = bool(np.array_equal(got, want))
    print(
        f"multihost-selfcheck host={ctx.process_id}/{ctx.n_hosts} "
        f"dph={ctx.devices_per_host} psum_ok={ok}",
        flush=True,
    )
    ctx.shutdown()
    sys.exit(0 if ok else 1)
