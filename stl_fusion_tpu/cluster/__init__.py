"""Cluster control plane (ISSUE 5): membership, epoch-versioned shard map,
live resharding with cache fencing.

Turns the static consistent-hash router into an elastic, failure-aware
mesh, reusing the existing substrates instead of duplicating them — the
``$sys-m`` frames ride :class:`~stl_fusion_tpu.rpc.outbox.PeerOutbox`,
failure detection feeds from :class:`~stl_fusion_tpu.resilience.breaker.
PeerCircuitBreaker`, fencing drives the ordinary ``set_invalidated``
client path, and every decision journals into the flight recorder /
metrics registry. CLUSTER.md is the runbook.

- :mod:`.shard_map` — pure, wire-serializable ``ShardMap``: V virtual
  shards → members by rendezvous hashing; ``diff()`` names exactly what
  moved between epochs. ``ShardMovedError`` is the protocol's rejection.
- :mod:`.membership` — ``ClusterMember``: heartbeat membership on
  ``$sys-m`` with a deterministic lowest-id coordinator (single-coordinator
  control plane; no consensus claimed — see CLUSTER.md).
- :mod:`.router` — ``ShardMapRouter`` (installable as ``RpcHub.call_router``
  and into ``RoutingComputeProxy``), the server-side
  ``install_cluster_guard`` fence, and the ``install_cluster_client`` glue.
- :mod:`.rebalancer` — ``ClusterRebalancer``: fences moved keys with a
  ``reshard:<epoch>`` cause and retires departed peers (clients, breakers,
  peer workers).
"""
from .membership import ClusterMember
from .rebalancer import ClusterRebalancer
from .router import (
    EPOCH_HEADER,
    FAILOVER_HEADER,
    SHARD_HEADER,
    ShardMapRouter,
    install_cluster_client,
    install_cluster_guard,
)
from .shard_map import DEFAULT_SHARDS, ShardMap, ShardMovedError

__all__ = [
    "ClusterMember",
    "ClusterRebalancer",
    "DEFAULT_SHARDS",
    "EPOCH_HEADER",
    "FAILOVER_HEADER",
    "SHARD_HEADER",
    "ShardMap",
    "ShardMapRouter",
    "ShardMovedError",
    "install_cluster_client",
    "install_cluster_guard",
]
