"""Cluster control plane (ISSUE 5): membership, epoch-versioned shard map,
live resharding with cache fencing.

Turns the static consistent-hash router into an elastic, failure-aware
mesh, reusing the existing substrates instead of duplicating them — the
``$sys-m`` frames ride :class:`~stl_fusion_tpu.rpc.outbox.PeerOutbox`,
failure detection feeds from :class:`~stl_fusion_tpu.resilience.breaker.
PeerCircuitBreaker`, fencing drives the ordinary ``set_invalidated``
client path, and every decision journals into the flight recorder /
metrics registry. CLUSTER.md is the runbook.

- :mod:`.shard_map` — pure, wire-serializable ``ShardMap``: V virtual
  shards → members by rendezvous hashing; ``diff()`` names exactly what
  moved between epochs. ``ShardMovedError`` is the protocol's rejection.
- :mod:`.membership` — ``ClusterMember``: heartbeat membership on
  ``$sys-m`` with a deterministic lowest-id coordinator (single-coordinator
  control plane; no consensus claimed — see CLUSTER.md).
- :mod:`.router` — ``ShardMapRouter`` (installable as ``RpcHub.call_router``
  and into ``RoutingComputeProxy``), the server-side
  ``install_cluster_guard`` fence, and the ``install_cluster_client`` glue.
- :mod:`.rebalancer` — ``ClusterRebalancer``: fences moved keys with a
  ``reshard:<epoch>`` cause and retires departed peers (clients, breakers,
  peer workers).
- :mod:`.rejoin` — ``warm_rejoin`` (ISSUE 6): restart-from-snapshot —
  restore the newest valid durable checkpoint, replay only the oplog tail
  above its watermark, re-announce to membership, and fence exactly the
  keys whose shard assignment changed between the snapshot epoch and the
  current epoch. DURABILITY.md is the runbook.
- :mod:`.placement` — ``DevicePlacement`` (ISSUE 9): the shard map's
  DEVICE half — the same epoch-versioned assignment extended onto the
  accelerator mesh, pinning each member's CSR slice to its devices; the
  layout contract parallel/routed_wave.py builds on.
- :mod:`.mesh_controller` — ``MeshController`` (ISSUE 16): elastic
  multi-host membership — evidence-converged death detection, counted
  in-process degrade (the survivor never restarts), coordinator
  re-election + re-form ladder over the rendezvous board, and live JOIN
  absorption. CLUSTER.md "Elastic mesh" is the runbook.
"""
from .membership import ClusterMember
from .mesh_controller import (
    JaxWorldOps,
    MeshController,
    MeshReformError,
    PeerEvidence,
    RendezvousBoard,
)
from .placement import DevicePlacement, PlacementError
from .rebalancer import ClusterRebalancer
from .rejoin import RejoinReport, fence_moved_keys, verify_restore, warm_rejoin
from .router import (
    EPOCH_HEADER,
    FAILOVER_HEADER,
    SHARD_HEADER,
    ShardMapRouter,
    install_cluster_client,
    install_cluster_guard,
)
from .multihost import MultiHostContext, init_multihost, launch_hosts, pick_coordinator
from .shard_map import DEFAULT_SHARDS, ShardMap, ShardMovedError

__all__ = [
    "ClusterMember",
    "ClusterRebalancer",
    "DEFAULT_SHARDS",
    "DevicePlacement",
    "JaxWorldOps",
    "MeshController",
    "MeshReformError",
    "MultiHostContext",
    "PeerEvidence",
    "PlacementError",
    "RendezvousBoard",
    "init_multihost",
    "launch_hosts",
    "pick_coordinator",
    "EPOCH_HEADER",
    "FAILOVER_HEADER",
    "RejoinReport",
    "SHARD_HEADER",
    "ShardMap",
    "ShardMapRouter",
    "ShardMovedError",
    "fence_moved_keys",
    "install_cluster_client",
    "install_cluster_guard",
    "verify_restore",
    "warm_rejoin",
]
