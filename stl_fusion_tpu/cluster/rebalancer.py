"""ClusterRebalancer — cache fencing on shard-map epoch changes.

The correctness half of live resharding. When a key moves shards, only the
OLD owner knows the key's subscribers — the new owner has never seen them.
Without fencing, every client-cached computed for a moved key stays
"consistent" forever: its ``$sys-c`` subscription points at a server that
will never invalidate it again (the old owner no longer takes the writes),
which is exactly the silent-staleness failure the issue names.

So, on every applied epoch (wired to ``ShardMapRouter.on_map_change``):

- **fence**: every registered outbound compute call whose key's shard is in
  ``ShardMap.diff(old, new)`` is invalidated through the EXISTING client
  invalidation path — ``RpcOutboundComputeCall.set_invalidated`` with a
  ``reshard:<epoch>`` cause id, so the bound ClientComputed re-enters the
  local cascade, dependents re-pull, the next read routes to the NEW owner
  and re-subscribes there, and ``explain()`` names the reshard end to end.
  Calls on unmoved shards keep their live subscriptions untouched.
- **retire departed peers**: a member that left the map has its per-peer
  ``FusionClient`` evicted from every attached ``RoutingComputeProxy``
  (the ISSUE-5 ``_clients`` leak fix — a departed peer used to keep a live
  client + cache routing into a dead socket forever), its pending calls
  failed, its breaker disposed, and the client peer stopped with a
  TERMINATED state so anything parked in ``when_connected()`` raises
  instead of waiting for a reconnect that can never come.

Everything here runs CLIENT-side (routers and routing proxies); servers
need no rebalancer — their data stays valid, the guard just stops them
serving shards they no longer own.
"""
from __future__ import annotations

import asyncio
import logging
from typing import List, Optional

from ..diagnostics.flight_recorder import RECORDER, call_key
from ..diagnostics.metrics import global_metrics
from ..resilience.events import ResilienceEvents, global_events
from .router import ShardMapRouter
from .shard_map import ShardMap

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["ClusterRebalancer"]


class ClusterRebalancer:
    def __init__(
        self,
        rpc_hub,
        router: ShardMapRouter,
        events: Optional[ResilienceEvents] = None,
    ):
        self.rpc_hub = rpc_hub
        self.router = router
        self.events = events if events is not None else global_events()
        #: RoutingComputeProxy instances whose per-peer FusionClients this
        #: rebalancer evicts when their peer departs
        self._proxies: List = []
        #: TpuGraphBackends with mesh routing enabled: an applied epoch
        #: MOVES their device shards (ISSUE 9 — the rebalancer relocates
        #: the graph slice itself, not just the cached calls)
        self._backends: List = []
        self.device_shards_moved = 0
        self.resharded_keys = 0
        self.peers_retired = 0
        self.rebalances = 0
        self._retire_tasks: set = set()
        router.on_map_change.append(self.on_map_change)
        global_metrics().register_collector(self, ClusterRebalancer._collect_metrics)

    def _collect_metrics(self) -> dict:
        return {
            "fusion_resharded_keys_total": self.resharded_keys,
            "fusion_cluster_peers_retired_total": self.peers_retired,
            "fusion_rebalances_total": self.rebalances,
            "fusion_mesh_rebalancer_shards_moved_total": self.device_shards_moved,
        }

    def attach_proxy(self, proxy) -> "ClusterRebalancer":
        """Register a ``RoutingComputeProxy`` for departed-peer eviction."""
        self._proxies.append(proxy)
        return self

    def attach_backend(self, backend) -> "ClusterRebalancer":
        """Register a mesh-routing ``TpuGraphBackend``: every applied epoch
        then moves the reassigned DEVICE SHARDS on the mesh (state blocks
        transfer on-device, exchange routes re-pack) in the same change
        that fences the moved keys' client caches — the cache-fencing +
        shard-moving pair the ISSUE 9 acceptance requires."""
        self._backends.append(backend)
        return self

    def dispose(self) -> None:
        try:
            self.router.on_map_change.remove(self.on_map_change)
        except ValueError:
            pass
        global_metrics().unregister_collector(self)

    # ------------------------------------------------------------------ fence
    def on_map_change(self, old: ShardMap, new: ShardMap) -> None:
        from ..client.compute_call import RpcOutboundComputeCall

        moved = frozenset(ShardMap.diff(old, new))
        cause = f"reshard:{new.epoch}"
        fenced = 0
        if moved:
            # only calls subscribed on CLUSTER members are governed by the
            # shard map — a pinned non-cluster service sharing this hub
            # (e.g. a plain CLIENT-mode FusionClient on "default") keeps its
            # subscriptions across epochs; its keys hashing into a moved
            # shard is coincidence, not ownership
            cluster_refs = set(old.members) | set(new.members)
            for ref, peer in list(self.rpc_hub.peers.items()):
                if ref not in cluster_refs:
                    continue
                for call in list(peer.outbound_calls.values()):
                    if not isinstance(call, RpcOutboundComputeCall):
                        continue
                    shard = self.router.shard_for(call.service, call.method, call.args)
                    if shard not in moved:
                        continue  # owner unchanged: the subscription stays live
                    if RECORDER.enabled:
                        RECORDER.note(
                            "resharded",
                            key=call_key(call.service, call.method, call.args),
                            cause=cause,
                            count=1,
                            detail=(
                                f"shard {shard} owner "
                                f"{old.owner_of_shard(shard)} -> {new.owner_of_shard(shard)}"
                            ),
                        )
                    call.set_invalidated(cause=cause)
                    fenced += 1
        self.resharded_keys += fenced
        self.rebalances += 1
        for backend in self._backends:
            try:
                self.device_shards_moved += backend.apply_mesh_reshard(new)
            except Exception:  # noqa: BLE001 — a mesh move must never block the map
                log.exception("mesh device-shard move failed; mirror will rebuild")
        departed = set(old.members) - set(new.members)
        for ref in departed:
            self._retire_peer(ref)
        if RECORDER.enabled:
            RECORDER.note(
                "resharded",
                key=None,
                cause=cause,
                count=fenced,
                detail=(
                    f"epoch {old.epoch}->{new.epoch}: {len(moved)} shard(s) moved, "
                    f"{fenced} client key(s) fenced, {len(departed)} peer(s) departed"
                ),
            )
        self.events.record(
            "cluster_rebalance", f"epoch {new.epoch}: {fenced} fenced, {sorted(departed)} departed"
        )

    # ------------------------------------------------------------------ retire
    def _retire_peer(self, ref: str) -> None:
        """Drain + dispose everything holding a departed member alive: the
        routing proxies' cached FusionClients (the ISSUE-5 leak), pending
        calls, the breaker, the peer worker itself."""
        for proxy in self._proxies:
            evict = getattr(proxy, "evict_peer", None)
            if evict is not None:
                evict(ref)
        peer = self.rpc_hub.peers.pop(ref, None)
        if peer is None:
            return
        self.peers_retired += 1
        err = ConnectionError(f"peer {ref} left the cluster")
        # TERMINATED first: when_connected() waiters must raise NOW, not
        # park behind a reconnect loop that can never succeed again
        peer._set_state("terminated", err)
        for call in list(peer.outbound_calls.values()):
            # compute calls were fenced above (their shards moved by
            # definition when the owner departed); anything left is a plain
            # call that can only error
            call.set_error(err)

        async def _stop() -> None:
            breaker = getattr(peer, "breaker", None)
            if breaker is not None:
                await breaker.dispose()
            await peer.stop()

        try:
            task = asyncio.get_event_loop().create_task(_stop())
        except RuntimeError:  # no loop (sync teardown): best-effort only
            return
        self._retire_tasks.add(task)
        task.add_done_callback(self._retire_tasks.discard)

    def snapshot(self) -> dict:
        return {
            "resharded_keys": self.resharded_keys,
            "peers_retired": self.peers_retired,
            "rebalances": self.rebalances,
            "device_shards_moved": self.device_shards_moved,
        }
