"""Warm rejoin — restart-from-snapshot for a cluster member (ISSUE 6).

A member restart used to mean a cold boot: empty graph, 45-60 s of mirror
rebuild + program warm-up, and every previously-served key recomputed from
scratch. This module is the warm path:

1. **restore** the newest valid durable snapshot
   (:meth:`~stl_fusion_tpu.checkpoint.CheckpointManager.restore_latest` —
   which already falls back past corrupt/torn files), re-registering every
   warm computed + MemoTable at its original version;
2. **replay** ONLY the oplog tail above the snapshot's watermark through
   the quarantine-aware :class:`~stl_fusion_tpu.oplog.OperationLogReader`
   — the replay runs under ``oplog:replay`` spans, so every invalidation
   it cascades carries a cause ``explain()`` resolves to the rehydration;
3. **re-announce** to membership (a plain :class:`ClusterMember` install —
   the first heartbeat is the join);
4. **fence** exactly the keys whose shard assignment changed between the
   snapshot's epoch and the cluster's current epoch
   (``ShardMap.diff(snapshot_map, current_map)``): a key that is STILL
   assigned elsewhere when this member returns must not serve its warm
   value, so it is invalidated (under a ``restore:fence`` span) rather
   than trusted. Keys whose assignment is unchanged — including keys that
   round-tripped through a survivor while this member was down — keep
   their warm values: every mutation in this system rides the oplog, so
   the step-2 tail replay already invalidated anything written elsewhere
   in the interim. The fence is an ownership guard, not a substitute for
   replay.

Everything is observable: ``fusion_restore_*`` metrics, a flight-recorder
``restored`` event, and :func:`verify_restore` runs one
:class:`~stl_fusion_tpu.diagnostics.auditor.ConsistencyAuditor` sweep over
the restored state (the acceptance gate: zero invariant violations).
"""
from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from ..diagnostics.flight_recorder import RECORDER
from ..diagnostics.metrics import global_metrics
from ..diagnostics.tracing import get_activity_source
from ..oplog.reader import OperationLogReader, attach_operation_log
from .membership import ClusterMember
from .shard_map import DEFAULT_SHARDS, ShardMap

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["RejoinReport", "fence_moved_keys", "verify_restore", "warm_rejoin"]


@dataclass
class RejoinReport:
    """What the rejoin did — mutable because the epoch-diff fence runs
    when the rejoined member LEARNS the current map (one heartbeat later),
    not inside :func:`warm_rejoin` itself; ``fence_applied`` is set then."""

    warm: bool = False
    restored_nodes: int = 0
    restored_tables: int = 0
    restored_edges: int = 0
    subscriptions_lost: int = 0  # live fan-out links at snapshot time (died with the process)
    snapshot_epoch: int = 0
    snapshot_watermark: int = 0
    oplog_last_index: int = 0
    replayed_entries: int = 0  # tail records scanned = watermark advance
    replayed_external: int = 0  # external operations replayed as invalidations
    current_epoch: int = 0  # set when the fence runs
    fenced_keys: int = 0
    restore_s: float = 0.0  # snapshot restore + tail replay, before announce
    fence_applied: "asyncio.Event" = field(default_factory=asyncio.Event)

    def snapshot(self) -> dict:
        return {
            "warm": self.warm,
            "restored_nodes": self.restored_nodes,
            "restored_tables": self.restored_tables,
            "restored_edges": self.restored_edges,
            "subscriptions_lost": self.subscriptions_lost,
            "snapshot_epoch": self.snapshot_epoch,
            "snapshot_watermark": self.snapshot_watermark,
            "oplog_last_index": self.oplog_last_index,
            "replayed_entries": self.replayed_entries,
            "replayed_external": self.replayed_external,
            "current_epoch": self.current_epoch,
            "fenced_keys": self.fenced_keys,
            "restore_s": round(self.restore_s, 4),
        }


def _routing_key(computed, key_arg: int, key_fn) -> Optional[str]:
    """The same key → shard convention ``ShardMapRouter.key_for`` uses,
    derived from a SERVER-side computed's input (None: not shard-governed,
    e.g. an anonymous computed)."""
    inp = getattr(computed, "input", None)
    args = getattr(inp, "args", None)
    if args is None:
        return None
    if key_fn is not None:
        return key_fn(computed)
    if len(args) > key_arg:
        return repr(args[key_arg])
    return None


def fence_moved_keys(
    computeds: Sequence,
    old_map: ShardMap,
    new_map: ShardMap,
    *,
    key_arg: int = 0,
    key_fn: Optional[Callable] = None,
) -> int:
    """Invalidate every restored computed whose key's shard owner changed
    between ``old_map`` (snapshot epoch) and ``new_map`` (current epoch).
    Runs under a ``restore:fence`` span so the cascades carry a cause
    ``explain()`` names. Returns the number fenced."""
    moved = frozenset(ShardMap.diff(old_map, new_map))
    if not moved:
        return 0
    fenced = 0
    with get_activity_source("restore").span(
        "fence", old_epoch=old_map.epoch, new_epoch=new_map.epoch, moved=len(moved)
    ):
        for c in computeds:
            key = _routing_key(c, key_arg, key_fn)
            if key is None:
                continue
            if new_map.shard_of(key) in moved and c.invalidate(immediately=True):
                fenced += 1
    return fenced


async def verify_restore(hub, backend=None, sample: float = 1.0) -> dict:
    """One full :class:`ConsistencyAuditor` sweep over the restored state
    (structural invariants + mirror cross-check + canary probe). Returns
    the audit report; the acceptance gate is ``violations == []``."""
    from ..diagnostics.auditor import ConsistencyAuditor

    auditor = ConsistencyAuditor(hub, backend=backend, sample=sample)
    try:
        return await auditor.audit_once()
    finally:
        auditor.dispose()


async def warm_rejoin(
    hub,
    rpc_hub,
    manager,
    log_store,
    *,
    member_id: str,
    seeds: Sequence[str],
    notifier=None,
    n_shards: int = DEFAULT_SHARDS,
    heartbeat_interval: float = 0.5,
    failure_timeout: float = 2.0,
    services=None,
    key_arg: int = 0,
    key_fn: Optional[Callable] = None,
    mesh=None,
    announce: bool = True,
    start_reader: bool = True,
) -> Tuple[Optional[ClusterMember], OperationLogReader, RejoinReport]:
    """Bring a restarted member back WARM: restore → replay tail →
    re-announce → epoch-diff fence. Returns ``(member, reader, report)``;
    ``member`` is None when ``announce=False`` (standalone warm boot).

    With no restorable snapshot this degrades to the cold path (reader
    tails from the end, nothing fenced) and ``report.warm`` is False —
    callers never need a separate cold branch.
    """
    t0 = time.perf_counter()
    metrics = global_metrics()
    result = manager.restore_latest(hub, services)
    report = RejoinReport(warm=result is not None)
    snapshot_map: Optional[ShardMap] = None
    if result is not None:
        report.restored_nodes = result.count
        report.restored_tables = result.tables
        report.restored_edges = result.edges
        report.subscriptions_lost = result.subscriptions
        report.snapshot_epoch = result.epoch
        report.snapshot_watermark = result.oplog_position
        if result.snapshot_map:
            try:
                snapshot_map = ShardMap.from_wire(result.snapshot_map)
            except (KeyError, ValueError, TypeError):
                snapshot_map = None
    # the reader resumes from the snapshot watermark (or tails from the
    # end on a cold boot — nothing warm exists that replay could fix)
    reader = attach_operation_log(
        hub.commander,
        log_store,
        notifier,
        start_reader=False,
        start_position=report.snapshot_watermark if result is not None else None,
        mesh=mesh,
    )
    if result is not None:
        # drain the tail SYNCHRONOUSLY before serving/announcing: the
        # member must not answer a read between "warm but stale" and
        # "replayed" — that window is exactly the stale-read bug class
        # this subsystem exists to remove
        report.replayed_external = await reader.read_new()
        report.replayed_entries = reader.watermark - report.snapshot_watermark
    report.oplog_last_index = log_store.last_index()
    report.restore_s = time.perf_counter() - t0
    if start_reader:
        reader.start()

    member: Optional[ClusterMember] = None
    if announce:
        member = ClusterMember(
            rpc_hub,
            member_id,
            seeds=seeds,
            n_shards=n_shards,
            heartbeat_interval=heartbeat_interval,
            failure_timeout=failure_timeout,
        ).install()

    # ------------------------------------------------------------ fence
    restored_refs: List = list(result.computeds) if result is not None else []

    def _fence(current: ShardMap) -> None:
        report.current_epoch = current.epoch
        if snapshot_map is not None and restored_refs:
            report.fenced_keys = fence_moved_keys(
                restored_refs, snapshot_map, current, key_arg=key_arg, key_fn=key_fn
            )
            if report.fenced_keys:
                metrics.counter(
                    "fusion_restore_fenced_keys_total",
                    help="restored keys invalidated by the rejoin epoch-diff fence",
                ).inc(report.fenced_keys)
        restored_refs.clear()  # drop the strong refs; live anchors own them now
        report.fence_applied.set()

    if member is not None and snapshot_map is not None:

        def _on_map(old: ShardMap, new: ShardMap) -> None:
            # fence against the JOIN epoch — the first at/above-snapshot map
            # that CONTAINS this member. Earlier maps (minted while we were
            # down) show every one of our shards as "moved away", and
            # fencing against one would invalidate the entire warm state the
            # restore just rebuilt; until we are in the map the guard
            # rejects routed traffic anyway, so waiting is safe. The
            # absent->present transition is the join itself regardless of
            # epoch: after a FULL-cluster restart the surviving members
            # re-mint epochs from 1, so a snapshot taken at epoch N may
            # never see new.epoch >= N again — without this clause the
            # fence would never fire and fence_applied awaiters would hang.
            # old.epoch == 0 is the member's own pre-join seed view (which
            # always lists itself, membership.py bootstrap): the first REAL
            # map applied over it that contains us is our join map too
            joined_now = member_id in new.members and (
                member_id not in old.members or old.epoch == 0
            )
            if (
                (new.epoch >= report.snapshot_epoch or joined_now)
                and member_id in new.members
                and not report.fence_applied.is_set()
            ):
                try:
                    member.on_map_change.remove(_on_map)
                except ValueError:
                    pass
                _fence(new)

        member.on_map_change.append(_on_map)
        if member.shard_map.epoch >= report.snapshot_epoch:
            _on_map(member.shard_map, member.shard_map)
    else:
        # no membership (standalone) or no epoch info in the snapshot:
        # there is nothing to diff against — the fence is a no-op, but the
        # event still fires so callers can await it unconditionally
        _fence(member.shard_map if member is not None else snapshot_map or ShardMap.initial([member_id], n_shards=n_shards))

    # ------------------------------------------------------------ telemetry
    metrics.counter(
        "fusion_restores_total", help="warm/cold rejoin restores attempted"
    ).inc()
    metrics.gauge(
        "fusion_restore_replayed_entries",
        help="oplog tail records replayed by the last restore (last_index - snapshot watermark)",
    ).set(report.replayed_entries)
    metrics.gauge(
        "fusion_restore_nodes", help="computeds restored warm by the last restore"
    ).set(report.restored_nodes)
    metrics.gauge(
        "fusion_restore_tables", help="MemoTables restored warm by the last restore"
    ).set(report.restored_tables)
    metrics.gauge(
        "fusion_restore_s", help="snapshot restore + tail replay wall time (s)"
    ).set(report.restore_s)
    if RECORDER.enabled:
        RECORDER.note(
            "restored",
            key=None,
            count=report.restored_nodes,
            oplog=reader.watermark,
            detail=(
                f"{'warm' if report.warm else 'cold'} rejoin of {member_id}: "
                f"{report.restored_nodes} node(s), {report.restored_tables} "
                f"table(s), replayed {report.replayed_entries} oplog entr(ies) "
                f"above watermark {report.snapshot_watermark} in "
                f"{report.restore_s:.3f}s"
            ),
        )
    log.info(
        "cluster %s: %s rejoin restored %d nodes / %d tables, replayed %d "
        "oplog entries in %.3fs",
        member_id,
        "warm" if report.warm else "cold",
        report.restored_nodes,
        report.restored_tables,
        report.replayed_entries,
        report.restore_s,
    )
    return member, reader, report
