"""ClusterMember — heartbeat membership on the ``$sys-m`` system service.

A deliberately small control plane, same pattern as ``$sys-c``/``$sys-d``:
one dispatch hook on the hub, tiny frames, no new transport. The design is
a SINGLE-COORDINATOR membership — the lowest member id coordinates, which
is deterministic and needs no consensus round; CLUSTER.md documents exactly
what that does NOT guarantee (a partitioned coordinator pair can mint
divergent epochs; epochs + the owner guard bound the damage to rejected
calls, never to silently-split writes... for reads — commands fail fast).

Protocol (all frames ride ``$sys-m``, fire-and-forget through the peer's
existing :class:`~stl_fusion_tpu.rpc.outbox.PeerOutbox`):

- ``heartbeat [member_id, epoch]`` — member → coordinator, every
  ``heartbeat_interval``. The coordinator ALWAYS answers with ``map`` on
  the same link: the reply is simultaneously the member's liveness signal
  for the coordinator and its epoch sync (a stale member catches up one
  heartbeat after any change). An unknown sender is a JOIN → new epoch.
- ``suspect [member_id, reason]`` — anyone → coordinator: failure evidence
  (the breaker-open fast path). The coordinator removes the member → new
  epoch.
- ``leave [member_id]`` — graceful departure → new epoch.
- ``map [shard_map]`` — the epoch broadcast. Applied iff newer; every
  member that APPLIES a map forwards it to all its connected peers, so
  downstream clients learn within one hop of whichever member they dial.
- ``sync [epoch]`` — anyone → member: reply ``map`` if ours is newer
  (client bootstrap).

Failure detection feeds from BOTH sources the issue names: missed
heartbeats (coordinator-side ``failure_timeout``) and open
:class:`~stl_fusion_tpu.resilience.PeerCircuitBreaker`s — the coordinator
checks each member peer's breaker every tick, and non-coordinators send
``suspect`` when THEIR breaker to a member opens. Breaker evidence only
exists where a breaker is INSTALLED: on an OUTBOUND ``client_peer(m)``
link to the member with ``PeerCircuitBreaker(peer).install()`` (the
routed-mesh deployment, where members dial each other to forward calls).
A hub that only hears a member's inbound heartbeats has no breaker to
consult, and the fast path silently contributes nothing there — the
heartbeat timeout is the universal backstop either way. Coordinator death
is covered by takeover: when the coordinator has been silent past
``failure_timeout``, the lowest surviving member mints the next epoch
without it.
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..diagnostics.flight_recorder import RECORDER
from ..diagnostics.metrics import global_metrics
from ..resilience.events import ResilienceEvents, global_events
from ..rpc.message import MEMBER_SYSTEM_SERVICE, RpcMessage
from ..utils.async_chain import WorkerBase
from ..utils.serialization import dumps, loads
from .shard_map import DEFAULT_SHARDS, ShardMap

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["ClusterMember"]


class ClusterMember(WorkerBase):
    def __init__(
        self,
        rpc_hub,
        member_id: str,
        seeds: Sequence[str],
        n_shards: int = DEFAULT_SHARDS,
        heartbeat_interval: float = 0.5,
        failure_timeout: float = 2.0,
        events: Optional[ResilienceEvents] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        super().__init__(f"cluster:{member_id}")
        self.rpc_hub = rpc_hub
        #: this member's id IS the peer ref others dial it by
        self.member_id = member_id
        self.heartbeat_interval = heartbeat_interval
        self.failure_timeout = failure_timeout
        self.events = events if events is not None else global_events()
        self._clock = clock
        #: epoch 0 = bootstrap view (seeds); the coordinator mints epoch 1
        #: on its first tick, so any coordinator map overrides any seed view
        self.shard_map = ShardMap.initial(list(seeds) + [member_id], n_shards=n_shards)
        now = clock()
        self._last_heard: Dict[str, float] = {m: now for m in self.shard_map.members}
        self._coord_heard = now
        #: callbacks ``(old_map, new_map)`` on every applied/minted epoch
        self.on_map_change: List[Callable[[ShardMap, ShardMap], None]] = []
        # -- counters (collector-exported; report()["cluster"]) -----------
        self.epochs_minted = 0
        self.joins_seen = 0
        self.failures_seen = 0
        self.takeovers = 0
        self.heartbeats_sent = 0
        self.heartbeats_seen = 0
        self.stale_rejections = 0  # bumped by the guard (cluster/router.py)
        self._suspected: set = set()  # dedup suspicion sends per incident
        #: member -> when we FIRST courted it as takeover successor; a
        #: candidate that never answers for a full failure window is
        #: treated as dead too (double-failure takeover, _member_tick)
        self._court_started: Dict[str, float] = {}
        #: when the would-be bootstrap coordinator FIRST probed its seeds
        #: for an existing cluster (ISSUE 6): a RESTARTING lowest-id member
        #: must rejoin the live epoch, not hijack it with a parallel one
        self._bootstrap_sync_started: Optional[float] = None
        global_metrics().register_collector(self, ClusterMember._collect_metrics)
        global_metrics().set_aggregation("fusion_shard_map_epoch", "max")
        # member count is a non-additive gauge: N co-hosted members must
        # scrape as N members, not N² (set_aggregation docstring rule)
        global_metrics().set_aggregation("fusion_cluster_members", "max")

    # ------------------------------------------------------------------ wiring
    def install(self) -> "ClusterMember":
        """Attach the ``$sys-m`` dispatch hook and start the tick loop."""
        self.rpc_hub.member_system_handler = self._handle
        self.start()
        return self

    async def dispose(self) -> None:
        if self.rpc_hub.member_system_handler is self._handle:
            self.rpc_hub.member_system_handler = None
        global_metrics().unregister_collector(self)
        await self.stop()

    def _collect_metrics(self) -> dict:
        return {
            "fusion_shard_map_epoch": self.shard_map.epoch,
            "fusion_cluster_members": len(self.shard_map.members),
            "fusion_cluster_is_coordinator": 1 if self.is_coordinator else 0,
            "fusion_cluster_epochs_minted_total": self.epochs_minted,
            "fusion_cluster_joins_total": self.joins_seen,
            "fusion_cluster_failures_total": self.failures_seen,
            "fusion_cluster_stale_rejections_total": self.stale_rejections,
        }

    # ------------------------------------------------------------------ state
    @property
    def coordinator(self) -> Optional[str]:
        return self.shard_map.coordinator

    @property
    def is_coordinator(self) -> bool:
        return self.coordinator == self.member_id

    def snapshot(self) -> dict:
        return {
            "member_id": self.member_id,
            "epoch": self.shard_map.epoch,
            "members": list(self.shard_map.members),
            "coordinator": self.coordinator,
            "is_coordinator": self.is_coordinator,
            "n_shards": self.shard_map.n_shards,
            "epochs_minted": self.epochs_minted,
            "joins_seen": self.joins_seen,
            "failures_seen": self.failures_seen,
            "takeovers": self.takeovers,
            "stale_rejections": self.stale_rejections,
        }

    # ------------------------------------------------------------------ frames
    @staticmethod
    def _frame(method: str, args: list) -> RpcMessage:
        return RpcMessage(0, 0, MEMBER_SYSTEM_SERVICE, method, dumps(args))

    async def _try_send(self, peer, method: str, args: list) -> bool:
        """Fire-and-forget control frame: membership is periodic, so a miss
        (link down, mid-dial) is covered by the next tick — never by
        parking the tick loop on ``when_connected``."""
        try:
            await peer.send(self._frame(method, args))
            return True
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — the next tick retries
            return False

    def _handle(self, peer, message: RpcMessage):
        """``$sys-m`` dispatch (may return a coroutine — the peer pump
        spawns it so replies never block the receive loop)."""
        method = message.method
        args = loads(message.argument_data)
        ref = getattr(peer, "ref", None)
        if ref is not None:
            # ANY $sys-m frame proves the sender lives: a courted takeover
            # candidate that answers stops its court-silence clock
            self._court_started.pop(ref, None)
        if method == "heartbeat":
            member_id, epoch = args[0], int(args[1])
            return self._on_heartbeat(peer, member_id, epoch)
        if method == "map":
            wire = args[0]
            smap = wire if isinstance(wire, ShardMap) else ShardMap.from_wire(wire)
            if peer.ref == self.coordinator or smap.coordinator == self.coordinator:
                self._coord_heard = self._clock()
            return self._apply_map(smap)
        if method == "suspect":
            member_id = args[0]
            reason = args[1] if len(args) > 1 else "suspected"
            if self.is_coordinator:
                return self._remove_members({member_id}, f"suspected: {reason}")
            return None
        if method == "leave":
            if self.is_coordinator:
                return self._remove_members({args[0]}, "graceful leave")
            return None
        if method == "sync":
            their_epoch = int(args[0])
            if self.shard_map.epoch > their_epoch:
                return self._try_send(peer, "map", [self.shard_map.to_wire()])
            return None
        return None

    async def _on_heartbeat(self, peer, member_id: str, epoch: int) -> None:
        self.heartbeats_seen += 1
        self._last_heard[member_id] = self._clock()
        self._suspected.discard(member_id)
        # epoch 0 = unresolved bootstrap: a RESTARTED lowest-id member also
        # believes it coordinates here, and minting a join epoch off the
        # seed view would spawn a parallel epoch-1 lineage next to the live
        # cluster — the same split-brain the coordinator-tick sync probe
        # guards against. Joins wait until the probe resolves (adopting the
        # live map, or minting the genuine bootstrap epoch).
        if (
            self.is_coordinator
            and self.shard_map.epoch > 0
            and member_id not in self.shard_map.members
        ):
            self.joins_seen += 1
            self.events.record("cluster_join", member_id)
            self._mint(
                list(self.shard_map.members) + [member_id], f"join: {member_id}"
            )
        # the reply is liveness + sync in one tiny frame; non-coordinators
        # answer too (a joiner seeded with only THIS member still learns
        # the real map, and through it the real coordinator)
        await self._try_send(peer, "map", [self.shard_map.to_wire()])

    # ------------------------------------------------------------------ epochs
    def _mint(self, members: Sequence[str], why: str) -> None:
        """Coordinator-side: mint the next epoch and broadcast it."""
        old = self.shard_map
        new = old.with_members(members)
        self.epochs_minted += 1
        log.debug("cluster %s: epoch %d -> %d (%s)", self.member_id, old.epoch, new.epoch, why)
        self._adopt(old, new, why)

    def _apply_map(self, new: ShardMap) -> None:
        old = self.shard_map
        if new.epoch <= old.epoch:
            return
        self._adopt(old, new, "applied from broadcast")

    def _adopt(self, old: ShardMap, new: ShardMap, why: str) -> None:
        self.shard_map = new
        if new.coordinator != old.coordinator:
            # the takeover clock restarts for a NEW coordinator: a bystander
            # adopting a takeover map mid-timeout would otherwise keep the
            # DEAD coordinator's last-heard stamp, decide the LIVE successor
            # is silent too, and mint an epoch ejecting it
            self._coord_heard = self._clock()
            self._court_started.clear()  # succession settled; fresh slate
        for m in new.members:
            self._last_heard.setdefault(m, self._clock())
        if RECORDER.enabled:
            moved = ShardMap.diff(old, new)
            RECORDER.note(
                "resharded",
                key=None,
                cause=f"reshard:{new.epoch}",
                count=len(moved),
                detail=(
                    f"epoch {old.epoch}->{new.epoch} on {self.member_id}: "
                    f"{len(moved)} shard(s) moved ({why})"
                ),
            )
        for cb in list(self.on_map_change):
            try:
                cb(old, new)
            except Exception:  # noqa: BLE001
                log.exception("cluster %s: map-change callback failed", self.member_id)
        # forward to every connected peer (members we dialed, members and
        # clients that dialed us) — one hop of gossip makes the broadcast
        # reach clients of every member, not just the coordinator's
        self._broadcast(new)

    def _broadcast(self, smap: ShardMap) -> None:
        wire = smap.to_wire()
        for peer in list(self.rpc_hub.peers.values()):
            if peer.is_connected:
                task = asyncio.get_event_loop().create_task(
                    self._try_send(peer, "map", [wire])
                )
                # tracked like $sys-d replies: silent, bounded, cancellable
                peer._diag_tasks.add(task)
                task.add_done_callback(peer._diag_tasks.discard)

    def _remove_members(self, gone: set, why: str) -> None:
        gone = {m for m in gone if m in self.shard_map.members and m != self.member_id}
        if not gone:
            return
        self.failures_seen += len(gone)
        self.events.record("cluster_member_removed", f"{sorted(gone)}: {why}")
        self._mint([m for m in self.shard_map.members if m not in gone], why)

    # ------------------------------------------------------------------ tick
    async def on_run(self) -> None:
        while True:
            await asyncio.sleep(self.heartbeat_interval)
            try:
                if self.is_coordinator:
                    await self._coordinator_tick()
                else:
                    await self._member_tick()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the tick loop must survive
                log.exception("cluster %s: tick failed", self.member_id)

    async def _coordinator_tick(self) -> None:
        now = self._clock()
        self._last_heard[self.member_id] = now
        if self.shard_map.epoch == 0:
            # Before promoting the seed view to epoch 1, probe the seeds
            # for a cluster that already exists: a RESTARTED lowest-id
            # member also lands here believing it coordinates, and minting
            # immediately would split-brain a live cluster that moved on
            # without it. Any seed holding a real epoch answers the sync
            # with its map; we adopt it and REJOIN through the normal
            # heartbeat path (the once-again-lowest id gets the
            # coordinator role handed back with the join epoch). The probe
            # window is a few heartbeats — enough for several sync retries
            # against a lossy link, NOT scaled to failure_timeout (a long
            # failure window must not stall a genuine fresh bootstrap).
            others = [m for m in self.shard_map.members if m != self.member_id]
            window = min(self.failure_timeout, 3 * self.heartbeat_interval + 0.25)
            if others:
                if self._bootstrap_sync_started is None:
                    self._bootstrap_sync_started = now
                if now < self._bootstrap_sync_started + window:
                    for m in others:
                        await self._try_send(
                            self.rpc_hub.client_peer(m), "sync", [0]
                        )
                    return
            self._mint(self.shard_map.members, "bootstrap")
            return
        dead = set()
        for m in self.shard_map.members:
            if m == self.member_id:
                continue
            if self._last_heard.get(m, now) + self.failure_timeout < now:
                dead.add(m)
                self.events.record("cluster_heartbeat_timeout", m)
                continue
            peer = self.rpc_hub.peers.get(m)
            breaker = getattr(peer, "breaker", None) if peer is not None else None
            if breaker is not None and breaker.state == "open":
                # the breaker's evidence is fresher than the heartbeat
                # timeout — fail the member over NOW
                dead.add(m)
                self.events.record("cluster_breaker_evidence", m)
        if dead:
            self._remove_members(dead, "failure detection")

    async def _member_tick(self) -> None:
        coord = self.coordinator
        now = self._clock()
        if coord is not None and coord != self.member_id:
            peer = self.rpc_hub.client_peer(coord)
            if await self._try_send(
                peer, "heartbeat", [self.member_id, self.shard_map.epoch]
            ):
                self.heartbeats_sent += 1
            # coordinator takeover: silent past the failure timeout, and we
            # are the lowest VIABLE survivor → mint the next epoch without
            # it (deterministic; a live-but-partitioned coordinator will
            # keep minting too — the documented no-consensus caveat). A
            # survivor we courted for a full failure window without ONE
            # answering frame counts as dead too: when the coordinator and
            # the lowest survivor die together (one rack), succession must
            # cascade to the next member, not leave the cluster headless.
            if self._coord_heard + self.failure_timeout < now:
                viable = [
                    m
                    for m in self.shard_map.members
                    if m != coord
                    and (
                        m == self.member_id
                        or self._court_started.get(m, now) + self.failure_timeout >= now
                    )
                ]
                if viable and min(viable) == self.member_id:
                    dropped = set(self.shard_map.members) - set(viable)
                    self.takeovers += 1
                    self.failures_seen += len(dropped)
                    self.events.record(
                        "cluster_takeover",
                        f"{self.member_id} replaces {coord} "
                        f"(silent: {sorted(dropped)})",
                    )
                    self._coord_heard = now
                    self._mint(viable, f"takeover from silent {coord}")
                elif viable:
                    # not the successor: court the would-be coordinator so
                    # we learn its takeover epoch (we only ever dial the
                    # coordinator, and ours is dead — without this hop a
                    # bystander member never hears the new map), and start
                    # its court-silence clock
                    candidate = min(viable)
                    self._court_started.setdefault(candidate, now)
                    if await self._try_send(
                        self.rpc_hub.client_peer(candidate),
                        "heartbeat",
                        [self.member_id, self.shard_map.epoch],
                    ):
                        self.heartbeats_sent += 1
        # suspicion fast path: OUR breaker to a fellow member opened —
        # tell the coordinator instead of waiting out its heartbeat window
        for m in self.shard_map.members:
            if m == self.member_id or m == coord:
                continue
            peer = self.rpc_hub.peers.get(m)
            breaker = getattr(peer, "breaker", None) if peer is not None else None
            if breaker is None or breaker.state != "open":
                # incident over (breaker closed / peer rebuilt): re-arm so
                # the member's NEXT failure takes the fast path again —
                # suspicion dedup is per incident, not per member forever
                self._suspected.discard(m)
                continue
            if m in self._suspected or coord is None:
                continue
            self._suspected.add(m)
            await self._try_send(
                self.rpc_hub.client_peer(coord), "suspect", [m, "breaker open"]
            )

    async def leave(self) -> None:
        """Graceful departure: tell the coordinator, then dispose."""
        coord = self.coordinator
        if coord is not None and coord != self.member_id:
            await self._try_send(self.rpc_hub.client_peer(coord), "leave", [self.member_id])
        await self.dispose()
